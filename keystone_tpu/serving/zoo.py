"""Multi-tenant model zoo: many fingerprinted plans on one device budget
(ROADMAP item 3 — "a model-zoo tier that pages exported plan weights
between host RAM and device HBM ... with per-tenant SLOs and fair
admission so one hot tenant can't starve the rest").

KeystoneML's pipeline-as-value design makes exported plans cheap to
HOLD — a frozen graph plus weight arrays — but every serving plane so
far serves exactly ONE of them: "production-scale serving for millions
of users" stops at a single model. This module is the robustness layer
that lets many tenants share one device, with ISOLATION as the headline
contract:

  - **Weight paging under a hard budget.** A RESIDENT tenant holds a
    live, AOT-warmed :class:`~keystone_tpu.serving.export.ExportedPlan`
    (weights device-pinned) behind its own batcher. A PAGED-OUT tenant
    holds its weights host-side in the compressed int16+bf16 split-plane
    encoding (:class:`PagedWeights` — the PR-8 resident tier's
    two-16-bit-lane layout, reused bit-EXACTLY: an f32 tensor splits
    into its bf16 high half + an int16 low-mantissa plane, and tensors
    whose low plane is all zero — bf16-representable weights — store
    only 2 B/elem). Every tensor carries a CRC in the
    ``data/durable.py`` checksum discipline: a bit-flipped paged tensor
    raises :class:`~keystone_tpu.data.durable.ShardCorrupted` at
    page-in and QUARANTINES the plan — never a silently-wrong response.
    Page-in/page-out run as tasks on a
    :class:`~keystone_tpu.data.runtime.DataPlaneRuntime` lane (host-only
    work — the jax-off-thread discipline; the JAX rebuild runs on the
    faulting caller) through the ``serving.zoo.page_in`` /
    ``serving.zoo.page_out`` fault sites with a bounded-backoff
    :class:`~keystone_tpu.utils.faults.RetryPolicy`.
  - **Bit-identity per fingerprint.** A plan's
    :func:`~keystone_tpu.serving.export.plan_fingerprint` is recorded at
    registration; after every page-in the rebuilt plan's fingerprint
    must MATCH it (the fingerprint covers weight content CRCs), so a
    paging round trip is provably bit-identical — the hot-swap contract
    of docs/reliability.md extended to residency transitions.
  - **LRU eviction priced by cost.** When the budget binds, the victim
    is chosen by score = recency / (page-in cost × SLO pressure):
    least-recently-used wins, discounted by how expensive the tenant is
    to bring back (measured page-in seconds, seeded from the cost
    model's byte pricing) and by its live SLO state (a WARN/BREACH
    tenant is held resident). Every choice is a structured
    ``zoo.decision`` audit event mirroring ``cost.decision`` /
    ``autoscale.decision``: candidates with their scores, winner,
    reason.
  - **Per-tenant SLOs + deficit-weighted fair admission.** Each tenant
    carries its own :class:`~keystone_tpu.obs.slo.SLOTracker` and the
    front door runs weighted fair queuing over tenants: every tenant
    has a per-tenant queue-depth cap, and once the GLOBAL outstanding
    pool is full, only tenants still under their deficit-weighted
    guaranteed share (``weight_i / Σweights × max_outstanding_total``)
    admit — a hot tenant's overflow is rejected AT ITS OWN DOOR with a
    named error that burns ITS budget, while every other tenant's
    guaranteed share stays admittable. The isolation contract
    (docs/reliability.md): *no tenant's admission latency or SLO state
    may degrade past WARN because of another tenant's offered load,
    and ``offered == completed + rejected + failed`` holds per tenant
    at all times.*
  - **Graceful degradation.** A page fault on a cold tenant is
    bounded-latency: when the request carries a deadline the page-in
    estimate (measured EMA, seeded by ``cold_start_estimate_s``) is
    checked FIRST and an unmeetable deadline fast-fails with the named
    :class:`TenantColdStart` instead of wedging behind a multi-second
    rebuild. Repeated page-in failures (retry exhaustion) or any CRC
    mismatch QUARANTINE the plan loudly — flight-record dump,
    ``zoo.quarantined`` metric, every later submit fast-failing with
    :class:`TenantQuarantined` — while every other tenant keeps
    serving.

Per-tenant servers are :class:`~keystone_tpu.serving.batcher
.MicroBatchServer`\\ s by default; ``replicas_per_tenant > 1`` fronts
each tenant with a full
:class:`~keystone_tpu.serving.replicas.ReplicatedServer` plane (same
submit/stats/close contract), so one replicated plane design serves
MANY fingerprinted plans.

Chaos-provable (tests/test_chaos_zoo.py): a hot-tenant spike leaves
every other tenant's SLO verdict OK with zero silent drops; a page-in
fault is absorbed by the retry budget; a kill mid-page-out leaves the
previous RESIDENT copy authoritative (the encode completes or nothing
changes — the paged copy is swapped in atomically after verification).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu import obs
from keystone_tpu.data import durable
from keystone_tpu.placement.engine import (
    KIND_ZOO_EVICT,
    KIND_ZOO_PAGE_IN,
    PlacementEngine,
    active_family,
)
from keystone_tpu.obs.metrics import (
    METRIC_TENANT_COLDSTART_FAILFAST,
    METRIC_TENANT_COMPLETED,
    METRIC_TENANT_FAILED,
    METRIC_TENANT_OFFERED,
    METRIC_TENANT_REJECTED,
    METRIC_ZOO_DECISIONS,
    METRIC_ZOO_PAGE_INS,
    METRIC_ZOO_PAGE_OUTS,
    METRIC_ZOO_QUARANTINED,
    METRIC_ZOO_RESIDENTS,
)
from keystone_tpu.utils import faults

from .batcher import (
    MicroBatchServer,
    ServerClosed,
    ServerDegraded,
    ServerOverloaded,
)
from .export import ExportedPlan

__all__ = [
    "ModelZoo",
    "PagedWeights",
    "TenantColdStart",
    "TenantQuarantined",
    "ZooDecision",
]

logger = logging.getLogger("keystone_tpu.serving")

# SLO-pressure multipliers for eviction scoring: a tenant already
# burning budget is held resident (its page-in cost is effectively
# multiplied), so the budget squeeze lands on healthy-idle tenants.
_SLO_PRESSURE = {"OK": 1.0, "WARN": 4.0, "BREACH": 16.0}


class TenantColdStart(ServerOverloaded):
    """A page fault on a cold (paged-out) tenant could not meet the
    request's deadline: the estimated page-in time exceeds the deadline
    budget, so the request fast-fails HERE — named, counted, SLO-fed —
    instead of wedging the batcher behind a multi-second weight rebuild.
    An :class:`~keystone_tpu.serving.batcher.ServerOverloaded` subclass:
    capacity (residency) was the limiting resource."""


class TenantQuarantined(ServerDegraded):
    """The tenant's plan is quarantined — a paged tensor failed its CRC
    (bit flip: serving it would be silently wrong) or page-in failed
    past the retry budget. Every submit fast-fails with this error until
    the operator re-registers the tenant; every OTHER tenant keeps
    serving. A :class:`~keystone_tpu.serving.batcher.ServerDegraded`
    subclass: the plan, not the load, is the problem."""


# ---------------------------------------------------------------------------
# Paged weight encoding: bit-exact int16+bf16 split planes + CRCs
# ---------------------------------------------------------------------------


class _PagedTensor:
    """One weight tensor paged host-side. f32 tensors store the PR-8
    two-16-bit-lane layout: ``hi`` is the bf16 high half (truncated f32
    top 16 bits — exactly the bfloat16 bit pattern) and ``lo`` the int16
    low-mantissa residue; ``f32 == (hi << 16) | lo`` bit-for-bit, so the
    round trip is EXACT, and a tensor whose low plane is all zero (a
    bf16-representable weight) drops it — 2 B/elem, the compressed win.
    Non-f32 dtypes ride as raw bytes. ``crc`` digests the ORIGINAL
    array's bytes (durable.py discipline, algorithm recorded)."""

    __slots__ = ("shape", "dtype", "hi", "lo", "raw", "crc", "algo")

    def __init__(self, shape, dtype, hi, lo, raw, crc, algo):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.hi = hi
        self.lo = lo
        self.raw = raw
        self.crc = crc
        self.algo = algo

    @property
    def nbytes(self) -> int:
        total = 0
        for plane in (self.hi, self.lo, self.raw):
            if plane is not None:
                total += plane.nbytes
        return total


def _encode_tensor(arr: np.ndarray) -> _PagedTensor:
    arr = np.ascontiguousarray(arr)
    crc = durable.crc_of_array(arr)
    algo = durable.checksum_algo()
    if arr.dtype == np.float32:
        u = arr.view(np.uint32)
        hi = (u >> np.uint32(16)).astype(np.uint16)
        lo = (u & np.uint32(0xFFFF)).astype(np.uint16)
        if not lo.any():
            lo = None  # bf16-representable: the compressed 2 B/elem form
        return _PagedTensor(arr.shape, arr.dtype, hi, lo, None, crc, algo)
    return _PagedTensor(
        arr.shape, arr.dtype, None, None,
        arr.view(np.uint8).reshape(-1).copy(), crc, algo,
    )


def _decode_tensor(pt: _PagedTensor, site: str) -> np.ndarray:
    """Decode one paged tensor, running each stored plane through the
    fault harness's corruption hook (so chaos plans can flip a byte at
    ``site``) and verifying the recorded CRC over the DECODED bytes —
    a mismatch raises through :func:`durable.corrupted` (flight dump
    beside it), which the retry layer never retries."""
    if pt.raw is not None:
        raw = faults.corrupt_array(site, pt.raw)
        out = raw.view(pt.dtype).reshape(pt.shape).copy()
    else:
        hi = faults.corrupt_array(site, pt.hi)
        u = hi.astype(np.uint32) << np.uint32(16)
        if pt.lo is not None:
            lo = faults.corrupt_array(site, pt.lo)
            u = u | lo.astype(np.uint32)
        out = u.view(np.float32).reshape(pt.shape)
    got = durable.crc_of_array(out, pt.algo)
    if got != pt.crc:
        raise durable.corrupted(
            f"paged weight tensor failed checksum at {site}: "
            f"crc {got:#x} != recorded {pt.crc:#x} ({pt.algo}, shape "
            f"{pt.shape}, dtype {pt.dtype}) — serving it would be "
            f"silently wrong; the plan must be quarantined"
        )
    return out


class PagedWeights:
    """The host-side paged form of one plan's device weights: the
    tensors in slot order (the deterministic jax-array-attribute walk of
    the plan graph), each CRC-guarded. ``decoded_bytes`` is the resident
    footprint the tensors decode back to — what the budget arithmetic
    charges a page-in with."""

    __slots__ = ("tensors", "decoded_bytes")

    def __init__(self, tensors: List[_PagedTensor], decoded_bytes: int):
        self.tensors = tensors
        self.decoded_bytes = int(decoded_bytes)

    @property
    def nbytes(self) -> int:
        return sum(t.nbytes for t in self.tensors)


def _page_out_task(host_arrays: List[np.ndarray]) -> PagedWeights:
    """The page-out lane task (host/numpy only — jax-off-thread): fire
    the fault site, then encode every tensor. Runs to completion or
    raises with NOTHING published — the caller swaps the result in
    atomically, so a kill mid-encode leaves the previous resident copy
    authoritative."""
    faults.maybe_fail(faults.SITE_ZOO_PAGE_OUT)
    tensors = [_encode_tensor(a) for a in host_arrays]
    return PagedWeights(tensors, sum(a.nbytes for a in host_arrays))


def _page_in_task(paged: PagedWeights) -> List[np.ndarray]:
    """The page-in lane task (host/numpy only): fire the fault site,
    decode + CRC-verify every tensor. A transient injected error is
    retried by the caller's policy; a checksum mismatch raises
    ShardCorrupted and is NEVER retried (persistent state)."""
    faults.maybe_fail(faults.SITE_ZOO_PAGE_IN)
    return [_decode_tensor(t, faults.SITE_ZOO_PAGE_IN) for t in paged.tensors]


# ---------------------------------------------------------------------------
# Decision audit
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ZooDecision:
    """One paging/eviction/quarantine choice, as evidence — the zoo
    analogue of ``cost.decision`` / ``autoscale.decision``: what the zoo
    saw (inputs, scored candidates for evictions), what it did (action,
    tenant), and why (reason). ``ok=False`` records an attempted action
    that failed (e.g. a page-out killed mid-encode)."""

    action: str                  # page_in | page_out | evict | quarantine
    tenant: str
    reason: str
    t_s: float
    ok: bool = True
    inputs: Dict[str, Any] = field(default_factory=dict)
    candidates: List[Dict[str, Any]] = field(default_factory=list)
    # Placement-engine provenance (ISSUE 19): which weight family priced
    # the paging/eviction candidates — the field every decision stream
    # shares.
    weights_family: Optional[str] = None

    def to_args(self) -> Dict[str, Any]:
        return {
            "action": self.action,
            "tenant": self.tenant,
            "reason": self.reason,
            "ok": self.ok,
            "t_s": self.t_s,
            "inputs": dict(self.inputs),
            # Unconditional: the decision-event schema (tools/lint.py)
            # wants candidates/winner on every stream, [] when the
            # action considered no alternatives.
            "candidates": [dict(c) for c in self.candidates],
            "winner": self.tenant,
            "weights_family": self.weights_family,
        }


# ---------------------------------------------------------------------------
# Tenant state
# ---------------------------------------------------------------------------


class _Tenant:
    """One tenant's full state: identity (fingerprint, graph, slots),
    residency (plan+server when resident, PagedWeights when not), the
    front-door accounting counters (authoritative — they survive server
    teardown across page-outs), and the per-tenant SLO tracker."""

    __slots__ = (
        "tenant_id", "weight", "graph", "source", "sink", "example",
        "max_batch", "buckets", "fingerprint", "slots", "op_ids", "plan",
        "server", "paged", "resident_bytes", "quarantined",
        "quarantine_reason", "paging", "outstanding", "offered",
        "completed", "rejected", "failed", "coldstart_failfast",
        "page_ins", "page_outs", "page_retries", "last_used",
        "last_page_in_s", "slo", "replicas",
    )

    def __init__(self, tenant_id: str, plan: ExportedPlan, weight: float,
                 slo, replicas: int, resident_bytes: int):
        self.tenant_id = tenant_id
        self.weight = float(weight)
        self.graph = plan.graph
        self.source = plan.source
        self.sink = plan.sink
        self.example = np.zeros(plan.item_shape, np.dtype(plan.dtype))
        self.max_batch = plan.max_batch
        self.buckets = list(plan.buckets)
        self.fingerprint = plan.fingerprint
        self.slots: List[Tuple[Any, str, Optional[int]]] = []
        self.op_ids: frozenset = frozenset()
        self.plan: Optional[ExportedPlan] = plan
        self.server = None
        self.paged: Optional[PagedWeights] = None
        self.resident_bytes = int(resident_bytes)
        self.quarantined = False
        self.quarantine_reason: Optional[str] = None
        self.paging = False
        self.outstanding = 0
        self.offered = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.coldstart_failfast = 0
        self.page_ins = 0
        self.page_outs = 0
        self.page_retries = 0
        self.last_used = 0.0
        self.last_page_in_s: Optional[float] = None
        self.slo = slo
        self.replicas = int(replicas)

    @property
    def resident(self) -> bool:
        return self.server is not None

    def slo_state(self) -> str:
        return self.slo.worst_state() if self.slo is not None else "OK"


def _collect_weight_slots(graph):
    """``[(operator, attr, list_index_or_None, array)]`` — the same
    jax-array-attribute DETECTION as ``export._pin_operator_arrays``
    (which attrs count as pageable device weights), walked in sorted
    attribute order. Slot order is the paging identity only between
    page-out and page-in of the same entry (both read ``entry.slots``);
    it deliberately does NOT promise to match the pin walk's insertion
    order. Caller-thread only (touches jax)."""
    import jax

    from keystone_tpu.workflow.fusion import fused_members

    slots = []
    seen = set()
    for node in graph.nodes:
        op0 = graph.get_operator(node)
        for op in fused_members(op0) + [op0]:
            if id(op) in seen or not hasattr(op, "__dict__"):
                continue
            seen.add(id(op))
            for k, v in sorted(op.__dict__.items()):
                if isinstance(v, jax.Array):
                    slots.append((op, k, None, v))
                elif isinstance(v, list) and v and all(
                    isinstance(a, jax.Array) for a in v
                ):
                    for i, a in enumerate(v):
                        slots.append((op, k, i, a))
    return slots


def _restore_slot(op, attr, idx, value) -> None:
    if idx is None:
        object.__setattr__(op, attr, value)
    else:
        getattr(op, attr)[idx] = value


# ---------------------------------------------------------------------------
# The zoo
# ---------------------------------------------------------------------------


class ModelZoo:
    """Serve MANY fingerprinted plans under one hard device-memory
    budget with per-tenant isolation (module docstring for the design).

    Knobs:

      - ``budget_bytes``: the hard resident-weight budget. Page-ins
        evict until the faulting tenant fits; a single tenant larger
        than the budget is rejected at :meth:`add_tenant`.
      - ``max_outstanding_total`` / ``tenant_queue_cap``: the fair
        admission surface — the global outstanding pool WFQ shares are
        computed over, and the per-tenant depth cap.
      - ``cold_start_estimate_s``: the page-in time estimate before any
        page-in has been measured (the deadline-aware fast-fail bound;
        replaced by a measured EMA after the first page-in).
      - ``page_retry_attempts``: transient page-task failures absorbed
        per page operation before the tenant is quarantined (page-in)
        or the page-out is abandoned with the resident copy intact.
      - ``evict_drain_timeout_s``: bound on draining an eviction
        victim's in-flight work; a victim that cannot drain re-enters
        rotation untouched (zero-drop) and the page-in fails.
      - ``replicas_per_tenant``: 1 = one MicroBatchServer per resident
        tenant; >1 fronts each with a ReplicatedServer plane.
      - ``max_batch`` / ``max_wait_ms`` / ``max_queue_depth``: the
        per-tenant server knobs (docs/serving.md).
    """

    def __init__(
        self,
        budget_bytes: int,
        max_outstanding_total: int = 256,
        tenant_queue_cap: int = 64,
        cold_start_estimate_s: float = 1.0,
        page_retry_attempts: int = 3,
        evict_drain_timeout_s: float = 5.0,
        replicas_per_tenant: int = 1,
        max_batch: int = 64,
        max_wait_ms: float = 1.0,
        max_queue_depth: int = 256,
        runtime=None,
        metrics=None,
        decision_log_len: int = 256,
    ):
        if budget_bytes < 1:
            raise ValueError("budget_bytes must be >= 1")
        if max_outstanding_total < 1:
            raise ValueError("max_outstanding_total must be >= 1")
        if tenant_queue_cap < 1:
            raise ValueError("tenant_queue_cap must be >= 1")
        if replicas_per_tenant < 1:
            raise ValueError("replicas_per_tenant must be >= 1")
        self.budget_bytes = int(budget_bytes)
        self.max_outstanding_total = int(max_outstanding_total)
        self.tenant_queue_cap = int(tenant_queue_cap)
        self.cold_start_estimate_s = float(cold_start_estimate_s)
        self.page_retry_attempts = int(page_retry_attempts)
        self.evict_drain_timeout_s = float(evict_drain_timeout_s)
        self.replicas_per_tenant = int(replicas_per_tenant)
        self._server_kwargs = dict(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_queue_depth=max_queue_depth,
        )
        self._runtime = runtime
        self.metrics = metrics if metrics is not None \
            else obs.MetricsRegistry()
        self._g_residents = self.metrics.gauge(METRIC_ZOO_RESIDENTS)
        self._c_page_ins = self.metrics.counter(METRIC_ZOO_PAGE_INS)
        self._c_page_outs = self.metrics.counter(METRIC_ZOO_PAGE_OUTS)
        self._c_quarantined = self.metrics.counter(METRIC_ZOO_QUARANTINED)
        self._c_decisions = self.metrics.counter(METRIC_ZOO_DECISIONS)

        self._lock = threading.Lock()
        # Serializes ALL residency transitions (page-in, page-out,
        # eviction, add/remove): budget arithmetic stays single-writer
        # and two concurrent page faults cannot double-evict.
        self._page_lock = threading.Lock()
        self._closed = False
        self._t0 = time.monotonic()
        self._tenants: Dict[str, _Tenant] = {}
        self._page_in_ema_s: Optional[float] = None
        self._decisions: "deque[Dict[str, Any]]" = deque(
            maxlen=decision_log_len
        )
        self.num_decisions = 0

    # -- construction / membership -----------------------------------------

    def _rt(self):
        if self._runtime is not None:
            return self._runtime
        from keystone_tpu.data.runtime import default_runtime

        return default_runtime()

    def add_tenant(
        self,
        tenant_id: str,
        plan_or_fitted,
        example=None,
        weight: float = 1.0,
        slo=None,
        resident: bool = True,
        resident_bytes: Optional[int] = None,
        max_batch: Optional[int] = None,
    ) -> str:
        """Register a tenant. ``plan_or_fitted`` is an
        :class:`ExportedPlan` or a ``FittedPipeline`` (exported here at
        ``example``'s signature). The plan's fingerprint is recorded as
        the tenant's bit-identity anchor — every later page-in must
        reproduce it exactly. ``resident=False`` registers the tenant
        paged-out (the weights are encoded immediately and the compiled
        plan dropped — the cold-start-storm shape). ``resident_bytes``
        overrides the budget charge (default: the plan's pinned bytes,
        falling back to the decoded paged footprint). Plans never share
        operator objects across tenants — export per tenant (paging
        mutates operator state in place)."""
        if weight <= 0:
            raise ValueError(f"tenant {tenant_id!r}: weight must be > 0")
        if isinstance(plan_or_fitted, ExportedPlan):
            plan = plan_or_fitted
        else:
            from .export import export_plan

            if example is None:
                raise ValueError(
                    "add_tenant needs example= to export a FittedPipeline"
                )
            plan = export_plan(
                plan_or_fitted, example,
                max_batch=max_batch or self._server_kwargs["max_batch"],
            )
        op_ids = frozenset(
            id(plan.graph.get_operator(n)) for n in plan.graph.nodes
        )
        with self._lock:
            if self._closed:
                raise ServerClosed("add_tenant() after close()")
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} already registered")
            for other in self._tenants.values():
                if op_ids & other.op_ids:
                    raise ValueError(
                        f"tenant {tenant_id!r} shares operator objects "
                        f"with tenant {other.tenant_id!r} — paging one "
                        "would corrupt the other; export a separate plan "
                        "per tenant (deepcopy the fitted pipeline)"
                    )
        slots = _collect_weight_slots(plan.graph)
        bytes_est = resident_bytes if resident_bytes is not None else max(
            plan.pinned_bytes,
            sum(int(np.asarray(a).nbytes) for _, _, _, a in slots),
            1,
        )
        if bytes_est > self.budget_bytes:
            raise ValueError(
                f"tenant {tenant_id!r} needs {bytes_est} resident bytes "
                f"but the zoo budget is {self.budget_bytes} — it could "
                "never be paged in"
            )
        entry = _Tenant(
            tenant_id, plan, weight, slo,
            self.replicas_per_tenant, bytes_est,
        )
        entry.slots = [(op, k, i) for op, k, i, _ in slots]
        entry.op_ids = op_ids
        with self._page_lock:
            self._evict_until_fits(entry)
            server = self._build_server(entry, plan)
            with self._lock:
                # Re-validate ATOMICALLY with the insertion: the checks
                # above ran before the (slow) slot walk released the
                # lock, and a concurrent add_tenant racing through that
                # window must not silently replace an entry (leaking its
                # live server) or smuggle shared operator objects past
                # the guard.
                conflict = None
                if self._closed:
                    conflict = ServerClosed("add_tenant() after close()")
                elif tenant_id in self._tenants:
                    conflict = ValueError(
                        f"tenant {tenant_id!r} already registered"
                    )
                elif any(
                    op_ids & other.op_ids
                    for other in self._tenants.values()
                ):
                    conflict = ValueError(
                        f"tenant {tenant_id!r} shares operator objects "
                        "with a registered tenant"
                    )
                if conflict is None:
                    entry.server = server
                    entry.last_used = self._now()
                    self._tenants[tenant_id] = entry
            if conflict is not None:
                server.close(timeout=1.0)
                raise conflict
            self._g_residents.set(self._num_residents())
        if not resident:
            self.page_out(tenant_id)
        return entry.fingerprint

    def _build_server(self, entry: _Tenant, plan: ExportedPlan):
        kw = dict(self._server_kwargs)
        if entry.replicas > 1:
            from .replicas import ReplicatedServer

            return ReplicatedServer(
                plan, num_replicas=entry.replicas, slo=entry.slo, **kw
            )
        return MicroBatchServer(plan, slo=entry.slo, **kw)

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _num_residents(self) -> int:
        with self._lock:
            return sum(1 for t in self._tenants.values() if t.resident)

    def _resident_bytes_total(self) -> int:
        with self._lock:
            return sum(
                t.resident_bytes for t in self._tenants.values()
                if t.resident
            )

    # -- fair admission + submit -------------------------------------------

    def guaranteed_share(self, tenant_id: str) -> int:
        """The tenant's deficit-weighted guaranteed slice of the global
        outstanding pool: ``max(1, weight_i / Σweights ×
        max_outstanding_total)``. Below it a tenant ALWAYS admits (up to
        its queue cap) even when the pool is full of someone else's
        load — the starvation-proof floor of the isolation contract."""
        with self._lock:
            entry = self._tenants[tenant_id]
            total_w = sum(t.weight for t in self._tenants.values())
        return max(
            1, int(self.max_outstanding_total * entry.weight / total_w)
        )

    def submit(self, tenant: str, x, deadline_ms: Optional[float] = None):
        """Route one request to ``tenant``'s plan; returns a Future
        annotated with ``tenant`` and ``plan_fingerprint``. Admission is
        decided FIRST (quarantine fast-fail, per-tenant queue cap,
        deficit-weighted fair share), then a page fault on a cold tenant
        either fast-fails (:class:`TenantColdStart`, deadline-aware) or
        pages the plan in synchronously — the measured cold-start cost
        this one caller pays, never the batcher's worker."""
        with self._lock:
            if self._closed:
                raise ServerClosed("submit() after close()")
            entry = self._tenants.get(tenant)
            if entry is None:
                raise ValueError(f"unknown tenant {tenant!r}")
            entry.offered += 1
            self.metrics.counter(
                METRIC_TENANT_OFFERED, tenant=tenant
            ).add(1)
            if entry.quarantined:
                entry.rejected += 1
                self.metrics.counter(
                    METRIC_TENANT_REJECTED, tenant=tenant
                ).add(1)
                reason = entry.quarantine_reason
                self._observe_slo_bad(entry)
                raise TenantQuarantined(
                    f"tenant {tenant!r} is quarantined: {reason}"
                )
            if entry.outstanding >= self.tenant_queue_cap:
                entry.rejected += 1
                self.metrics.counter(
                    METRIC_TENANT_REJECTED, tenant=tenant
                ).add(1)
                self._observe_slo_bad(entry)
                raise ServerOverloaded(
                    f"tenant {tenant!r} is at its queue cap "
                    f"({self.tenant_queue_cap}) — its own offered load "
                    "exceeds its admission share"
                )
            total_out = sum(t.outstanding for t in self._tenants.values())
            total_w = sum(t.weight for t in self._tenants.values())
            share = max(1, int(
                self.max_outstanding_total * entry.weight / total_w
            ))
            if (total_out >= self.max_outstanding_total
                    and entry.outstanding >= share):
                # The pool is full AND this tenant is at/over its
                # deficit-weighted share: ITS overflow is what yields.
                # Under-share tenants keep admitting — the WFQ floor.
                entry.rejected += 1
                self.metrics.counter(
                    METRIC_TENANT_REJECTED, tenant=tenant
                ).add(1)
                self._observe_slo_bad(entry)
                raise ServerOverloaded(
                    f"tenant {tenant!r} is over its fair admission share "
                    f"({entry.outstanding}/{share} outstanding) while the "
                    f"global pool is full ({total_out}/"
                    f"{self.max_outstanding_total}) — another tenant's "
                    "guaranteed share is protected"
                )
            entry.last_used = self._now()
        # Serve loop: reserve ONLY while the tenant is observably
        # resident (a reservation held while blocked on the page lock
        # would wedge an eviction drain forever — the drain counts
        # outstanding reservations); a page fault runs WITHOUT a
        # reservation, then re-checks residency. Bounded: an eviction
        # racing this tenant back out between iterations is pathological
        # and still terminates with a named error.
        for _ in range(8):
            with self._lock:
                server = (
                    entry.server
                    if entry.resident and not entry.paging else None
                )
                if server is not None:
                    entry.outstanding += 1  # reserve: drains count us
            if server is None:
                est = self.page_in_estimate_s()
                if deadline_ms is not None and est > deadline_ms / 1e3:
                    with self._lock:
                        entry.rejected += 1
                        entry.coldstart_failfast += 1
                        self.metrics.counter(
                            METRIC_TENANT_REJECTED, tenant=tenant
                        ).add(1)
                        self.metrics.counter(
                            METRIC_TENANT_COLDSTART_FAILFAST,
                            tenant=tenant,
                        ).add(1)
                    self._observe_slo_bad(entry)
                    raise TenantColdStart(
                        f"tenant {tenant!r} is paged out and the page-in "
                        f"estimate ({est:.3g}s) exceeds the request "
                        f"deadline ({deadline_ms:.3g}ms) — fast-failing "
                        "instead of wedging the request behind a cold "
                        "start"
                    )
                try:
                    self._ensure_resident(entry)
                except BaseException:
                    with self._lock:
                        entry.failed += 1
                        self.metrics.counter(
                            METRIC_TENANT_FAILED, tenant=tenant
                        ).add(1)
                    self._observe_slo_bad(entry)
                    raise
                continue
            try:
                fut = server.submit(x, deadline_ms)
            except ServerOverloaded:
                with self._lock:
                    entry.outstanding -= 1
                    entry.rejected += 1
                    self.metrics.counter(
                        METRIC_TENANT_REJECTED, tenant=tenant
                    ).add(1)
                raise  # the tenant's own server already fed its SLO
            except BaseException:
                with self._lock:
                    entry.outstanding -= 1
                    entry.failed += 1
                    self.metrics.counter(
                        METRIC_TENANT_FAILED, tenant=tenant
                    ).add(1)
                raise
            fut.tenant = tenant
            fut.plan_fingerprint = entry.fingerprint
            fut.add_done_callback(self._done_callback(entry))
            return fut
        with self._lock:
            entry.failed += 1
            self.metrics.counter(METRIC_TENANT_FAILED, tenant=tenant).add(1)
        self._observe_slo_bad(entry)
        raise ServerDegraded(
            f"tenant {tenant!r} was repeatedly evicted between page-in "
            "and dispatch — the zoo budget is thrashing"
        )

    def _observe_slo_bad(self, entry: _Tenant) -> None:
        if entry.slo is not None:
            entry.slo.observe(ok=False)

    def _done_callback(self, entry: _Tenant):
        def _cb(fut) -> None:
            try:
                exc = fut.exception()
            except BaseException:  # noqa: BLE001 — client cancelled
                exc = None
            with self._lock:
                entry.outstanding -= 1
                if exc is None:
                    entry.completed += 1
                    name = METRIC_TENANT_COMPLETED
                elif isinstance(exc, ServerOverloaded):
                    entry.rejected += 1
                    name = METRIC_TENANT_REJECTED
                else:
                    entry.failed += 1
                    name = METRIC_TENANT_FAILED
                self.metrics.counter(name, tenant=entry.tenant_id).add(1)
        return _cb

    # -- residency transitions ---------------------------------------------

    def page_in_estimate_s(self) -> float:
        """The deadline-aware cold-start bound: the measured page-in EMA
        once one has completed, else the placement engine's priced
        worst-case tenant footprint under the active weight family
        (``zoo_page_overhead`` — what ``bin/calibrate --refit`` refits
        from stamped page-ins), floored at ``cold_start_estimate_s``
        (conservative by design — a first-ever cold start against a
        tight deadline should fast-fail, not gamble)."""
        with self._lock:
            if self._page_in_ema_s is not None:
                return self._page_in_ema_s
            worst_bytes = max(
                (t.resident_bytes for t in self._tenants.values()),
                default=0,
            )
        if worst_bytes:
            priced = PlacementEngine().price_page_in(worst_bytes)
            return max(priced, self.cold_start_estimate_s)
        return self.cold_start_estimate_s

    def _retry_policy(self) -> faults.RetryPolicy:
        return faults.RetryPolicy(attempts=self.page_retry_attempts)

    def page_in(self, tenant_id: str) -> None:
        """Make ``tenant_id`` resident (public form of the page-fault
        path — benches pre-warm through it). No-op when already
        resident; raises :class:`TenantQuarantined` when the decode
        fails its CRCs or the retry budget exhausts."""
        with self._lock:
            entry = self._tenants[tenant_id]
        self._ensure_resident(entry)

    def _ensure_resident(self, entry: _Tenant) -> None:
        with self._page_lock:
            with self._lock:
                if self._closed:
                    raise ServerClosed("page_in() after close()")
                if entry.resident and not entry.paging:
                    return  # someone paged it in while we waited
                if entry.quarantined:
                    raise TenantQuarantined(
                        f"tenant {entry.tenant_id!r} is quarantined: "
                        f"{entry.quarantine_reason}"
                    )
                paged = entry.paged
            if paged is None:  # pragma: no cover — structural invariant
                raise RuntimeError(
                    f"tenant {entry.tenant_id!r} is neither resident nor "
                    "paged"
                )
            t0 = time.perf_counter()
            self._evict_until_fits(entry)
            # Price the fault before paying it: the unified placement
            # stream records the PREDICTED page-in (the calibrated
            # ``zoo_page_overhead`` family) and gets the measured wall
            # stamped onto the same record below — the rows
            # ``bin/calibrate --refit`` refits zoo paging from.
            engine = PlacementEngine(metrics=self.metrics)
            placement_ref = engine.audit(
                KIND_ZOO_PAGE_IN, entry.tenant_id,
                [{
                    "label": entry.tenant_id,
                    "cost_s": engine.price_page_in(entry.resident_bytes),
                    "feasible": True,
                    "resident_bytes": entry.resident_bytes,
                }],
                reason="page_fault",
                context={
                    "budget_bytes": self.budget_bytes,
                    "fingerprint": entry.fingerprint,
                },
            )
            retries = [0]

            def _on_retry(attempt, delay_s, exc):
                retries[0] += 1
                logger.warning(
                    "zoo: page-in of tenant %r attempt %d failed "
                    "(retrying in %.3gs): %r",
                    entry.tenant_id, attempt, delay_s, exc,
                )

            policy = self._retry_policy()
            try:
                host = policy.call(
                    lambda: self._rt().submit(
                        "zoo.page", _page_in_task, paged
                    ).result(),
                    key=f"zoo.page_in:{entry.tenant_id}",
                    on_retry=_on_retry,
                )
            except durable.ShardCorrupted as e:
                self._quarantine_locked_page(
                    entry, f"paged weights failed CRC verification: {e}"
                )
                raise TenantQuarantined(
                    f"tenant {entry.tenant_id!r} quarantined: {e}"
                ) from e
            except OSError as e:
                self._quarantine_locked_page(
                    entry,
                    f"page-in failed {self.page_retry_attempts} "
                    f"attempt(s): {e!r}",
                )
                raise TenantQuarantined(
                    f"tenant {entry.tenant_id!r} quarantined after "
                    f"{self.page_retry_attempts} failed page-in "
                    f"attempt(s): {e!r}"
                ) from e
            # Host decode verified — restore the slots (as device arrays,
            # so export re-pins them) and rebuild the plan on THIS
            # thread (the JAX side of the page fault; the lane stays
            # jax-free).
            import jax.numpy as jnp

            for (op, attr, idx), arr in zip(entry.slots, host):
                _restore_slot(op, attr, idx, jnp.asarray(arr))
            plan = ExportedPlan(
                entry.graph, entry.source, entry.sink, entry.example,
                max_batch=entry.max_batch, buckets=entry.buckets,
            )
            if plan.fingerprint != entry.fingerprint:
                self._quarantine_locked_page(
                    entry,
                    f"rebuilt plan fingerprint {plan.fingerprint} != "
                    f"registered {entry.fingerprint} — the paging round "
                    "trip was not bit-identical",
                )
                raise TenantQuarantined(
                    f"tenant {entry.tenant_id!r} quarantined: paging "
                    f"round trip broke bit-identity ({plan.fingerprint} "
                    f"!= {entry.fingerprint})"
                )
            server = self._build_server(entry, plan)
            wall = time.perf_counter() - t0
            with self._lock:
                entry.plan = plan
                entry.server = server
                entry.paging = False
                # Drop the host-side copy: a resident tenant holding its
                # PagedWeights forever would grow host RAM by a full
                # fleet weight copy over paging cycles, and read as
                # still-paged in stats(). Page-out re-encodes from the
                # live slots; the quarantine paths (which keep the copy
                # for the postmortem) never reach this commit block.
                entry.paged = None
                entry.page_ins += 1
                entry.page_retries += retries[0]
                entry.last_page_in_s = wall
                self._page_in_ema_s = (
                    wall if self._page_in_ema_s is None
                    else 0.5 * self._page_in_ema_s + 0.5 * wall
                )
            self._c_page_ins.add(1)
            self._g_residents.set(self._num_residents())
            if placement_ref is not None:
                placement_ref.stamp(wall, timing="single_run_cold")
            self._record_decision(
                "page_in", entry.tenant_id,
                reason=f"page fault; decode+rebuild took {wall:.4g}s "
                f"({retries[0]} transient retr{'y' if retries[0] == 1 else 'ies'} absorbed)",
                inputs={
                    "resident_bytes": entry.resident_bytes,
                    "budget_bytes": self.budget_bytes,
                    "page_in_s": round(wall, 6),
                    "retries": retries[0],
                    "fingerprint": entry.fingerprint,
                },
            )

    def page_out(self, tenant_id: str) -> None:
        """Page ``tenant_id``'s weights host-side and release its device
        residency. The encode runs on the page lane through the
        ``serving.zoo.page_out`` fault site and is swapped in ATOMICALLY
        after it completes — a kill mid-encode raises with the resident
        copy untouched and still authoritative (chaos-pinned)."""
        with self._lock:
            entry = self._tenants[tenant_id]
        with self._page_lock:
            self._page_out_locked(entry, reason="explicit page_out")

    def _page_out_locked(self, entry: _Tenant, reason: str) -> None:
        """Page out one tenant (page lock held). Drains the tenant's
        outstanding work first (no admissions race: ``paging`` flips
        under the zoo lock, and submit routes paging tenants into the
        page-fault path which serializes behind the page lock)."""
        with self._lock:
            if not entry.resident:
                return
            entry.paging = True
        try:
            deadline = time.perf_counter() + self.evict_drain_timeout_s
            while True:
                with self._lock:
                    if entry.outstanding == 0:
                        break
                if time.perf_counter() >= deadline:
                    raise TimeoutError(
                        f"tenant {entry.tenant_id!r} failed to drain "
                        f"within {self.evict_drain_timeout_s:.3g}s "
                        f"({entry.outstanding} outstanding); it stays "
                        "resident"
                    )
                time.sleep(0.001)
            # Pull to host on THIS thread (jax), encode on the lane
            # (numpy) — nothing is published until the encode verifies.
            host = [
                np.asarray(a) for a in (
                    getattr(op, attr) if idx is None
                    else getattr(op, attr)[idx]
                    for op, attr, idx in entry.slots
                )
            ]
            policy = self._retry_policy()
            try:
                paged = policy.call(
                    lambda: self._rt().submit(
                        "zoo.page", _page_out_task, host
                    ).result(),
                    key=f"zoo.page_out:{entry.tenant_id}",
                )
            except BaseException as e:
                self._record_decision(
                    "page_out", entry.tenant_id, ok=False,
                    reason=f"page-out failed ({e!r}); the resident copy "
                    "stays authoritative",
                    inputs={"resident_bytes": entry.resident_bytes},
                )
                raise
            # Point of no return — everything below only releases.
            server = entry.server
            with self._lock:
                entry.paged = paged
                entry.server = None
                entry.plan = None
                entry.page_outs += 1
            server.close()
            for op, attr, idx in entry.slots:
                _restore_slot(op, attr, idx, None)
            self._c_page_outs.add(1)
            self._g_residents.set(self._num_residents())
            self._record_decision(
                "page_out", entry.tenant_id, reason=reason,
                inputs={
                    "resident_bytes": entry.resident_bytes,
                    "paged_bytes": paged.nbytes,
                    "compression": round(
                        paged.nbytes / max(paged.decoded_bytes, 1), 4
                    ),
                },
            )
        finally:
            with self._lock:
                entry.paging = False

    # -- eviction (LRU priced by cost) -------------------------------------

    def _page_cost_estimate_s(self, entry: _Tenant) -> float:
        """What bringing this tenant BACK would cost: its measured
        page-in wall when one exists, else the zoo EMA, else the cost
        model's byte pricing (active mem weight × resident bytes) with
        the cold-start seed as the floor — so eviction scoring is priced
        even before the first measurement."""
        if entry.last_page_in_s is not None:
            return entry.last_page_in_s
        with self._lock:
            ema = self._page_in_ema_s
        if ema is not None:
            return ema
        return max(
            PlacementEngine().price_page_in(entry.resident_bytes),
            self.cold_start_estimate_s,
        )

    def _evict_until_fits(self, incoming: _Tenant) -> None:
        """Evict resident tenants (page lock held) until ``incoming``
        fits the budget. Victim score = recency / (page-in cost × SLO
        pressure) — the LRU-priced-by-cost policy: old, cheap-to-restore,
        healthy tenants go first; a WARN/BREACH tenant is 4–16× stickier.
        Deterministic: ties break on tenant id. Raises
        :class:`TenantColdStart` when nothing can be evicted (every
        resident tenant is the faulting one, draining, or undrainable)."""
        while (self._resident_bytes_total() + incoming.resident_bytes
               > self.budget_bytes):
            now = self._now()
            with self._lock:
                candidates = [
                    t for t in self._tenants.values()
                    if t.resident and not t.paging
                    and t.tenant_id != incoming.tenant_id
                ]
            if not candidates:
                raise TenantColdStart(
                    f"tenant {incoming.tenant_id!r} needs "
                    f"{incoming.resident_bytes} bytes but nothing can be "
                    f"evicted (budget {self.budget_bytes}, resident "
                    f"{self._resident_bytes_total()})"
                )
            scored = []
            for t in candidates:
                age_s = max(now - t.last_used, 1e-9)
                cost_s = max(self._page_cost_estimate_s(t), 1e-9)
                pressure = _SLO_PRESSURE.get(t.slo_state(), 1.0)
                scored.append({
                    "tenant": t.tenant_id,
                    "age_s": round(age_s, 6),
                    "page_in_cost_s": round(cost_s, 6),
                    "slo_state": t.slo_state(),
                    "slo_pressure": pressure,
                    "resident_bytes": t.resident_bytes,
                    "score": age_s / (cost_s * pressure),
                })
            # Highest score evicts first; ties by tenant id so the
            # choice replays identically (tests pin this).
            scored.sort(key=lambda c: (-c["score"], c["tenant"]))
            victim_id = scored[0]["tenant"]
            with self._lock:
                victim = self._tenants[victim_id]
            reason = (
                f"budget binds paging in {incoming.tenant_id!r} "
                f"(+{incoming.resident_bytes}B over "
                f"{self.budget_bytes}B); LRU-by-cost winner"
            )
            candidates = [
                {k: v for k, v in c.items() if k != "score"}
                | {"score": round(c["score"], 6)}
                for c in scored
            ]
            self._record_decision(
                "evict", victim_id,
                reason=reason,
                inputs={
                    "incoming": incoming.tenant_id,
                    "incoming_bytes": incoming.resident_bytes,
                    "budget_bytes": self.budget_bytes,
                    "resident_bytes": self._resident_bytes_total(),
                },
                candidates=candidates,
            )
            # The placement mirror: eviction scoring is policy-chosen
            # (LRU-priced-by-cost, not a cost argmin), so the engine
            # audits rather than decides — each candidate's restore
            # price rides in ``page_in_cost_s``.
            PlacementEngine(metrics=self.metrics).audit(
                KIND_ZOO_EVICT, victim_id,
                [
                    {**c, "cost_s": c.get("page_in_cost_s")}
                    for c in candidates
                ],
                reason="lru_by_cost",
                context={
                    "incoming": incoming.tenant_id,
                    "incoming_bytes": incoming.resident_bytes,
                    "budget_bytes": self.budget_bytes,
                },
            )
            self._page_out_locked(
                victim,
                reason=f"evicted for {incoming.tenant_id!r} (LRU-by-cost)",
            )

    # -- quarantine ---------------------------------------------------------

    def _quarantine_locked_page(self, entry: _Tenant, reason: str) -> None:
        """Quarantine a tenant (page lock held): tear down any live
        server, keep the paged copy for the postmortem, flip the loud
        signals (flight dump, ``zoo.quarantined`` metric, decision
        event). Every other tenant keeps serving."""
        server = None
        with self._lock:
            entry.quarantined = True
            entry.quarantine_reason = reason
            server = entry.server
            entry.server = None
            entry.plan = None
            entry.paging = False
        if server is not None:
            server.close()
        self._c_quarantined.add(1)
        self._g_residents.set(self._num_residents())
        logger.warning(
            "zoo tenant %r QUARANTINED: %s", entry.tenant_id, reason
        )
        obs.flight.dump_flight_record(
            f"zoo tenant {entry.tenant_id!r} quarantined: {reason}",
            log=logger,
        )
        self._record_decision(
            "quarantine", entry.tenant_id, reason=reason,
            inputs={"fingerprint": entry.fingerprint},
        )

    # -- decision audit ----------------------------------------------------

    def _record_decision(self, action, tenant, reason, ok=True,
                         inputs=None, candidates=None) -> Dict[str, Any]:
        decision = ZooDecision(
            action=action, tenant=tenant, reason=reason, ok=ok,
            t_s=round(self._now(), 6),
            inputs=dict(inputs or {}),
            candidates=list(candidates or []),
            weights_family=active_family(),
        )
        rec = decision.to_args()
        with self._lock:
            self._decisions.append(rec)
            self.num_decisions += 1
        self._c_decisions.add(1)
        obs.event("zoo.decision", **rec)
        obs.flight_note(
            "zoo", f"{action}:{tenant}", ok=ok, reason=reason,
        )
        return rec

    def decision_log(self) -> List[Dict[str, Any]]:
        """The bounded in-memory audit trail (newest last)."""
        with self._lock:
            return list(self._decisions)

    # -- observability -----------------------------------------------------

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def stats(self) -> Dict[str, Any]:
        """The zoo summary block: per-tenant accounting + residency +
        compact SLO verdicts, zoo-level paging counters, the decision
        log tail, and ``accounting_ok`` — the per-tenant zero-silent-drop
        claim (``offered == completed + rejected + failed + outstanding``
        at the instant of the snapshot; exactly ``== completed +
        rejected + failed`` once the plane is drained). ``bin/slo``
        renders the tenant table from this shape."""
        now = self._now()
        per_tenant: Dict[str, Dict[str, Any]] = {}
        accounting_ok = True
        quarantined = 0
        coldstart_failfast = 0
        # Counter fields are mutated in single lock acquisitions at the
        # front door (offered+outstanding together, resolution
        # outstanding+outcome together), so the balance check must read
        # them under the SAME lock — a half-observed submit would read
        # as a spurious accounting violation.
        with self._lock:
            tenants = list(self._tenants.values())
            decisions = list(self._decisions)
            num_decisions = self.num_decisions
            total_out = sum(t.outstanding for t in tenants)
            total_w = sum(t.weight for t in tenants) or 1.0
            for t in tenants:
                balanced = (
                    t.offered
                    == t.completed + t.rejected + t.failed + t.outstanding
                )
                accounting_ok = accounting_ok and balanced
                quarantined += int(t.quarantined)
                coldstart_failfast += t.coldstart_failfast
                block: Dict[str, Any] = {
                    "resident": t.resident,
                    "quarantined": t.quarantined,
                    "weight": t.weight,
                    "offered": t.offered,
                    "completed": t.completed,
                    "rejected": t.rejected,
                    "failed": t.failed,
                    "outstanding": t.outstanding,
                    "coldstart_failfast": t.coldstart_failfast,
                    "accounting_ok": balanced,
                    "resident_bytes": t.resident_bytes,
                    "paged_bytes": t.paged.nbytes if t.paged else None,
                    "page_ins": t.page_ins,
                    "page_outs": t.page_outs,
                    "page_retries": t.page_retries,
                    "last_used_age_s": round(
                        max(now - t.last_used, 0.0), 6
                    ),
                    "guaranteed_share": max(1, int(
                        self.max_outstanding_total * t.weight / total_w
                    )),
                    "admission_share": round(
                        t.outstanding / total_out, 4
                    ) if total_out else 0.0,
                    "fingerprint": t.fingerprint,
                }
                if t.quarantine_reason:
                    block["quarantine_reason"] = t.quarantine_reason
                per_tenant[t.tenant_id] = block
        # SLO verdicts OUTSIDE the zoo lock (each tracker takes its own
        # lock and renders ledgers).
        for t in tenants:
            if t.slo is not None:
                v = t.slo.verdict()
                per_tenant[t.tenant_id]["slo"] = {
                    "state": v["state"],
                    "objectives": {
                        name: {
                            "state": o["state"],
                            "burn_fast": o["burn_fast"],
                            "burn_slow": o["burn_slow"],
                            "budget_spent_fraction":
                                o["budget_spent_fraction"],
                        }
                        for name, o in v["objectives"].items()
                    },
                }
        return {
            "num_tenants": len(tenants),
            "residents": sum(1 for t in tenants if t.resident),
            "budget_bytes": self.budget_bytes,
            "resident_bytes": sum(
                t.resident_bytes for t in tenants if t.resident
            ),
            "page_ins": int(self._c_page_ins.value),
            "page_outs": int(self._c_page_outs.value),
            "quarantined": quarantined,
            "coldstart_failfast": coldstart_failfast,
            "accounting_ok": accounting_ok,
            "num_decisions": num_decisions,
            "page_in_estimate_s": round(self.page_in_estimate_s(), 6),
            "max_outstanding_total": self.max_outstanding_total,
            "tenant_queue_cap": self.tenant_queue_cap,
            "tenants": per_tenant,
            "decisions": decisions[-64:],
        }

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop the zoo: every resident tenant's server closes
        (in-flight batches complete, queued requests fail with
        :class:`~keystone_tpu.serving.batcher.ServerClosed`).
        Idempotent; paged copies are left in place."""
        with self._page_lock:
            with self._lock:
                self._closed = True
                servers = [
                    t.server for t in self._tenants.values()
                    if t.server is not None
                ]
            for s in servers:
                s.close(timeout=timeout)

    def __enter__(self) -> "ModelZoo":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
