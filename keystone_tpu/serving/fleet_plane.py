"""One serving-plane PROCESS of the fleet: plan-ship codec + the
``multiprocessing`` bootstrap target (docs/serving.md fleet section).

The router (``serving/fleet.py``) is a jax-clean module; everything
that must touch jax — decoding shipped weights onto the device,
exporting the plan, running today's full :class:`ReplicatedServer`
stack — lives here, and ONLY runs inside the spawned plane process.
Module level stays import-light (stdlib + numpy + the jax-free fault
harness) so the parent can reference :func:`plane_main` as a spawn
target without dragging jax into the router; the heavy imports happen
inside the functions, i.e. inside the child.

Plan shipping (the tentpole's integrity contract): a plan travels as

  - a pickled *skeleton* — the fitted pipeline (fused operators
    rebuild their composed closures inside ``__setstate__``, so the
    skeleton must unpickle standalone — weight slots cannot be
    stripped to sentinels);
  - the weights, AGAIN, as the zoo's bit-exact split-plane tensors
    (``uint16`` hi/lo planes + per-tensor CRC — the PR-13 encoding,
    unchanged). These are the AUTHORITATIVE bits: on arrival each is
    CRC-verified, decoded, required to be BIT-IDENTICAL to the
    skeleton's corresponding slot (a disagreement between the two
    channels means wire corruption or tampering), and then restored
    into the slots — the skeleton's own copies are never trusted
    un-cross-checked;
  - the export signature (item shape/dtype, max_batch, padding
    buckets) and the CLAIMED ``plan_fingerprint``.

After restore the plane re-exports the plan and recomputes the
fingerprint end-to-end (the ``fleet.rpc.send`` corrupt site models
wire corruption of a shipped weight plane). Any mismatch — CRC,
cross-channel bit-identity, or fingerprint — QUARANTINES the plane: it
stays up, answers heartbeats, and refuses every request with a named
error; wrong bits are never served (the zoo's posture, extended across
the process boundary).
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.utils import faults

from .fleet_rpc import RpcServer

__all__ = ["PlanShip", "encode_plan_ship", "decode_plan_ship",
           "plane_main"]

logger = logging.getLogger(__name__)


class PlanShip:
    """The cross-process form of one exported plan (see module
    docstring). ``tensors`` are zoo ``_PagedTensor`` objects — hi/lo
    ``uint16`` planes + per-tensor CRC."""

    __slots__ = ("skeleton", "tensors", "item_shape", "dtype",
                 "max_batch", "buckets", "fingerprint")

    def __init__(self, skeleton: bytes, tensors: List[Any],
                 item_shape: Tuple[int, ...], dtype: str,
                 max_batch: Optional[int], buckets: Sequence[int],
                 fingerprint: str):
        self.skeleton = skeleton
        self.tensors = tensors
        self.item_shape = tuple(item_shape)
        self.dtype = str(dtype)
        self.max_batch = max_batch
        self.buckets = tuple(buckets)
        self.fingerprint = str(fingerprint)

    def __getstate__(self):
        return {s: getattr(self, s) for s in self.__slots__}

    def __setstate__(self, state):
        for s in self.__slots__:
            setattr(self, s, state[s])


class ShipRejected(RuntimeError):
    """A shipped plan failed its integrity verification (tensor CRC or
    end-to-end fingerprint) — the receiving plane must quarantine."""


def encode_plan_ship(fitted, plan) -> PlanShip:
    """Encode ``fitted`` (the pipeline ``plan`` was exported from) for
    shipping. Runs in the jax-owning caller process (the process that
    fit the model). The weight slots are walked in the zoo's sorted
    deterministic order and split-plane encoded (per-tensor CRC); the
    receiving plane re-walks the unpickled skeleton in the same order,
    so slot ``i`` on both sides names the same weight."""
    from keystone_tpu.serving.zoo import (
        _collect_weight_slots,
        _encode_tensor,
    )

    graph = fitted.transformer_graph
    slots = _collect_weight_slots(graph)
    host = [np.asarray(a) for (_op, _k, _i, a) in slots]
    tensors = [_encode_tensor(a) for a in host]
    skeleton = pickle.dumps(fitted, protocol=4)
    return PlanShip(
        skeleton=skeleton,
        tensors=tensors,
        item_shape=plan.item_shape,
        dtype=str(plan.dtype),
        max_batch=plan.max_batch,
        buckets=plan.buckets,
        fingerprint=plan.fingerprint,
    )


def decode_plan_ship(ship: PlanShip):
    """Rebuild an :class:`ExportedPlan` from a ship, verifying every
    tensor CRC, the cross-channel bit-identity (split-plane tensors vs
    the skeleton's own slots) and the end-to-end ``plan_fingerprint``.
    Runs in the PLANE process (owns jax). Raises :class:`ShipRejected`
    on any integrity failure — callers quarantine, never serve."""
    import jax.numpy as jnp

    from keystone_tpu.data.durable import ShardCorrupted
    from keystone_tpu.serving.export import export_plan
    from keystone_tpu.serving.zoo import (
        _collect_weight_slots,
        _decode_tensor,
        _restore_slot,
    )

    try:
        decoded = [
            _decode_tensor(t, faults.SITE_FLEET_RPC_SEND)
            for t in ship.tensors
        ]
    except ShardCorrupted as e:
        raise ShipRejected(f"weight plane CRC mismatch: {e}") from e
    fitted = pickle.loads(ship.skeleton)
    slots = _collect_weight_slots(fitted.transformer_graph)
    if len(slots) != len(decoded):
        raise ShipRejected(
            f"skeleton carries {len(slots)} weight slots, ship carries "
            f"{len(decoded)} tensors"
        )
    for ordinal, ((op, k, i, skel_val), arr) in enumerate(
        zip(slots, decoded)
    ):
        skel = np.asarray(skel_val)
        if (skel.dtype != arr.dtype or skel.shape != arr.shape
                or skel.tobytes() != arr.tobytes()):
            raise ShipRejected(
                f"weight slot {ordinal} ({k}): split-plane channel "
                f"disagrees with skeleton channel — wire corruption "
                f"or tampering"
            )
        # The CRC'd split-plane copy is the authoritative one.
        _restore_slot(op, k, i, jnp.asarray(arr))
    example = np.zeros(ship.item_shape, np.dtype(ship.dtype))
    plan = export_plan(
        fitted, example, max_batch=ship.max_batch,
        buckets=list(ship.buckets),
    )
    if plan.fingerprint != ship.fingerprint:
        raise ShipRejected(
            f"fingerprint mismatch: shipped {ship.fingerprint}, "
            f"rebuilt {plan.fingerprint}"
        )
    return plan


# ---------------------------------------------------------------------------
# The plane process
# ---------------------------------------------------------------------------


def _plane_handler(state: Dict[str, Any]):
    """Build the RPC handler closure over the plane's mutable state."""
    from keystone_tpu.serving.batcher import (
        ServerClosed,
        ServerDegraded,
        ServerOverloaded,
    )

    def handler(req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pid": os.getpid(),
                    "quarantined": state["quarantined"] is not None}
        if op == "shutdown":
            state["shutdown"].set()
            return {"ok": True}
        if op == "stats":
            srv = state["server"]
            return {
                "ok": True,
                "quarantined": state["quarantined"],
                "fingerprint": state["fingerprint"],
                "stats": srv.stats() if srv is not None else {},
            }
        if op == "submit":
            if state["quarantined"] is not None:
                return {"ok": False, "error": "quarantined",
                        "message": state["quarantined"]}
            deadline_ms = req.get("deadline_ms")
            timeout_s = (deadline_ms / 1e3 + state["grace_s"]
                         if deadline_ms is not None
                         else state["default_timeout_s"])
            t0 = time.perf_counter()
            try:
                fut = state["server"].submit(
                    req["x"], deadline_ms=deadline_ms
                )
                y = fut.result(timeout=timeout_s)
            except ServerOverloaded as e:
                return {"ok": False, "error": "overloaded",
                        "message": str(e)}
            except (ServerDegraded, ServerClosed) as e:
                return {"ok": False, "error": "degraded",
                        "message": f"{type(e).__name__}: {e}"}
            state["hist"].observe(time.perf_counter() - t0)
            return {"ok": True, "y": np.asarray(y),
                    "fingerprint": getattr(fut, "plan_fingerprint",
                                           state["fingerprint"])}
        if op == "offer":
            # Lifecycle roll across the fleet: decode the candidate
            # ship (same CRC + fingerprint verification as boot) and
            # run it through THIS plane's LifecycleController —
            # validation gate, single-replica canary, zero-drop
            # promotion — exactly the PR-14 machinery, per process.
            if state["quarantined"] is not None:
                return {"ok": False, "error": "quarantined",
                        "message": state["quarantined"]}
            try:
                candidate = decode_plan_ship(req["ship"])
            except ShipRejected as e:
                return {"ok": False, "error": "ship_rejected",
                        "message": str(e)}
            ctrl = state["lifecycle"]()
            result = ctrl.offer(candidate)
            if result.get("published"):
                state["fingerprint"] = result["fingerprint"]
            return {"ok": True, "result": result}
        return {"ok": False, "error": "unknown_op",
                "message": f"unknown op {op!r}"}

    return handler


def plane_main(name: str, conn, ship: PlanShip,
               cfg: Dict[str, Any]) -> None:
    """Child-process entry: decode the shipped plan (quarantine on any
    integrity failure), stand up the full per-process serving stack
    (:class:`ReplicatedServer` + latency histogram + ``LiveExporter``
    publishing ``/snapshot.json``), serve the fleet RPC until told to
    shut down. ``conn`` is the bootstrap pipe: exactly one dict with
    the ports/pid/quarantine verdict is sent, then it is closed."""
    # Heavy imports here — this IS the jax-owning process.
    from keystone_tpu.obs.live import LiveExporter
    from keystone_tpu.obs.metrics import BucketedHistogram
    from keystone_tpu.serving.lifecycle import LifecycleController
    from keystone_tpu.serving.replicas import ReplicatedServer

    quarantined: Optional[str] = None
    plan = None
    try:
        plan = decode_plan_ship(ship)
    except ShipRejected as e:
        quarantined = str(e)
        logger.warning(
            "fleet plane %s QUARANTINED on arrival: %s", name, e
        )
    except Exception as e:  # noqa: BLE001 — quarantine, never serve
        quarantined = f"{type(e).__name__}: {e}"
        logger.warning(
            "fleet plane %s QUARANTINED (decode error): %r", name, e
        )

    server = None
    if quarantined is None:
        server = ReplicatedServer(
            plan,
            num_replicas=int(cfg.get("replicas", 2)),
            max_wait_ms=float(cfg.get("max_wait_ms", 2.0)),
            max_queue_depth=int(cfg.get("max_queue_depth", 1024)),
            restart_budget=int(cfg.get("replica_restart_budget", 3)),
            watchdog_interval_s=float(
                cfg.get("watchdog_interval_s", 0.02)
            ),
        )

    hist = BucketedHistogram()
    state: Dict[str, Any] = {
        "server": server,
        "hist": hist,
        "quarantined": quarantined,
        "fingerprint": ship.fingerprint,
        "shutdown": threading.Event(),
        "grace_s": float(cfg.get("deadline_grace_s", 5.0)),
        "default_timeout_s": float(cfg.get("default_timeout_s", 30.0)),
    }

    _lc_lock = threading.Lock()
    _lc: List[Any] = []

    def _lifecycle() -> LifecycleController:
        with _lc_lock:
            if not _lc:
                _lc.append(LifecycleController(
                    server, plan,
                    canary_sustain_s=float(
                        cfg.get("canary_sustain_s", 0.5)
                    ),
                    canary_min_samples=int(
                        cfg.get("canary_min_samples", 5)
                    ),
                ))
            return _lc[0]

    state["lifecycle"] = _lifecycle

    def _export_stats() -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "pid": os.getpid(),
            "name": name,
            "quarantined": state["quarantined"],
            "fingerprint": state["fingerprint"],
            "latency_hist": hist.state_dict(),
        }
        srv = state["server"]
        if srv is not None:
            doc["server"] = srv.stats()
        return doc

    exporter = LiveExporter(
        {"fleet_plane": _export_stats},
        port=0,
        interval_s=float(cfg.get("metrics_interval_s", 0.25)),
    )
    rpc = RpcServer(_plane_handler(state))
    try:
        conn.send({
            "rpc_port": rpc.port,
            "metrics_port": exporter.port,
            "pid": os.getpid(),
            "quarantined": quarantined,
            "fingerprint": ship.fingerprint,
        })
        conn.close()
        state["shutdown"].wait()
    finally:
        rpc.close()
        exporter.close()
        if server is not None:
            server.close()
