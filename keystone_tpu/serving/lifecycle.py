"""Model-publication lifecycle: validation-gated publication, canary
rollout with auto-rollback, and the model-staleness clock (ROADMAP
item 4, docs/reliability.md's model-publication contract).

Every piece of the online-learning scenario existed separately —
resumable streamed fits (checkpoint/resume, PR 5), zero-drop
``swap_plan`` with per-fingerprint bit-identity (PR 7), live SLO
verdicts (PR 10) — but nothing composed them, so the most dangerous
path in the system was unguarded: a trainer could push a NaN-weighted,
quality-regressed, or latency-regressed plan straight into rotation and
the plane would serve it faithfully. The
:class:`LifecycleController` owns the path from candidate
:class:`~keystone_tpu.workflow.pipeline.FittedPipeline` to serving
rotation:

  1. **Validation gate** (:meth:`LifecycleController.offer`). Every
     candidate is exported at the plane's request signature and padding
     buckets, fingerprinted, checked for NON-FINITE weights (a NaN
     Gramian solve must die here, not in a served response), dry-run
     for BIT-IDENTITY across the padding buckets (the same rows served
     through every bucket must produce byte-identical responses — the
     per-fingerprint contract the plane states), and scored on a
     held-out shard. A candidate that regresses quality past the
     declared ``quality_bound`` is REJECTED LOUDLY — a structured
     ``lifecycle.decision`` audit event (the ``cost.decision`` /
     ``autoscale.decision`` / ``zoo.decision`` mirror), a flight note,
     the ``lifecycle.rejected`` counter — and never touches the plane:
     zero requests are ever served under a rejected fingerprint.
  2. **Canary rollout.** A passing candidate is swapped into ONE
     replica first (:meth:`ReplicatedServer.swap_replica_plan` — the
     zero-drop drain protocol, scoped to the lowest live index), and
     the controller compares the canary's exec-latency percentile and
     the plane's SLO state against the incumbent replicas over a
     ``canary_sustain_s`` window. A canary whose exec p99 exceeds
     ``canary_latency_factor``× the incumbents' (at
     ``canary_min_samples`` or more completions), or under which the
     SLO state DEGRADES, is swapped straight back — the regression
     never reaches the full plane. Otherwise the candidate promotes
     via the full zero-drop rollout.
  3. **Automatic rollback.** The controller keeps a bounded ring of
     previously-served plans keyed by fingerprint. After a promotion an
     ATTRIBUTION WINDOW opens (``attribution_window_s``): an SLO
     WARN/BREACH inside the window, while the new fingerprint is the
     incumbent and the state at promotion was better, is attributed to
     the new plan and triggers a zero-drop ``swap_plan`` back to the
     prior plan. The attribution rule is deliberately conservative in
     ONE direction: a plan that was promoted into an already-degraded
     plane is never blamed for the pre-existing degradation.
  4. **Model staleness.** ``offer(candidate, data_time=...)`` carries
     the arrival stamp of the newest shard the candidate covers; the
     serving plane stamps the FIRST response completed under each
     fingerprint (:meth:`ReplicatedServer.first_completion_times`), and
     the controller publishes the difference — shard arrival → first
     response served under the covering fingerprint — as
     ``lifecycle.staleness_s`` (registry gauge + stats block, rendered
     by ``bin/slo``). Both ends are exact stamps, not poll estimates.

Fault sites ``lifecycle.validate`` (gate-infrastructure failure →
loud ``ok=False`` rejection, plane untouched) and ``lifecycle.publish``
(swap-path failure → loud publication failure, incumbent keeps
serving) feed the chaos suite (tests/test_chaos_lifecycle.py), beside
the trainer's ``trainer.fit`` kill-mid-fit site.

Thread contract: ``offer()`` runs on the trainer's thread (one
publication at a time — the controller lock); the optional monitor
thread (:meth:`start`) drives :meth:`poll` for staleness detection and
post-promotion rollback. No jax imports in this module — device work
happens inside the exported plans and the plane's swap machinery.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu import obs
from keystone_tpu.obs.metrics import (
    METRIC_LIFECYCLE_CANARY_PROMOTIONS,
    METRIC_LIFECYCLE_PUBLISHED,
    METRIC_LIFECYCLE_REJECTED,
    METRIC_LIFECYCLE_ROLLBACKS,
    METRIC_LIFECYCLE_STALENESS_S,
)
from keystone_tpu.obs.slo import STATE_BREACH, STATE_OK, STATE_WARN
from keystone_tpu.utils import faults

from .export import ExportedPlan, export_plan

__all__ = ["LifecycleController", "LifecycleDecision"]

logger = logging.getLogger("keystone_tpu.serving")

_STATE_RANK = {STATE_OK: 0, STATE_WARN: 1, STATE_BREACH: 2}


@dataclass(frozen=True)
class LifecycleDecision:
    """One publication-path action, as evidence — the model-lifecycle
    analogue of ``cost.decision``/``autoscale.decision``: which
    fingerprint, what the gate/canary saw (inputs), the declared bounds
    it was judged against (thresholds), what happened (action), and why
    (reason). ``ok=False`` records an action that FAILED (a gate
    infrastructure error, a publish swap failure) — part of the audit
    trail, never a silent no-op."""

    action: str        # publish | reject | canary_rollback | rollback
    reason: str
    fingerprint: Optional[str]
    t_s: float
    ok: bool = True
    inputs: Dict[str, Any] = field(default_factory=dict)
    thresholds: Dict[str, Any] = field(default_factory=dict)
    # Decision-stream schema fields (ISSUE 19): lifecycle gates are
    # quality decisions, not resource pricing, so candidates is usually
    # the single judged fingerprint — but the stream carries the same
    # winner/candidates/weights_family shape as the other five, so
    # ``bin/trace --decisions`` and the capacity planner merge it.
    weights_family: Optional[str] = None

    def to_args(self) -> Dict[str, Any]:
        return {
            "action": self.action,
            "reason": self.reason,
            "fingerprint": self.fingerprint,
            "ok": self.ok,
            "t_s": self.t_s,
            "inputs": dict(self.inputs),
            "thresholds": dict(self.thresholds),
            "winner": self.fingerprint or self.action,
            "candidates": (
                [{"label": self.fingerprint, "cost_s": None,
                  "feasible": self.ok}]
                if self.fingerprint else []
            ),
            "weights_family": self.weights_family,
        }


class _Watch:
    """The post-promotion attribution window: which fingerprint is on
    probation, what it replaced, and the SLO state it inherited."""

    __slots__ = ("fingerprint", "prior_fingerprint", "prior_plan",
                 "t_promoted", "baseline_rank")

    def __init__(self, fingerprint, prior_fingerprint, prior_plan,
                 t_promoted, baseline_rank):
        self.fingerprint = fingerprint
        self.prior_fingerprint = prior_fingerprint
        self.prior_plan = prior_plan
        self.t_promoted = t_promoted
        self.baseline_rank = baseline_rank


class LifecycleController:
    """Own the candidate → rotation path for one serving plane
    (module docstring for the full design).

    Knobs:

      - ``holdout``: ``(X, y)`` numpy pair the gate scores candidates
        on (None disables quality gating — the finite-weights and
        bit-identity checks still run).
      - ``quality_bound``: maximum allowed held-out score REGRESSION
        vs the incumbent (score units — the default scorer is negative
        MSE, so 0.05 means "at most 0.05 more MSE than the incumbent").
      - ``score_fn(plan, X, y) -> float``: higher-is-better scorer
        (default: negative mean squared error over batched applies).
      - ``canary_sustain_s`` / ``canary_latency_factor`` /
        ``canary_min_samples``: the canary window, the exec-p99
        regression multiple that fails it, and the minimum canary
        completions a latency verdict needs (0 sustain disables the
        canary — candidates promote directly; a single-replica plane
        also promotes directly, there is no second replica to canary
        on).
      - ``attribution_window_s``: how long after a promotion an SLO
        degradation is attributed to the new fingerprint.
      - ``canary_pollution_grace_s``: how long after a canary ROLLBACK
        the attribution check stands down — the rolled-back canary's
        slow responses are still in the SLO burn windows, and blaming
        the incumbent on probation for the canary's pollution would
        cascade one caught regression into a second, spurious
        full-plane rollback.
      - ``rollback_ring``: how many previously-served plans are kept
        promotable-back-to.
      - ``slo``: the plane's :class:`~keystone_tpu.obs.slo.SLOTracker`
        (optional — without it canary/rollback judge on latency only).
      - ``metrics``: registry for the ``lifecycle.*`` counters/gauge
        (defaults to the plane's own, so the live exporter renders
        them beside the serving counters).
    """

    def __init__(
        self,
        plane,
        incumbent: ExportedPlan,
        holdout: Optional[Tuple[Any, Any]] = None,
        quality_bound: float = 0.05,
        score_fn: Optional[Callable[..., float]] = None,
        canary_sustain_s: float = 1.0,
        canary_latency_factor: float = 3.0,
        canary_min_samples: int = 20,
        attribution_window_s: float = 30.0,
        canary_pollution_grace_s: float = 10.0,
        rollback_ring: int = 4,
        slo=None,
        metrics=None,
        poll_interval_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
        decision_log_len: int = 256,
    ):
        if quality_bound < 0:
            raise ValueError("quality_bound must be >= 0")
        if canary_latency_factor < 1.0:
            raise ValueError("canary_latency_factor must be >= 1")
        if rollback_ring < 1:
            raise ValueError("rollback_ring must be >= 1")
        self.plane = plane
        self.quality_bound = float(quality_bound)
        self.canary_sustain_s = float(canary_sustain_s)
        self.canary_latency_factor = float(canary_latency_factor)
        self.canary_min_samples = int(canary_min_samples)
        self.attribution_window_s = float(attribution_window_s)
        self.canary_pollution_grace_s = float(canary_pollution_grace_s)
        self.poll_interval_s = float(poll_interval_s)
        self._score_fn = score_fn or _default_score
        self._holdout = None
        if holdout is not None:
            X, y = holdout
            self._holdout = (np.asarray(X), np.asarray(y))
        self._slo = slo
        self._clock = clock
        self._t0 = clock()

        # Publication state — one lock owns incumbent/ring/watch/pending
        # (offer() holds it for a whole publication, so poll()'s
        # rollback can never interleave with a half-done promotion).
        self._pub_lock = threading.RLock()
        self._incumbent = incumbent
        self._incumbent_score: Optional[float] = None
        self._ring: "deque[Tuple[str, ExportedPlan]]" = deque(
            maxlen=int(rollback_ring)
        )
        self._watch: Optional[_Watch] = None
        # Attribution stands down until this stamp after a canary
        # rollback (the canary's pollution is still in the SLO burn
        # windows — class docstring).
        self._attribution_hold_until = -float("inf")
        # fingerprint -> (data_time, t_published): awaiting their first
        # served response for the staleness clock.
        self._pending_staleness: Dict[str, Tuple[float, float]] = {}

        self._stats_lock = threading.Lock()
        self.published = 0
        self.rejected = 0
        self.rollbacks = 0
        self.canary_promotions = 0
        self.num_decisions = 0
        self._decisions: "deque[Dict[str, Any]]" = deque(
            maxlen=decision_log_len
        )
        # Bounded like the decision log: a learn deployment publishes
        # indefinitely, and stats() reads this every exporter tick —
        # the window median over the retained samples is the claim.
        self._staleness: "deque[float]" = deque(maxlen=1024)
        self._staleness_total = 0

        reg = metrics if metrics is not None else getattr(
            plane, "metrics", None
        )
        self._metrics = reg
        if reg is not None:
            self._c_published = reg.counter(METRIC_LIFECYCLE_PUBLISHED)
            self._c_rejected = reg.counter(METRIC_LIFECYCLE_REJECTED)
            self._c_rollbacks = reg.counter(METRIC_LIFECYCLE_ROLLBACKS)
            self._c_canary = reg.counter(
                METRIC_LIFECYCLE_CANARY_PROMOTIONS
            )
            self._g_staleness = reg.gauge(METRIC_LIFECYCLE_STALENESS_S)

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the monitor loop --------------------------------------------------

    def start(self) -> "LifecycleController":
        """Start the monitor thread: drives :meth:`poll` (staleness
        detection + post-promotion rollback) every ``poll_interval_s``.
        Idempotent."""
        with self._stats_lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._loop,
                name="keystone-serving-lifecycle", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — monitor must survive
                logger.warning("lifecycle poll failed: %r", e)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the monitor thread (joins it). The serving plane is NOT
        closed — the controller owns the publication path, not the
        plane. Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def __enter__(self) -> "LifecycleController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the publication path ----------------------------------------------

    def offer(self, candidate, data_time: Optional[float] = None,
              context: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Run one candidate through gate → canary → promote.

        ``candidate`` is a ``FittedPipeline`` (exported here at the
        plane's signature and padding buckets) or an
        :class:`ExportedPlan`. ``data_time`` is the ``time.monotonic()``
        arrival stamp of the newest shard the candidate covers — the
        staleness clock's start. Returns a result dict:
        ``{"published": bool, "fingerprint": ..., "reason": ...}``.
        Rejections are LOUD (audit event, flight note, counter, warning
        log) but never raise — a bad candidate must not kill the
        trainer that produced it."""
        t_offer = self._clock()
        with self._pub_lock:
            # ---- the validation gate ----
            try:
                faults.maybe_fail(faults.SITE_LIFECYCLE_VALIDATE)
                plan = self._export(candidate)
                reason, gate = self._validate(plan)
            except Exception as e:  # noqa: BLE001 — gate must fail closed
                self._reject(
                    None, f"validate_error:{type(e).__name__}",
                    ok=False, inputs={"error": str(e)[:300],
                                      **(context or {})},
                )
                return {"published": False, "fingerprint": None,
                        "reason": f"validate_error:{type(e).__name__}"}
            gate.update(context or {})
            if reason is not None:
                self._reject(plan.fingerprint, reason, inputs=gate)
                return {"published": False,
                        "fingerprint": plan.fingerprint, "reason": reason}
            # ---- canary + promote ----
            try:
                faults.maybe_fail(faults.SITE_LIFECYCLE_PUBLISH)
                return self._publish(plan, gate, data_time, t_offer)
            except Exception as e:  # noqa: BLE001 — loud, plane intact
                self._record(
                    "publish", f"publish_error:{type(e).__name__}",
                    plan.fingerprint, ok=False,
                    inputs={"error": str(e)[:300], **gate},
                )
                logger.warning(
                    "lifecycle: publishing candidate %s FAILED (%r) — "
                    "the candidate was NOT promoted",
                    plan.fingerprint, e,
                )
                return {"published": False,
                        "fingerprint": plan.fingerprint,
                        "reason": f"publish_error:{type(e).__name__}"}

    def _export(self, candidate) -> ExportedPlan:
        """Candidate → ExportedPlan at the plane's signature, max_batch
        and padding buckets (so the swap drain protocol holds by
        construction, exactly like ``swap_plan``'s FittedPipeline
        form)."""
        cur = self._incumbent
        if isinstance(candidate, ExportedPlan):
            if (candidate.item_shape != cur.item_shape
                    or candidate.dtype != cur.dtype):
                raise ValueError(
                    f"candidate signature {candidate.item_shape}/"
                    f"{candidate.dtype} != plane signature "
                    f"{cur.item_shape}/{cur.dtype}"
                )
            return candidate
        example = np.zeros(cur.item_shape, np.dtype(cur.dtype))
        return export_plan(
            candidate, example, max_batch=cur.max_batch,
            buckets=cur.buckets,
        )

    def _validate(self, plan: ExportedPlan):
        """The gate body: (reject_reason | None, gate-evidence dict)."""
        gate: Dict[str, Any] = {"candidate_fingerprint": plan.fingerprint}
        # 1. Non-finite weights: a NaN/Inf anywhere in the exported
        # operators poisons every response silently — die here.
        site = _non_finite_site(plan.graph)
        if site is not None:
            gate["non_finite_at"] = site
            return "non_finite_weights", gate
        # 2. Bit-identity dry-run across the padding buckets: the same
        # rows served through EVERY bucket (and served twice through
        # the same bucket) must produce byte-identical outputs — the
        # per-fingerprint contract the plane stamps on every response.
        mismatch = _bucket_identity_mismatch(plan)
        if mismatch is not None:
            gate["bit_identity_mismatch"] = mismatch
            return "bucket_bit_identity", gate
        gate["buckets_dry_run"] = list(plan.buckets)
        # 3. Held-out quality: candidate score (higher is better) must
        # not regress past the declared bound vs the incumbent.
        if self._holdout is not None:
            X, y = self._holdout
            cand = float(self._score_fn(plan, X, y))
            if self._incumbent_score is None:
                self._incumbent_score = float(
                    self._score_fn(self._incumbent, X, y)
                )
            gate["candidate_score"] = round(cand, 6)
            gate["incumbent_score"] = round(self._incumbent_score, 6)
            if cand < self._incumbent_score - self.quality_bound:
                return "quality_regression", gate
            gate["_score"] = cand
        return None, gate

    def _publish(self, plan: ExportedPlan, gate: Dict[str, Any],
                 data_time: Optional[float], t_offer: float):
        incumbent = self._incumbent
        fp = plan.fingerprint
        if fp == incumbent.fingerprint:
            # Publishing the incumbent again is a no-op, not a rollout:
            # re-draining the plane to install identical bits would be
            # pure churn (and would reopen its attribution window).
            self._record("publish", "already_incumbent", fp,
                         inputs={k: v for k, v in gate.items()
                                 if not k.startswith("_")})
            return {"published": True, "fingerprint": fp,
                    "reason": "already_incumbent", "canary": False}
        state_before = (
            self._slo.worst_state() if self._slo is not None else None
        )
        live = self.plane.live_replica_indices()
        canary_block: Optional[Dict[str, Any]] = None
        if self.canary_sustain_s > 0 and len(live) >= 2:
            canary_block = self._run_canary(plan, incumbent, live[0],
                                            state_before)
            if canary_block.get("regressed"):
                # The canary's slow responses are in the SLO windows:
                # attribution to the incumbent stands down while they
                # age out, or one caught regression cascades into a
                # spurious full-plane rollback.
                self._attribution_hold_until = (
                    self._clock() + self.canary_pollution_grace_s
                )
                with self._stats_lock:
                    self.rollbacks += 1
                if self._metrics is not None:
                    self._c_rollbacks.add(1)
                self._record(
                    "canary_rollback", canary_block["reason"], fp,
                    inputs={**{k: v for k, v in gate.items()
                               if not k.startswith("_")},
                            "canary": canary_block},
                )
                logger.warning(
                    "lifecycle: canary REGRESSED for candidate %s (%s) "
                    "— rolled the canary replica back to incumbent %s",
                    fp, canary_block["reason"], incumbent.fingerprint,
                )
                return {"published": False, "fingerprint": fp,
                        "reason": canary_block["reason"],
                        "canary": canary_block}
        # Full-plane promotion (the canary replica re-swaps with the
        # rest — each worker generation still serves one version).
        self.plane.swap_plan(plan)
        self._ring.append((incumbent.fingerprint, incumbent))
        self._incumbent = plan
        if gate.get("_score") is not None:
            self._incumbent_score = gate["_score"]
        self._watch = _Watch(
            fp, incumbent.fingerprint, incumbent, self._clock(),
            _STATE_RANK.get(state_before, 0),
        )
        # Settle + prune the staleness book: a superseded fingerprint
        # that never served cannot serve now (its generations drained
        # to zero before closing), so keeping it pending would leak one
        # entry per unserved publication forever.
        self._settle_staleness()
        self._pending_staleness = {
            f: v for f, v in self._pending_staleness.items() if f == fp
        }
        if data_time is not None:
            self._pending_staleness[fp] = (
                float(data_time), self._clock()
            )
        with self._stats_lock:
            self.published += 1
            if canary_block is not None:
                self.canary_promotions += 1
        if self._metrics is not None:
            self._c_published.add(1)
            if canary_block is not None:
                self._c_canary.add(1)
        self._record(
            "publish", "promoted", fp,
            inputs={
                **{k: v for k, v in gate.items()
                   if not k.startswith("_")},
                "prior_fingerprint": incumbent.fingerprint,
                "canary": canary_block,
                "publish_wall_s": round(self._clock() - t_offer, 6),
            },
        )
        return {"published": True, "fingerprint": fp,
                "reason": "promoted", "canary": canary_block}

    def _swap_back(self, canary_index: int,
                   incumbent: ExportedPlan) -> None:
        """Return the canary replica to the incumbent plan — with one
        paced retry, because FAILING here leaves a known-bad candidate
        serving a share of live traffic. If both attempts fail, the
        raise NAMES that state explicitly (it lands in the ok=False
        decision's inputs and the warning log) instead of letting the
        generic publish-error path claim the incumbent kept serving."""
        last: Optional[BaseException] = None
        for attempt in (1, 2):
            try:
                self.plane.swap_replica_plan(canary_index, incumbent)
                return
            except Exception as e:  # noqa: BLE001 — retried, then loud
                last = e
                if attempt == 1:
                    time.sleep(0.1)
        logger.error(
            "lifecycle: canary swap-back FAILED twice (%r) — the "
            "REJECTED candidate is STILL SERVING on replica %d until "
            "the next successful swap", last, canary_index,
        )
        obs.flight_note(
            "lifecycle", f"canary_swap_back_failed:replica={canary_index}",
            ok=False, error=repr(last),
        )
        raise RuntimeError(
            f"canary swap-back failed on replica {canary_index}: the "
            f"rejected candidate is STILL IN ROTATION there ({last!r})"
        ) from last

    def _run_canary(self, plan: ExportedPlan, incumbent: ExportedPlan,
                    canary_index: int, state_before) -> Dict[str, Any]:
        """Swap the candidate into one replica, hold it under live
        traffic for the sustain window, and judge its exec-latency tail
        and the SLO state against the incumbents. On regression the
        canary replica swaps straight back — zero-drop both ways.

        Window caveat (stated, accepted): the canary's exec p99 covers
        only its fresh generation's sustain window while the incumbents'
        covers their span ring (bounded — recent spans, not lifetime),
        so the comparison is not perfectly matched; the
        ``canary_latency_factor`` margin absorbs the skew and the
        post-promotion attribution window is the backstop for anything
        it lets through."""
        self.plane.swap_replica_plan(canary_index, plan)
        deadline = self._clock() + self.canary_sustain_s
        canary_p99 = incumbent_p99 = None
        canary_completed = 0
        try:
            while self._clock() < deadline:
                time.sleep(min(0.02, self.canary_sustain_s / 10.0))
            stats = self.plane.stats()
            per_rep = stats.get("per_replica") or {}
            c = per_rep.get(canary_index) or {}
            canary_p99 = c.get("p99_exec_s")
            canary_completed = int(c.get("completed") or 0)
            others = [
                r.get("p99_exec_s")
                for idx, r in per_rep.items()
                if idx != canary_index and r.get("in_rotation")
                and r.get("p99_exec_s") is not None
            ]
            incumbent_p99 = (
                float(np.median(others)) if others else None
            )
        except Exception:
            # Judging failed — the canary must not stay in rotation on
            # an unjudged candidate.
            self._swap_back(canary_index, incumbent)
            raise
        state_now = (
            self._slo.worst_state() if self._slo is not None else None
        )
        block: Dict[str, Any] = {
            "replica": canary_index,
            "sustain_s": self.canary_sustain_s,
            "canary_p99_exec_s": canary_p99,
            "incumbent_p99_exec_s": incumbent_p99,
            "canary_completed": canary_completed,
            "slo_state_before": state_before,
            "slo_state_after": state_now,
            "regressed": False,
            "reason": "canary_held",
        }
        latency_regressed = (
            canary_p99 is not None and incumbent_p99 is not None
            and canary_completed >= self.canary_min_samples
            and canary_p99 > self.canary_latency_factor * incumbent_p99
        )
        slo_regressed = (
            state_now is not None and state_before is not None
            and _STATE_RANK.get(state_now, 0)
            > _STATE_RANK.get(state_before, 0)
        )
        if latency_regressed or slo_regressed:
            block["regressed"] = True
            block["reason"] = (
                "canary_latency_regression" if latency_regressed
                else f"canary_slo_{state_now}"
            )
            self._swap_back(canary_index, incumbent)
        elif canary_completed < self.canary_min_samples:
            # Too little traffic for a latency verdict: promote, but
            # say so — the attribution window is the backstop.
            block["reason"] = "insufficient_canary_samples"
        return block

    # -- the monitor body --------------------------------------------------

    def poll(self) -> Optional[Dict[str, Any]]:
        """One monitor pass: close any completed staleness clocks, then
        check the post-promotion attribution window — an SLO WARN/BREACH
        inside it, attributable to the promoted fingerprint, triggers
        the automatic zero-drop rollback. Returns the rollback decision
        record when one fired, else None."""
        with self._pub_lock:
            self._settle_staleness()
            watch = self._watch
            if watch is None:
                return None
            now = self._clock()
            if now - watch.t_promoted > self.attribution_window_s:
                self._watch = None  # survived probation
                return None
            if self._slo is None:
                return None
            if self._incumbent.fingerprint != watch.fingerprint:
                self._watch = None  # superseded (or manually swapped)
                return None
            if now < self._attribution_hold_until:
                # A rolled-back canary's pollution is still aging out
                # of the burn windows — degradation here is ITS fault,
                # not the probationary incumbent's.
                return None
            state = self._slo.worst_state()
            rank = _STATE_RANK.get(state, 0)
            if rank <= max(watch.baseline_rank,
                           _STATE_RANK[STATE_OK]):
                return None
            # Attributed: the plane degraded past its promotion-time
            # state while the new fingerprint was serving, inside the
            # window. Roll back to the prior plan — zero-drop.
            self.plane.swap_plan(watch.prior_plan)
            self._incumbent = watch.prior_plan
            self._incumbent_score = None  # re-score lazily
            self._pending_staleness.pop(watch.fingerprint, None)
            self._watch = None
            with self._stats_lock:
                self.rollbacks += 1
            if self._metrics is not None:
                self._c_rollbacks.add(1)
            rec = self._record(
                "rollback", f"slo_{state.lower()}_attributed",
                watch.fingerprint,
                inputs={
                    "slo_state": state,
                    "baseline_state_rank": watch.baseline_rank,
                    "window_s": round(now - watch.t_promoted, 6),
                    "restored_fingerprint": watch.prior_fingerprint,
                },
            )
            logger.warning(
                "lifecycle: SLO %s attributed to fingerprint %s "
                "(%.3fs after promotion) — ROLLED BACK to %s",
                state, watch.fingerprint, now - watch.t_promoted,
                watch.prior_fingerprint,
            )
            return rec

    def _settle_staleness(self) -> None:
        if not self._pending_staleness:
            return
        first = self.plane.first_completion_times()
        for fp in list(self._pending_staleness):
            t_first = first.get(fp)
            if t_first is None:
                continue
            data_time, _t_pub = self._pending_staleness.pop(fp)
            staleness = max(t_first - data_time, 0.0)
            with self._stats_lock:
                self._staleness.append(staleness)
                self._staleness_total += 1
            if self._metrics is not None:
                self._g_staleness.set(staleness)
            obs.event(
                "lifecycle.staleness", fingerprint=fp,
                staleness_s=round(staleness, 6),
            )

    # -- audit -------------------------------------------------------------

    def _thresholds(self) -> Dict[str, Any]:
        return {
            "quality_bound": self.quality_bound,
            "canary_sustain_s": self.canary_sustain_s,
            "canary_latency_factor": self.canary_latency_factor,
            "canary_min_samples": self.canary_min_samples,
            "attribution_window_s": self.attribution_window_s,
            "canary_pollution_grace_s": self.canary_pollution_grace_s,
        }

    def _reject(self, fingerprint, reason, ok=True, inputs=None):
        with self._stats_lock:
            self.rejected += 1
        if self._metrics is not None:
            self._c_rejected.add(1)
        logger.warning(
            "lifecycle: candidate %s REJECTED at the validation gate "
            "(%s) — it never touches the serving plane",
            fingerprint or "<unexported>", reason,
        )
        self._record("reject", reason, fingerprint, ok=ok,
                     inputs=inputs)

    def _record(self, action, reason, fingerprint, ok=True,
                inputs=None) -> Dict[str, Any]:
        from keystone_tpu.placement.engine import active_family

        decision = LifecycleDecision(
            action=action, reason=reason, fingerprint=fingerprint,
            ok=ok, t_s=round(self._clock() - self._t0, 6),
            inputs=dict(inputs or {}), thresholds=self._thresholds(),
            weights_family=active_family(),
        )
        rec = decision.to_args()
        with self._stats_lock:
            self._decisions.append(rec)
            self.num_decisions += 1
        obs.event("lifecycle.decision", **rec)
        obs.flight_note(
            "lifecycle", f"{action}:{fingerprint}", ok=ok,
            reason=reason,
        )
        return rec

    # -- reading -----------------------------------------------------------

    @property
    def incumbent_fingerprint(self) -> str:
        with self._pub_lock:
            return self._incumbent.fingerprint

    def ring_fingerprints(self) -> List[str]:
        with self._pub_lock:
            return [fp for fp, _ in self._ring]

    def decision_log(self) -> List[Dict[str, Any]]:
        """The bounded in-memory audit trail (newest last)."""
        with self._stats_lock:
            return list(self._decisions)

    def staleness_samples(self) -> List[float]:
        with self._stats_lock:
            return list(self._staleness)

    def stats(self) -> Dict[str, Any]:
        """The lifecycle summary block ``bin/slo`` renders and the
        ``learn`` summary line / bench row embed. NOTE the bench
        ``make_row`` audit rule: any dict claiming ``staleness*`` or
        ``rollbacks`` must also carry a numeric ``offered*`` rate —
        this block carries ``num_published`` itself; embedders merge it
        into a dict that carries the offered rate of the load the
        claims were measured under."""
        with self._stats_lock:
            staleness = list(self._staleness)
            decisions = list(self._decisions)
            out: Dict[str, Any] = {
                "published": self.published,
                "num_published": self.published,
                "rejected": self.rejected,
                "rollbacks": self.rollbacks,
                "canary_promotions": self.canary_promotions,
                "num_decisions": self.num_decisions,
            }
        out["staleness_s"] = (
            round(staleness[-1], 6) if staleness else None
        )
        out["staleness_median_s"] = (
            round(float(np.median(staleness)), 6) if staleness else None
        )
        with self._stats_lock:
            out["staleness_num_samples"] = self._staleness_total
        with self._pub_lock:
            out["incumbent_fingerprint"] = self._incumbent.fingerprint
            out["ring_fingerprints"] = [fp for fp, _ in self._ring]
            out["pending_staleness"] = len(self._pending_staleness)
            out["attribution_open"] = self._watch is not None
        out["thresholds"] = self._thresholds()
        out["decisions"] = decisions[-64:]
        return out


# -- gate helpers ------------------------------------------------------------


def _iter_arrays(v):
    """Yield array-likes inside an operator attribute value: numpy /
    jax arrays directly (duck-typed — no jax import in this module),
    lists/tuples elementwise."""
    if isinstance(v, (list, tuple)):
        for e in v:
            yield from _iter_arrays(e)
        return
    if isinstance(v, np.ndarray):
        yield v
        return
    if (hasattr(v, "dtype") and hasattr(v, "shape")
            and hasattr(v, "__array__")):
        yield v


def _non_finite_site(graph) -> Optional[str]:
    """``"Operator.attr"`` of the first non-finite float array in any
    exported operator's state (fused members included), or None when
    every weight is finite."""
    from keystone_tpu.workflow.fusion import fused_members

    seen = set()
    for node in graph.nodes:
        op = graph.get_operator(node)
        for member in fused_members(op) + [op]:
            if id(member) in seen or not hasattr(member, "__dict__"):
                continue
            seen.add(id(member))
            for k, v in member.__dict__.items():
                if k.startswith("_"):
                    continue
                for arr in _iter_arrays(v):
                    a = np.asarray(arr)
                    if a.dtype.kind == "f" and a.size and not bool(
                        np.isfinite(a).all()
                    ):
                        return f"{type(member).__name__}.{k}"
    return None


def _bucket_identity_mismatch(plan: ExportedPlan) -> Optional[str]:
    """Serve one deterministic probe batch through EVERY padding bucket
    (and twice through the first) and require byte-identical outputs —
    the dry-run form of the plane's per-fingerprint bit-identity
    contract. Returns a description of the first mismatch, or None."""
    m = min(plan.buckets)
    rng = np.random.default_rng(0xC0FFEE)
    X = rng.normal(size=(m,) + plan.item_shape).astype(
        np.dtype(plan.dtype), copy=False
    )
    rows = list(X)
    ref = np.asarray(plan.apply_batch(rows))
    again = np.asarray(plan.apply_batch(rows))
    if not np.array_equal(ref, again):
        return f"bucket={m}: two applies of the same batch differ"
    for b in plan.buckets[1:]:
        pad = np.zeros((b - m,) + plan.item_shape, X.dtype)
        out = np.asarray(
            plan.apply_padded(np.concatenate([X, pad], axis=0))
        )[:m]
        if not np.array_equal(ref, out):
            return (
                f"bucket={b}: padded output differs from bucket={m} "
                "reference"
            )
    return None


def _default_score(plan: ExportedPlan, X, y) -> float:
    """Negative mean squared error of batched applies (higher is
    better) — the gate's default held-out scorer."""
    X = np.asarray(X)
    y = np.asarray(y)
    outs = []
    for i in range(0, len(X), plan.max_batch):
        outs.append(np.asarray(
            plan.apply_batch(list(X[i:i + plan.max_batch]))
        ))
    out = np.concatenate(outs, axis=0)
    return -float(np.mean((out.astype(np.float64)
                           - y.astype(np.float64)) ** 2))
