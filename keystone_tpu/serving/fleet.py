"""Multi-process serving fleet: crash-contained planes behind one
admission router (docs/serving.md fleet section; ISSUE 20 tentpole).

# lint: jax-clean-module

Every serving-side robustness mechanism so far — replica failover, the
autoscaler, tenant isolation, the canary lifecycle — lives as threads
inside ONE process; a single interpreter crash takes the whole fabric
down. This module breaks that ceiling: a :class:`FleetRouter` fronts N
per-process serving planes (each today's full ``ReplicatedServer``
stack, spawned via ``multiprocessing`` — ``serving/fleet_plane.py``)
over the stdlib-socket RPC of ``serving/fleet_rpc.py``.

The router process owns NO device work and imports NO jax — this
module is under the ``jax-clean-module`` lint rule (marker above), so
the front door can run on a host with no accelerator stack at all.

Contracts (docs/reliability.md process-death row):

  - **Admission + routing**: least-loaded across healthy planes with
    per-tenant deficit fairness — a tenant's requests spread across
    its planes by dispatch deficit, so one hot tenant cannot pile a
    single plane while others idle. Routing reads each plane's LIVE
    exporter snapshot (``/snapshot.json``) plus the router's own
    outstanding counters.
  - **Fleet-wide accounting**: ``offered == completed + rejected +
    failed`` at the router front door, across process kills — the
    PR-7/PR-11 zero-drop contract extended from thread scope to
    process scope. Every future resolves with a result or a NAMED
    error; nothing is ever silently dropped.
  - **Process watchdog**: a plane that stops heartbeating (snapshot
    scrape + liveness) is declared DEAD: its in-flight requests fail
    LOUDLY at the router (:class:`FleetPlaneDied`), its last-scraped
    latency histogram is folded into the fleet merge (the degraded
    window stays visible), and a replacement process is respawned
    through the ``fleet.plane.spawn`` fault site with paced bounded
    retries inside a per-plane restart budget. Budget exhaustion
    EVICTS the plane loudly; the surviving fleet keeps serving.
  - **Integrity**: plans ship in the zoo's bit-exact split-plane
    encoding and are fingerprint-verified end-to-end on arrival; a
    mismatch QUARANTINES the plane (it heartbeats but refuses every
    request) rather than serving wrong bits.
  - **Fleet p99**: per-plane ``BucketedHistogram`` states merge
    EXACTLY at the router (PR-10's merge property, now cross-process
    over ``/snapshot.json``).
"""

from __future__ import annotations

import json
import logging
import multiprocessing as mp
import os
import queue
import threading
import time
import urllib.request
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from keystone_tpu.obs.metrics import BucketedHistogram
from keystone_tpu.serving.batcher import (
    ServerClosed,
    ServerDegraded,
    ServerOverloaded,
)
from keystone_tpu.utils import faults

from .fleet_plane import PlanShip, plane_main
from .fleet_rpc import RpcClient

__all__ = [
    "FleetClosed",
    "FleetPlaneDied",
    "FleetRouter",
    "FleetSaturated",
    "PlaneQuarantined",
]

logger = logging.getLogger(__name__)


class FleetSaturated(ServerOverloaded):
    """Router admission bound hit — counted ``rejected`` (the named
    shed, same classification as a plane-level overload)."""


class FleetPlaneDied(ServerDegraded):
    """The plane handling (or chosen for) a request died or its RPC
    failed — counted ``failed``, never silently dropped."""


class PlaneQuarantined(ServerDegraded):
    """The plane refused to serve: its shipped plan failed integrity
    verification."""


class FleetClosed(ServerClosed):
    """Submission after (or unresolved at) ``close()``."""


class _Plane:
    """Router-side state for one plane slot. All mutable fields are
    guarded by the router's lock except the RPC client (thread-safe)
    and the atomic-enough heartbeat stamp."""

    def __init__(self, name: str):
        self.name = name
        self.proc: Optional[Any] = None
        self.client: Optional[RpcClient] = None
        self.rpc_port: Optional[int] = None
        self.metrics_port: Optional[int] = None
        self.pid: Optional[int] = None
        self.quarantined: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.healthy = False
        self.evicted = False
        self.outstanding = 0
        self.offered = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.restarts = 0
        self.budget_left = 0
        self.last_heartbeat = 0.0
        self.last_hist_state: Optional[Dict[str, Any]] = None
        self.last_snapshot: Optional[Dict[str, Any]] = None

    def eligible(self) -> bool:
        return self.healthy and not self.evicted \
            and self.quarantined is None


class FleetRouter:
    """N crash-contained serving-plane processes behind one admission
    front door (module docstring). ``ship`` is the split-plane-encoded
    plan every plane boots from (``fleet_plane.encode_plan_ship``).

    Knobs: ``restart_budget`` respawn attempts per plane slot (paced by
    ``spawn_retry_delay_s`` doubling per attempt), ``heartbeat_timeout_s``
    without a successful snapshot scrape (or a dead process) declares a
    plane dead, ``max_outstanding`` bounds router-queued + in-flight
    requests (beyond it submissions shed with :class:`FleetSaturated`).
    """

    def __init__(
        self,
        ship: PlanShip,
        num_planes: int = 2,
        replicas_per_plane: int = 2,
        max_outstanding: int = 1024,
        dispatchers: Optional[int] = None,
        heartbeat_interval_s: float = 0.2,
        heartbeat_timeout_s: float = 5.0,
        restart_budget: int = 2,
        spawn_retry_delay_s: float = 0.05,
        startup_timeout_s: float = 120.0,
        request_timeout_s: float = 30.0,
        plane_cfg: Optional[Dict[str, Any]] = None,
    ):
        if num_planes < 1:
            raise ValueError("num_planes must be >= 1")
        self.ship = ship
        self.num_planes = int(num_planes)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.restart_budget = int(restart_budget)
        self.spawn_retry_delay_s = float(spawn_retry_delay_s)
        self.startup_timeout_s = float(startup_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.max_outstanding = int(max_outstanding)
        self._cfg = dict(plane_cfg or {})
        self._cfg.setdefault("replicas", int(replicas_per_plane))
        self._cfg.setdefault("default_timeout_s", request_timeout_s)

        self._ctx = mp.get_context("spawn")  # jax + fork don't mix
        self._lock = threading.Lock()
        self._closed = False
        self._planes: List[_Plane] = [
            _Plane(f"plane{i}") for i in range(self.num_planes)
        ]
        for p in self._planes:
            p.budget_left = self.restart_budget
        # Front-door books (the fleet invariant's single source of
        # truth): offered at submit, exactly one of completed /
        # rejected / failed at resolution.
        self.offered = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self._inflight = 0
        # Per-tenant deficit fairness: tenant -> plane name -> sends.
        self._sent: Dict[str, Dict[str, int]] = {}
        # Latency histograms of planes that died or were replaced —
        # their last-scraped state stays in the fleet merge so the
        # degraded window's tail is never erased.
        self._retired_hist = BucketedHistogram()

        for p in self._planes:
            self._spawn_plane(p, initial=True)

        self._queue: "queue.Queue[Optional[Tuple]]" = queue.Queue()
        n_disp = dispatchers if dispatchers is not None \
            else 4 * self.num_planes
        self._dispatchers = [
            threading.Thread(target=self._dispatch_loop,
                             name=f"fleet-dispatch-{i}", daemon=True)
            for i in range(int(n_disp))
        ]
        for t in self._dispatchers:
            t.start()
        self._watchdog_stop = threading.Event()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="fleet-watchdog",
            daemon=True,
        )
        self._watchdog.start()

    # -- spawn / respawn ---------------------------------------------------

    def _spawn_once(self, plane: _Plane) -> None:
        """One spawn attempt: fire the fault site, start the process,
        wait for its bootstrap handshake."""
        faults.maybe_fail(faults.SITE_FLEET_PLANE_SPAWN)
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=plane_main,
            args=(plane.name, child_conn, self.ship, self._cfg),
            name=f"keystone-fleet-{plane.name}",
            daemon=True,
        )
        proc.start()
        child_conn.close()
        if not parent_conn.poll(self.startup_timeout_s):
            proc.terminate()
            proc.join(5.0)
            raise OSError(
                f"{plane.name}: no bootstrap handshake within "
                f"{self.startup_timeout_s}s"
            )
        hello = parent_conn.recv()
        parent_conn.close()
        with self._lock:
            plane.proc = proc
            plane.pid = hello["pid"]
            plane.rpc_port = hello["rpc_port"]
            plane.metrics_port = hello["metrics_port"]
            plane.quarantined = hello["quarantined"]
            plane.fingerprint = hello["fingerprint"]
            plane.client = RpcClient("127.0.0.1", hello["rpc_port"])
            plane.healthy = True
            plane.last_heartbeat = time.monotonic()
        if plane.quarantined is not None:
            logger.warning(
                "fleet: %s came up QUARANTINED (%s) — heartbeating but "
                "refusing traffic; wrong bits are never served",
                plane.name, plane.quarantined,
            )

    def _spawn_plane(self, plane: _Plane, initial: bool = False) -> None:
        """Paced bounded respawn inside the plane's restart budget.
        At construction (``initial``) the budget is NOT burned — a
        fleet that cannot boot raises instead. On respawn, every
        attempt (success or failure) burns one budget unit; exhaustion
        evicts the plane LOUDLY and permanently."""
        attempt = 0
        while True:
            if not initial:
                with self._lock:
                    if plane.budget_left <= 0:
                        plane.evicted = True
                        plane.healthy = False
                        logger.warning(
                            "fleet: %s restart budget EXHAUSTED — "
                            "permanently evicted; surviving planes "
                            "keep serving", plane.name,
                        )
                        return
                    plane.budget_left -= 1
            try:
                self._spawn_once(plane)
            except Exception as e:  # noqa: BLE001 — budgeted chaos path
                attempt += 1
                if initial and attempt > 3:
                    raise
                logger.warning(
                    "fleet: spawn attempt %d for %s failed: %r",
                    attempt, plane.name, e,
                )
                time.sleep(
                    self.spawn_retry_delay_s * (2 ** min(attempt - 1, 6))
                )
                continue
            if not initial:
                with self._lock:
                    plane.restarts += 1
            return

    # -- submission / dispatch ---------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None,
               tenant: str = "fleet") -> Future:
        """Route one request; returns a Future resolving to the plane's
        response (or a NAMED error — never a silent drop)."""
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise FleetClosed("fleet is closed")
            self.offered += 1
            if self._inflight >= self.max_outstanding:
                self.rejected += 1
                raise FleetSaturated(
                    f"router outstanding bound {self.max_outstanding} "
                    f"reached"
                )
            if not any(p.eligible() for p in self._planes):
                self.failed += 1
                raise FleetPlaneDied(
                    "no eligible planes (all dead, evicted or "
                    "quarantined)"
                )
            self._inflight += 1
        self._queue.put((fut, tenant, x, deadline_ms,
                         time.monotonic()))
        return fut

    def submit_tenant(self, tenant: str, x,
                      deadline_ms: Optional[float] = None) -> Future:
        """`run_multi_tenant_open_loop`-shaped front door."""
        return self.submit(x, deadline_ms=deadline_ms, tenant=tenant)

    def _pick_plane(self, tenant: str) -> Optional[_Plane]:
        """Least-loaded with per-tenant deficit fairness: among
        eligible planes, minimize (router outstanding, this tenant's
        sends to the plane) lexicographically — the plane with headroom
        wins; ties break toward the plane this tenant has used least,
        spreading each tenant across the fleet by dispatch deficit."""
        with self._lock:
            eligible = [p for p in self._planes if p.eligible()]
            if not eligible:
                return None
            sent = self._sent.setdefault(tenant, {})
            best = min(
                eligible,
                key=lambda p: (p.outstanding, sent.get(p.name, 0)),
            )
            sent[best.name] = sent.get(best.name, 0) + 1
            best.outstanding += 1
            best.offered += 1
            return best

    def _resolve(self, fut: Future, plane: Optional[_Plane],
                 outcome: str, value: Any) -> None:
        """Exactly-once bookkeeping + future resolution."""
        with self._lock:
            self._inflight -= 1
            if outcome == "completed":
                self.completed += 1
            elif outcome == "rejected":
                self.rejected += 1
            else:
                self.failed += 1
            if plane is not None:
                plane.outstanding -= 1
                setattr(plane, outcome, getattr(plane, outcome) + 1)
        if outcome == "completed":
            fut.set_result(value)
        else:
            fut.set_exception(value)

    def _dispatch_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            fut, tenant, x, deadline_ms, t_submit = item
            with self._lock:
                closed = self._closed
            if closed:
                # FIFO: every request queued before close() reaches a
                # dispatcher before its shutdown sentinel does, so the
                # drain is loud and complete by construction.
                self._resolve(fut, None, "failed", FleetClosed(
                    "fleet closed with request queued"
                ))
                continue
            plane = self._pick_plane(tenant)
            if plane is None:
                self._resolve(fut, None, "failed", FleetPlaneDied(
                    "no eligible planes"
                ))
                continue
            # Deadline propagation: the plane sees the REMAINING
            # budget after router queueing.
            remaining_ms = deadline_ms
            if deadline_ms is not None:
                elapsed_ms = (time.monotonic() - t_submit) * 1e3
                remaining_ms = deadline_ms - elapsed_ms
                if remaining_ms <= 0.0:
                    self._resolve(fut, plane, "rejected", FleetSaturated(
                        f"deadline ({deadline_ms:.1f} ms) burned in "
                        f"router queue"
                    ))
                    continue
            timeout_s = (remaining_ms / 1e3 + 5.0
                         if remaining_ms is not None
                         else self.request_timeout_s)
            try:
                resp = plane.client.request(
                    {"op": "submit", "x": x, "deadline_ms": remaining_ms,
                     "tenant": tenant},
                    timeout_s=timeout_s,
                )
            except Exception as e:  # noqa: BLE001 — named, loud
                logger.warning(
                    "fleet: in-flight request to %s FAILED (%r)",
                    plane.name, e,
                )
                self._resolve(fut, plane, "failed", FleetPlaneDied(
                    f"{plane.name}: rpc failed: "
                    f"{type(e).__name__}: {e}"
                ))
                continue
            if resp.get("ok"):
                self._resolve(fut, plane, "completed", resp["y"])
            else:
                err = resp.get("error")
                msg = f"{plane.name}: {resp.get('message', err)}"
                if err == "overloaded":
                    self._resolve(fut, plane, "rejected",
                                  FleetSaturated(msg))
                elif err == "quarantined":
                    self._resolve(fut, plane, "failed",
                                  PlaneQuarantined(msg))
                else:
                    self._resolve(fut, plane, "failed",
                                  FleetPlaneDied(msg))

    # -- watchdog ----------------------------------------------------------

    def _scrape(self, plane: _Plane) -> bool:
        """One snapshot scrape; True on success (heartbeat)."""
        url = (f"http://127.0.0.1:{plane.metrics_port}/snapshot.json")
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                doc = json.loads(r.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 — any scrape failure = no beat
            return False
        section = doc.get("fleet_plane") or {}
        with self._lock:
            plane.last_snapshot = section
            hist = section.get("latency_hist")
            if hist is not None:
                plane.last_hist_state = hist
            # The plane's ADVERTISED fingerprint moves when its own
            # lifecycle controller promotes a canary — the router's
            # attribution must track the live value, not the boot one.
            fp = section.get("fingerprint")
            if fp:
                plane.fingerprint = fp
            plane.last_heartbeat = time.monotonic()
        return True

    def _watchdog_loop(self) -> None:
        while not self._watchdog_stop.wait(self.heartbeat_interval_s):
            for plane in self._planes:
                with self._lock:
                    if self._closed:
                        return
                    if plane.evicted or not plane.healthy:
                        continue
                    proc = plane.proc
                self._scrape(plane)
                dead = (proc is not None and not proc.is_alive())
                with self._lock:
                    beat_age = time.monotonic() - plane.last_heartbeat
                if dead or beat_age > self.heartbeat_timeout_s:
                    self._declare_dead(
                        plane,
                        "process exited" if dead else
                        f"no heartbeat for {beat_age:.1f}s",
                    )

    def _declare_dead(self, plane: _Plane, reason: str) -> None:
        logger.warning(
            "fleet: %s (pid %s) DECLARED DEAD (%s) — failing its "
            "in-flight requests loudly and respawning within budget "
            "(%d left)", plane.name, plane.pid, reason,
            plane.budget_left,
        )
        with self._lock:
            plane.healthy = False
            # Keep the dead plane's tail visible: its last-scraped
            # histogram joins the fleet merge permanently.
            if plane.last_hist_state is not None:
                self._retired_hist.merge_state(plane.last_hist_state)
                plane.last_hist_state = None
            client = plane.client
            plane.client = None
        # Closing the pool wakes any dispatcher blocked on this
        # plane's sockets; each in-flight request fails LOUDLY through
        # its own dispatcher (FleetPlaneDied), never silently.
        if client is not None:
            client.close()
        if plane.proc is not None:
            plane.proc.join(timeout=1.0)
        self._spawn_plane(plane)

    # -- fleet-wide operations ---------------------------------------------

    def offer_canary(self, candidate_ship: PlanShip,
                     timeout_s: float = 120.0) -> Dict[str, Any]:
        """Roll one candidate across the surviving fleet: each eligible
        plane's OWN LifecycleController runs the gate → single-replica
        canary → zero-drop promotion (PR-14 machinery, per process).
        Returns per-plane results."""
        results: Dict[str, Any] = {}
        for plane in self._planes:
            with self._lock:
                ok = plane.eligible()
                client = plane.client
            if not ok or client is None:
                results[plane.name] = {"ok": False,
                                       "error": "ineligible"}
                continue
            try:
                results[plane.name] = client.request(
                    {"op": "offer", "ship": candidate_ship},
                    timeout_s=timeout_s,
                )
            except Exception as e:  # noqa: BLE001 — named, per plane
                results[plane.name] = {
                    "ok": False, "error": "rpc_failed",
                    "message": f"{type(e).__name__}: {e}",
                }
        return results

    def merged_histogram(self) -> BucketedHistogram:
        """The fleet-wide latency distribution: the retired planes'
        last-scraped states + every live plane's latest snapshot,
        merged EXACTLY (counts add — PR-10's property, cross-process).
        """
        merged = BucketedHistogram()
        with self._lock:
            merged.merge_state(self._retired_hist.state_dict())
            states = [p.last_hist_state for p in self._planes
                      if p.last_hist_state is not None]
        for s in states:
            merged.merge_state(s)
        return merged

    def stats(self) -> Dict[str, Any]:
        """Fleet books + per-plane attribution. The dict satisfies
        bench.py's ``_fleet_violations`` audit by construction: every
        ``fleet_p99*`` / ``aggregate_offered*`` claim rides beside a
        numeric ``num_planes`` and per-plane accounting sums."""
        hist = self.merged_histogram()
        snap = hist.stats_snapshot()
        with self._lock:
            planes = {
                p.name: {
                    "pid": p.pid,
                    "healthy": p.healthy,
                    "evicted": p.evicted,
                    "quarantined": p.quarantined,
                    "fingerprint": p.fingerprint,
                    "outstanding": p.outstanding,
                    "offered": p.offered,
                    "completed": p.completed,
                    "rejected": p.rejected,
                    "failed": p.failed,
                    "restarts": p.restarts,
                    "restart_budget_left": p.budget_left,
                }
                for p in self._planes
            }
            return {
                "num_planes": len(self._planes),
                "healthy_planes": sum(
                    1 for p in self._planes if p.eligible()
                ),
                "evicted_planes": [
                    p.name for p in self._planes if p.evicted
                ],
                "quarantined_planes": [
                    p.name for p in self._planes
                    if p.quarantined is not None
                ],
                "restarts_total": sum(
                    p.restarts for p in self._planes
                ),
                "aggregate_offered": self.offered,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                "inflight": self._inflight,
                "fleet_latency_count": snap["count"],
                "fleet_p50_latency_s": snap["p50"],
                "fleet_p99_latency_s": snap["p99"],
                "planes": planes,
            }

    def accounting_ok(self) -> bool:
        """The fleet invariant, checked after a drain: every offered
        request is accounted exactly once."""
        with self._lock:
            return (self._inflight == 0
                    and self.offered == (self.completed + self.rejected
                                         + self.failed))

    def plane_pids(self) -> Dict[str, Optional[int]]:
        with self._lock:
            return {p.name: p.pid for p in self._planes}

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 30.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._watchdog_stop.set()
        self._watchdog.join(timeout)
        # One sentinel per dispatcher; anything still queued ahead of
        # the sentinels is failed LOUDLY by the dispatchers themselves
        # (the closed check in _dispatch_loop) — books stay exact.
        for _ in self._dispatchers:
            self._queue.put(None)
        for t in self._dispatchers:
            t.join(timeout)
        for plane in self._planes:
            client = plane.client
            if client is not None:
                try:
                    client.request({"op": "shutdown"}, timeout_s=5.0)
                except Exception:  # noqa: BLE001 — dying anyway
                    pass
                client.close()
            if plane.proc is not None:
                plane.proc.join(timeout=10.0)
                if plane.proc.is_alive():
                    plane.proc.terminate()
                    plane.proc.join(timeout=5.0)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
