"""Replicated serving plane: N micro-batch replicas behind one
admission-controlled front door (ROADMAP item 3, docs/serving.md).

A single :class:`~keystone_tpu.serving.batcher.MicroBatchServer` is one
worker thread driving one plan — a replica death or a model refresh is a
full outage. This module composes PR 5's reliability ingredients
(per-replica circuit breakers, the worker watchdog, deterministic fault
sites) into the thing the north star actually requires: a serving plane
that keeps meeting its SLO while replicas die and plans swap underneath
live traffic.

  - **One front door.** Submitters call
    :meth:`ReplicatedServer.submit` exactly as they would a single
    server and get the same ``Future`` contract (result, or a NAMED
    error — nothing is ever silently dropped). Admission is decided at
    the front: a request is admitted iff some in-rotation replica
    admits it. The queue is logically one, physically partitioned per
    replica worker — a single shared deque would serialize every worker
    on one lock and put a cross-thread JAX handoff in the hot path;
    partitioning keeps each worker's dispatch loop lock-local while the
    admission decision (and its earliest-deadline-first shedding,
    delegated to the chosen replica's bounded queue) stays global.
  - **Least-loaded routing with per-replica breakers.** The replica
    with the fewest outstanding requests wins. A replica whose breaker
    is OPEN is removed from rotation entirely; when its cooldown
    elapses (state ``half_open``) the router deliberately hands it the
    next request as the recovery probe — without that, healthy replicas
    would absorb all traffic and an opened breaker could never re-close.
    If the chosen replica sheds or fails fast, the router FAILS OVER to
    the next candidate; only when every in-rotation replica rejects
    does the submitter see an error (``ServerOverloaded`` if anything
    shed on load, else ``ServerDegraded``).
  - **Replica watchdog + bounded restarts.** A background watchdog
    (numpy/threading only — the jax-off-thread discipline) notices a
    dead replica worker and respawns it from the SAME exported plan.
    Each spawn attempt runs the ``serving.replica.spawn`` fault site
    and burns one unit of the per-replica ``restart_budget``; past the
    budget the replica is PERMANENTLY EVICTED — loudly: a warning log
    and ``stats()["degraded"]``/``evicted_replicas`` flip, because a
    plane quietly running at N-1 capacity is how the next death becomes
    an outage.
  - **Atomic zero-drop hot-swap.** :meth:`swap_plan` replaces the plan
    under live traffic, one replica at a time: the new plan AOT-warms
    at the same padding buckets *before* any capacity is taken out,
    then each replica in turn leaves rotation, drains its in-flight
    work to zero (queued requests finish — they are never failed), is
    closed, and re-enters rotation wrapped around the new plan. Each
    replica serves EXACTLY ONE plan version for the lifetime of its
    worker, every response's future carries that version's fingerprint
    (``fut.plan_fingerprint``), and no batch ever mixes versions — the
    bit-identity contract of docs/reliability.md is stated per
    fingerprint.
  - **Zero-drop elasticity.** :meth:`add_replica` and
    :meth:`remove_replica` are the first-class capacity primitives the
    SLO-closed-loop autoscaler (``serving/autoscale.py``) drives.
    Addition warms the new worker's plan BEFORE it enters rotation
    (spawn attempts run the ``serving.autoscale.spawn`` fault site with
    bounded retries inside the restart budget — a chaos kill mid-spawn
    is absorbed, never a dropped request). Removal reuses the hot-swap
    drain protocol: the victim leaves rotation, drains its admitted
    work to zero on the reservation counters, closes on an empty queue,
    and rotation membership updates atomically — and removal never
    picks the half-open-probe replica (evicting the probe would leave
    its breaker's recovery unobservable). At every instant
    ``offered == completed + rejected + failed``.
  - **Brownout ladder.** The wall past ``max_replicas``: when scale-up
    is exhausted and burn keeps rising, admission degrades in NAMED,
    REVERSIBLE steps (:data:`BROWNOUT_STEPS`, entered/exited strictly
    LIFO): ``widen_deadlines`` (coalescing windows stretch by
    ``brownout_wait_factor`` — bigger batches, more throughput per
    dispatch at a latency cost), then ``aggressive_shed`` (the EDF shed
    depth shrinks by ``brownout_shed_factor`` — load is refused
    earlier, explicitly), then ``reject_admissions`` (the front door
    fast-fails every new request with :class:`ServerOverloaded`).
    Every step keeps the zero-drop accounting: a browned-out rejection
    is a NAMED error and a counted bad SLI event, never a silent drop.
  - **Chaos-provable.** ``serving.replica.execute`` is a loop-level
    fault site on replica workers (outside the per-batch error guard —
    an injected error there kills the whole worker, watchdog
    territory); ``serving.replica.spawn`` fires per respawn attempt and
    ``serving.autoscale.spawn`` per scale-up spawn attempt.
    tests/test_chaos_replicas.py drives kill-mid-Poisson-storm and
    swap-under-load through them; tests/test_chaos_autoscale.py drives
    kill-mid-scale-up and the spike→recover→quiesce closed loop.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from keystone_tpu import obs
from keystone_tpu.obs.metrics import METRIC_SERVING_LATENCY_S
from keystone_tpu.utils import faults

from .batcher import (
    MicroBatchServer,
    ServerClosed,
    ServerDegraded,
    ServerOverloaded,
)
from .export import ExportedPlan

__all__ = ["BROWNOUT_STEPS", "ReplicatedServer"]

logger = logging.getLogger("keystone_tpu.serving")

# Breaker states eligible for normal least-loaded routing.
_ROUTABLE = ("closed", "disabled")

# The overload brownout ladder, in ENTRY order (exit is strictly LIFO):
# each step is a named, reversible admission degradation the autoscaler
# climbs when scale-up is exhausted past max_replicas (module docstring).
BROWNOUT_STEPS = ("widen_deadlines", "aggressive_shed", "reject_admissions")


class _ReplicaBatchServer(MicroBatchServer):
    """A MicroBatchServer whose worker loop runs the
    ``serving.replica.execute`` fault site OUTSIDE the per-batch error
    guard: an injected error here propagates to the worker loop's
    watchdog-of-last-resort and kills the whole replica (every in-flight
    and queued future fails loudly with ServerDegraded) — modeling
    whole-replica death rather than one bad batch. The per-batch
    ``serving.execute`` site inside the guard still models plan/batch
    failures."""

    def _execute(self, batch) -> None:
        faults.maybe_fail(faults.SITE_REPLICA_EXECUTE)
        super()._execute(batch)


class _Replica:
    """One slot in the rotation: the current server generation, the
    plan it wraps, and the lifecycle counters. ``outstanding`` counts
    futures submitted through the front door and not yet resolved — the
    load signal routing sorts by, and the drain signal hot-swap waits
    on (mutated only under the ReplicatedServer lock / done-callbacks)."""

    __slots__ = (
        "index", "plan", "server", "outstanding", "restarts",
        "evicted", "out_of_rotation", "busy",
    )

    def __init__(self, index: int, plan: ExportedPlan,
                 server: MicroBatchServer):
        self.index = index
        self.plan = plan
        self.server = server
        self.outstanding = 0
        self.restarts = 0
        self.evicted = False
        self.out_of_rotation = False
        # Lifecycle ownership token (under the plane lock): exactly one
        # actor — the watchdog's restart or a swap — may be replacing
        # this replica's server generation at a time; without it a death
        # DURING a swap could have both spawn a server and leak one.
        self.busy = False


class ReplicatedServer:
    """Front N micro-batch replicas behind one admission-controlled
    submit path (module docstring for the full design).

    ``plans`` is one :class:`ExportedPlan` shared by every replica (the
    N-workers-on-one-device shape — compiled executables are immutable
    after export, so sharing is read-only), a sequence of N plans (one
    copy per device), or a ``factory(replica_index) -> ExportedPlan``.
    All plans must serve the same request signature (item shape/dtype).

    Knobs beyond the per-replica ``MicroBatchServer`` surface:

      - ``num_replicas``: rotation size (ignored when ``plans`` is a
        sequence — its length wins).
      - ``restart_budget``: spawn attempts per replica before permanent
        eviction (0 = never restart, first death evicts).
      - ``watchdog_interval_s``: dead-replica detection cadence — the
        floor on restart latency, and therefore on how fast p99
        recovers after a kill.
      - ``drain_timeout_s``: hot-swap's bound on waiting for one
        replica's in-flight work; on timeout the replica re-enters
        rotation on its OLD plan and the swap raises (zero-drop is
        preserved either way).
      - ``slo``: an :class:`~keystone_tpu.obs.slo.SLOTracker` fed at
        the FRONT DOOR (one outcome per admitted/rejected request, at
        future resolution) — the verdict survives replica restarts and
        swaps exactly like the front-door counters do.
    """

    def __init__(
        self,
        plans: Union[ExportedPlan, Sequence[ExportedPlan],
                     Callable[[int], ExportedPlan]],
        num_replicas: int = 2,
        max_batch: Optional[int] = None,
        max_wait_ms: float = 2.0,
        max_queue_depth: int = 1024,
        span_log_len: int = 4096,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 1.0,
        restart_budget: int = 3,
        watchdog_interval_s: float = 0.05,
        drain_timeout_s: float = 30.0,
        brownout_wait_factor: float = 4.0,
        brownout_shed_factor: float = 0.25,
        slo=None,
    ):
        factory, n = self._plan_factory(plans, num_replicas)
        if n < 1:
            raise ValueError(f"num_replicas must be >= 1, got {n}")
        if restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        if brownout_wait_factor < 1.0:
            raise ValueError("brownout_wait_factor must be >= 1 (widening)")
        if not 0.0 < brownout_shed_factor <= 1.0:
            raise ValueError("brownout_shed_factor must be in (0, 1]")
        self.num_replicas = n
        self.restart_budget = int(restart_budget)
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.brownout_wait_factor = float(brownout_wait_factor)
        self.brownout_shed_factor = float(brownout_shed_factor)
        self._server_kwargs = dict(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_queue_depth=max_queue_depth, span_log_len=span_log_len,
            breaker_threshold=breaker_threshold,
            breaker_reset_s=breaker_reset_s,
        )
        # Active brownout steps, in entry order (exit pops the tail —
        # LIFO). Mutated only under _lock.
        self._brownout: List[str] = []

        self._lock = threading.Lock()
        self._swap_lock = threading.Lock()  # serializes swap_plan calls
        self._closed = False
        self._next_index = n  # elasticity: added replicas get fresh indices
        self._replicas: List[_Replica] = []
        self._item_shape: Optional[tuple] = None
        self._dtype = None
        try:
            for i in range(n):
                plan = factory(i)
                self._check_signature(plan)
                self._replicas.append(
                    _Replica(i, plan, self._build_server(i, plan))
                )
        except BaseException:
            # Replica servers start their worker threads at build; a
            # half-constructed plane must not leak the ones already
            # running when a later plan fails validation.
            for rep in self._replicas:
                rep.server.close(timeout=1.0)
            raise

        # Front-door accounting (all under _lock). Counters folded in
        # from retired server generations live in _retired so restarts
        # and swaps never lose history. End-to-end latency lives in the
        # plane's own registry as a MERGEABLE bucketed histogram (ISSUE
        # 10): whole-run percentiles at O(1) memory, and the live
        # exporter renders the registry directly.
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.degraded_rejected = 0
        self.restarts_total = 0
        self.swaps_completed = 0
        self.replicas_added = 0
        self.replicas_removed = 0
        self.brownout_rejected = 0
        # First-completion clock per plan fingerprint (monotonic stamp
        # of the first successfully served response under each plan
        # version) — the serving-side half of the lifecycle plane's
        # model-staleness measurement (shard arrival -> first response
        # under the covering fingerprint). Stamped in the done-callback,
        # so it is exact, not a poll-granularity estimate.
        self._first_completed: Dict[str, float] = {}
        self.metrics = obs.MetricsRegistry()
        self._latencies = self.metrics.bucketed_histogram(
            METRIC_SERVING_LATENCY_S
        )
        self._slo = slo
        self._retired: Dict[str, int] = {
            "completed": 0, "rejected": 0, "failed": 0, "breaker_opens": 0,
        }

        self._stop = threading.Event()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop,
            name="keystone-serving-replica-watchdog", daemon=True,
        )
        self._watchdog.start()

    # -- construction helpers ---------------------------------------------

    @staticmethod
    def _plan_factory(plans, num_replicas):
        if isinstance(plans, ExportedPlan):
            return (lambda i: plans), int(num_replicas)
        if callable(plans):
            return plans, int(num_replicas)
        seq = list(plans)
        if not seq:
            raise ValueError("plans sequence is empty")
        return (lambda i: seq[i]), len(seq)

    def _check_signature(self, plan: ExportedPlan) -> None:
        """Every replica must serve the same request signature — routing
        is load-based, so any request must be servable by any replica."""
        if self._item_shape is None:
            self._item_shape = plan.item_shape
            self._dtype = plan.dtype
            return
        if plan.item_shape != self._item_shape or plan.dtype != self._dtype:
            raise ValueError(
                f"replica plan signature {plan.item_shape}/{plan.dtype} != "
                f"plane signature {self._item_shape}/{self._dtype} — every "
                "replica must serve the same request shape and dtype"
            )

    def _effective_server_kwargs(self) -> Dict[str, Any]:
        """The base server kwargs with the ACTIVE brownout overrides
        applied — so a worker generation spawned mid-brownout (watchdog
        restart, swap, scale-up) admits under the same degraded policy
        as the live generations (mutating only live servers would let a
        restart silently undo a brownout step)."""
        kw = dict(self._server_kwargs)
        with self._lock:
            steps = list(self._brownout)
        if "widen_deadlines" in steps:
            kw["max_wait_ms"] = float(kw["max_wait_ms"]) \
                * self.brownout_wait_factor
        if "aggressive_shed" in steps:
            kw["max_queue_depth"] = max(
                1, int(kw["max_queue_depth"] * self.brownout_shed_factor)
            )
        return kw

    def _build_server(self, index: int, plan: ExportedPlan):
        return _ReplicaBatchServer(
            plan, replica_index=index, **self._effective_server_kwargs()
        )

    # -- submit side -------------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Route one request to the best replica; returns its Future,
        annotated with ``replica_index`` and ``plan_fingerprint`` (the
        version of the plan that will serve it — fixed at admission,
        because a replica's worker serves exactly one plan version for
        its whole lifetime).

        Raises :class:`ServerClosed` after close(); fails over across
        replicas on shed/degraded rejections and raises only when EVERY
        in-rotation replica rejected (:class:`ServerOverloaded` if any
        rejection was load shedding, else :class:`ServerDegraded`)."""
        t_sub = time.perf_counter()
        x = np.asarray(x)
        tried: set = set()
        saw_overload = False
        last_exc: Optional[BaseException] = None
        with self._lock:
            if self._closed:
                raise ServerClosed("submit() after close()")
            # Brownout ladder top: the front door fast-fails every new
            # admission with the NAMED overload error (counted, SLO-fed
            # below — a browned-out reject is never a silent drop).
            browned_out = "reject_admissions" in self._brownout
            if browned_out:
                self.rejected += 1
                self.brownout_rejected += 1
        if browned_out:
            if self._slo is not None:
                self._slo.observe(ok=False)
            raise ServerOverloaded(
                "brownout ladder at reject_admissions: scale-up is "
                "exhausted and admission is fast-failing new requests "
                "until load subsides (docs/serving.md brownout contract)"
            )
        while True:
            with self._lock:
                if self._closed:
                    raise ServerClosed("submit() after close()")
                rep = self._pick_locked(tried)
                if rep is None:
                    break
                # Reserve BEFORE the replica sees the request: hot-swap
                # drains on this counter, and a request queued before
                # its reservation is visible could be closed mid-swap.
                rep.outstanding += 1
            try:
                fut = rep.server.submit(x, deadline_ms)
            except (ServerOverloaded, ServerDegraded, ServerClosed) as e:
                with self._lock:
                    rep.outstanding -= 1
                saw_overload = saw_overload or isinstance(e, ServerOverloaded)
                last_exc = e
                tried.add(rep.index)
                continue
            except BaseException:
                # Anything else (e.g. a malformed deadline) is the
                # caller's error, not a failover signal — but the
                # reservation MUST still be released, or this replica
                # reads permanently loaded and every later swap drain
                # of it times out.
                with self._lock:
                    rep.outstanding -= 1
                raise
            fut.replica_index = rep.index
            fut.plan_fingerprint = rep.server.plan.fingerprint
            fut.add_done_callback(self._done_callback(rep, t_sub))
            return fut
        with self._lock:
            if saw_overload:
                self.rejected += 1
            else:
                self.degraded_rejected += 1
        if self._slo is not None:
            # A request EVERY replica rejected is a front-door bad event
            # — the degraded window spends error budget even though no
            # replica ever queued it.
            self._slo.observe(ok=False)
        if saw_overload:
            raise ServerOverloaded(
                f"every in-rotation replica shed this request "
                f"(last: {last_exc})"
            )
        raise ServerDegraded(
            f"no replica available: all {self.num_replicas} replicas are "
            f"open-breaker, restarting, evicted, or dead (last: {last_exc})"
        )

    def _pick_locked(self, tried: set) -> Optional[_Replica]:
        """Routing policy (under _lock): a probe-ready half-open replica
        first (it needs the next request as its recovery probe), else
        the least-loaded replica whose breaker admits traffic. A
        half-open replica whose probe is already IN FLIGHT is skipped
        outright — its server fails every further submit fast, so
        offering it traffic would only buy a reject/failover round-trip
        per request for the whole probe-execution window."""
        candidates = [
            r for r in self._replicas
            if not r.evicted and not r.out_of_rotation
            and r.index not in tried
        ]
        probe_ready = None
        routable = []
        for r in candidates:
            state, probe_free = r.server.routing_state
            if state == "half_open":
                if probe_free:
                    probe_ready = probe_ready or r
            elif state in _ROUTABLE:
                routable.append(r)
        if probe_ready is not None:
            return probe_ready
        if not routable:
            return None
        return min(routable, key=lambda r: (r.outstanding, r.index))

    def _done_callback(self, rep: _Replica, t_sub: float):
        def _cb(fut: Future) -> None:
            t_done = time.perf_counter()
            try:
                exc = fut.exception()
            except BaseException:  # noqa: BLE001 — client cancelled
                with self._lock:
                    rep.outstanding -= 1
                return
            lat = t_done - t_sub
            fp = getattr(fut, "plan_fingerprint", None)
            with self._lock:
                rep.outstanding -= 1
                if exc is None:
                    self.completed += 1
                    self._latencies.observe(lat)
                    if fp is not None and fp not in self._first_completed:
                        self._first_completed[fp] = time.monotonic()
                        # Bounded: one entry per plan version EVER
                        # served would grow forever under a continuous
                        # trainer; the staleness consumer settles each
                        # fingerprint within one publication cycle, so
                        # retiring the oldest entries is safe.
                        while len(self._first_completed) > 256:
                            self._first_completed.pop(
                                next(iter(self._first_completed))
                            )
                elif isinstance(exc, ServerOverloaded):
                    self.rejected += 1
                else:
                    self.failed += 1
            # SLO feed OUTSIDE the plane lock (a transition may dump the
            # flight record — rendering under the routing lock would
            # stall every submit behind a postmortem).
            if self._slo is not None:
                if exc is None:
                    self._slo.observe(latency_s=lat, ok=True)
                else:
                    self._slo.observe(ok=False)
        return _cb

    # -- watchdog / restart ------------------------------------------------

    def _watchdog_loop(self) -> None:
        while not self._stop.wait(self.watchdog_interval_s):
            self._sweep_dead_replicas()

    def _sweep_dead_replicas(self) -> None:
        # Snapshot: remove_replica() mutates membership concurrently,
        # and iterating the live list could skip a neighbour mid-sweep.
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            with self._lock:
                if rep not in self._replicas:  # removed while sweeping
                    continue
                if self._closed:
                    return
                if rep.evicted or rep.out_of_rotation or rep.busy:
                    continue
                if not self._server_dead_locked(rep.server):
                    continue
                rep.busy = True
                rep.out_of_rotation = True
            try:
                self._restart(rep)
            finally:
                with self._lock:
                    rep.busy = False

    @staticmethod
    def _server_dead_locked(server: MicroBatchServer) -> bool:
        return server._worker_dead or not server.is_alive

    def _restart(self, rep: _Replica) -> None:
        """Replace a dead replica's server generation from its exported
        plan, within the restart budget; past it, evict permanently —
        and loudly."""
        self._retire_server(rep.server)
        rep.server.close(timeout=1.0)  # dead worker: join is immediate
        if self._try_spawn(rep, rep.plan):
            with self._lock:
                rep.out_of_rotation = False
            logger.warning(
                "serving replica %d worker died; restarted (%d/%d of the "
                "restart budget used)", rep.index, rep.restarts,
                self.restart_budget,
            )

    def _spawn_backoff_interrupted(self, attempt: int) -> bool:
        """Paced spawn-retry backoff shared by the watchdog-restart,
        swap, and scale-up paths: a transient blip (fd exhaustion, a
        briefly busy device) must not burn a whole spawn budget in
        microseconds. Bounded exponential; returns True when close()
        cut the wait short (the caller must abandon the spawn)."""
        return self._stop.wait(min(0.05 * (2 ** (attempt - 1)), 1.0))

    def _try_spawn(self, rep: _Replica, plan: ExportedPlan,
                   count_restart: bool = True) -> bool:
        """Spawn attempts through the ``serving.replica.spawn`` fault
        site. Death restarts (``count_restart=True``) burn the
        per-replica lifetime ``restart_budget``; planned swap spawns
        track their own bounded attempts instead — a healthy plan
        refresh must not eat the budget reserved for real deaths.
        Returns True on success; False means the replica was
        permanently evicted."""
        swap_attempts = 0
        while True:
            with self._lock:
                if self._closed:
                    return False
                if count_restart:
                    if rep.restarts >= self.restart_budget:
                        rep.evicted = True
                        rep.out_of_rotation = True
                        break
                    rep.restarts += 1
                    self.restarts_total += 1
                else:
                    # A swap gets at least one attempt even at budget 0.
                    if swap_attempts >= max(1, self.restart_budget):
                        rep.evicted = True
                        rep.out_of_rotation = True
                        break
                    swap_attempts += 1
            try:
                faults.maybe_fail(faults.SITE_REPLICA_SPAWN)
                server = self._build_server(rep.index, plan)
            except BaseException as e:  # noqa: BLE001 — budget-bounded
                attempt = rep.restarts if count_restart else swap_attempts
                logger.warning(
                    "serving replica %d spawn attempt %d failed: %r",
                    rep.index, attempt, e,
                )
                if self._spawn_backoff_interrupted(attempt):
                    return False
                continue
            with self._lock:
                closed = self._closed
                if not closed:
                    rep.server = server
                    rep.plan = plan
            if closed:
                # close() ran while we were building: installing now
                # would leak a worker thread close() already iterated
                # past. Tear the fresh generation down instead.
                server.close(timeout=1.0)
                return False
            return True
        logger.warning(
            "serving replica %d PERMANENTLY EVICTED: restart budget "
            "(%d) exhausted — the plane is degraded to %d replicas",
            rep.index, self.restart_budget,
            sum(1 for r in self._replicas if not r.evicted),
        )
        # Watchdog eviction is a postmortem moment: dump the flight
        # record (recent spans, breaker events, in-flight work) beside
        # the eviction so the degradation has a causal trail (ISSUE 9).
        obs.flight.dump_flight_record(
            f"serving replica {rep.index} permanently evicted "
            f"(restart budget {self.restart_budget} exhausted)",
            log=logger,
        )
        return False

    # -- hot swap ----------------------------------------------------------

    def swap_plan(
        self,
        new: Union[ExportedPlan, Sequence[ExportedPlan],
                   Callable[[int], ExportedPlan], Any],
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Atomically hot-swap every replica onto a new plan version
        under live traffic, with ZERO dropped requests.

        ``new`` is an :class:`ExportedPlan` (shared), a sequence /
        ``factory(index)`` of per-replica plans, or a
        ``FittedPipeline`` — the latter is exported here with the SAME
        request signature, max_batch, and padding buckets as the
        current plan, so the drain protocol below holds by construction.

        Protocol, per replica in turn (rolling — capacity never drops
        by more than one replica):

          1. The new plan AOT-warms at the same padding buckets
             (:meth:`ExportedPlan.warm` — a no-op for default exports)
             BEFORE any capacity leaves rotation.
          2. The replica leaves rotation: no new admissions.
          3. Drain: every request already admitted to it completes (the
             old plan finishes its in-flight batches; queued requests
             are served, never failed).
          4. The old server closes on an empty queue; a NEW worker
             generation spawns around the new plan and re-enters
             rotation.

        Each worker generation serves exactly one plan version, so no
        batch ever mixes versions and every response's
        ``plan_fingerprint`` names the version that produced it —
        bit-identical to that version's offline apply
        (docs/reliability.md). Returns a per-replica swap report.
        """
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        with self._swap_lock:
            factory = self._resolve_swap_plans(new)
            report: List[Dict[str, Any]] = []
            with self._lock:
                reps = list(self._replicas)  # membership may shrink mid-swap
            for rep in reps:
                with self._lock:
                    removed = rep not in self._replicas
                if removed:
                    report.append({
                        "replica": rep.index, "swapped": False,
                        "reason": "removed",
                    })
                    continue
                if rep.evicted:
                    report.append({
                        "replica": rep.index, "swapped": False,
                        "reason": "evicted",
                    })
                    continue
                report.append(self._swap_one(rep, factory(rep.index),
                                             timeout))
            with self._lock:
                self.swaps_completed += 1
            return {"replicas": report}

    def _swap_one(self, rep: _Replica, new_plan: ExportedPlan,
                  timeout: float) -> Dict[str, Any]:
        """The per-replica swap protocol (swap_plan docstring steps 1-4):
        warm, take lifecycle ownership, drain to zero, close the old
        generation, spawn the new one. Caller holds the SWAP lock.
        Returns the replica's swap-report dict."""
        self._check_signature(new_plan)
        new_plan.warm()  # warm BEFORE taking capacity out
        # Take lifecycle ownership: wait out a watchdog restart
        # already replacing this replica's server generation.
        own_deadline = time.perf_counter() + timeout
        while True:
            with self._lock:
                if self._closed:
                    raise ServerClosed("swap_plan() after close()")
                if rep.evicted:
                    break
                if not rep.busy:
                    rep.busy = True
                    rep.out_of_rotation = True
                    break
            if time.perf_counter() >= own_deadline:
                raise TimeoutError(
                    f"replica {rep.index} is mid-restart and did "
                    f"not settle within {timeout:.3g}s"
                )
            time.sleep(0.005)
        if rep.evicted:  # evicted while we waited
            return {
                "replica": rep.index, "swapped": False,
                "reason": "evicted",
            }
        try:
            try:
                t0 = time.perf_counter()
                self._drain(rep, timeout)
                drain_s = time.perf_counter() - t0
            except BaseException:
                with self._lock:  # zero-drop: old plan keeps serving
                    rep.out_of_rotation = False
                raise
            old_fp = rep.server.plan.fingerprint
            self._retire_server(rep.server)
            rep.server.close()
            if not self._try_spawn(rep, new_plan, count_restart=False):
                return {
                    "replica": rep.index, "swapped": False,
                    "reason": "spawn failed; replica evicted",
                    "old_fingerprint": old_fp,
                }
            with self._lock:
                rep.out_of_rotation = False
            return {
                "replica": rep.index, "swapped": True,
                "old_fingerprint": old_fp,
                "new_fingerprint": new_plan.fingerprint,
                "drain_s": round(drain_s, 6),
            }
        finally:
            with self._lock:
                rep.busy = False

    def swap_replica_plan(
        self,
        index: int,
        new: Union[ExportedPlan, Any],
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Hot-swap ONE replica onto a new plan version — the canary
        primitive the lifecycle controller drives: a passing candidate
        is swapped into a single replica first, compared against the
        incumbent replicas over a sustain window, then promoted
        (:meth:`swap_plan`) or swapped back. Same zero-drop drain
        protocol as the full rollout, per replica; the plane serves
        MIXED fingerprints while a canary is live (each worker
        generation still serves exactly one version — no mixed batch
        ever exists, and every response still names its version).

        ``new`` is an :class:`ExportedPlan` or a ``FittedPipeline``
        (exported at the plane's signature/buckets). Raises
        :class:`ValueError` for an unknown/evicted index; serialized
        against :meth:`swap_plan` and elasticity on the swap lock."""
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        if isinstance(new, (list, tuple)):
            raise TypeError(
                "swap_replica_plan swaps ONE replica — pass a single "
                "ExportedPlan or FittedPipeline, not a sequence"
            )
        with self._swap_lock:
            plan = self._resolve_swap_plans(new)(index)
            with self._lock:
                rep = next(
                    (r for r in self._replicas
                     if r.index == index and not r.evicted), None,
                )
            if rep is None:
                raise ValueError(
                    f"swap_replica_plan: no live replica with index "
                    f"{index}"
                )
            return self._swap_one(rep, plan, timeout)

    def _resolve_swap_plans(self, new) -> Callable[[int], ExportedPlan]:
        # A freshly fitted pipeline: export with the current signature so
        # the new plan warms at the same buckets the plane already runs.
        # (Checked FIRST — FittedPipeline is itself callable, and the
        # factory branch would otherwise apply it to the replica index.)
        from keystone_tpu.workflow.pipeline import FittedPipeline

        if isinstance(new, FittedPipeline):
            from .export import export_plan

            cur = self._replicas[0].plan
            example = np.zeros(self._item_shape, np.dtype(self._dtype))
            plan = export_plan(
                new, example, max_batch=cur.max_batch, buckets=cur.buckets,
            )
            return lambda i: plan
        if isinstance(new, ExportedPlan):
            return lambda i: new
        if isinstance(new, (list, tuple)):
            seq = list(new)
            # Replica indices are not dense once elasticity has
            # added/removed workers (fresh indices beyond the
            # construction range), so a per-replica sequence maps by
            # ROTATION POSITION over the live membership — a raw
            # ``seq[index]`` would drop one device-pinned plan and
            # double-assign another without any error. Membership
            # cannot change under us: swap_plan holds the swap lock and
            # add_replica serializes on it.
            with self._lock:
                live = sorted(
                    (r.index for r in self._replicas if not r.evicted)
                )
            if len(seq) != len(live):
                raise ValueError(
                    f"swap_plan got {len(seq)} plans for "
                    f"{len(live)} replicas (live membership)"
                )
            mapping = dict(zip(live, seq))
            return lambda i: mapping[i]
        if callable(new):
            return new
        raise TypeError(
            f"swap_plan takes an ExportedPlan, a sequence/factory of "
            f"them, or a FittedPipeline (got {type(new).__name__})"
        )

    def _drain(self, rep: _Replica, timeout: float) -> None:
        """Wait until every request admitted to ``rep`` has resolved
        (the batcher guarantees every future resolves — results, plan
        errors, watchdog failures — so drain always terminates unless
        the replica is genuinely wedged past ``timeout``)."""
        deadline = time.perf_counter() + timeout
        while True:
            with self._lock:
                if rep.outstanding == 0:
                    return
            if time.perf_counter() >= deadline:
                raise TimeoutError(
                    f"replica {rep.index} failed to drain within "
                    f"{timeout:.3g}s ({rep.outstanding} outstanding); "
                    "it re-enters rotation on its OLD plan"
                )
            time.sleep(0.001)

    # -- elasticity (the autoscaler's capacity primitives) -----------------

    def add_replica(self) -> int:
        """Grow rotation by one replica, ZERO-DROP: the new worker's
        plan is warmed at the plane's padding buckets BEFORE the replica
        enters rotation (no cold-compile request ever lands on it), and
        membership updates atomically under the plane lock. The plan is
        cloned from the first live replica, so a scale-up after a
        hot-swap serves the swapped version.

        Spawn attempts run the ``serving.autoscale.spawn`` fault site
        with bounded, paced retries inside the restart budget — a chaos
        kill mid-spawn is ABSORBED (the next attempt succeeds) rather
        than dropped or leaked. Raises :class:`ServerDegraded` when the
        budget is exhausted (the plane keeps serving at its current
        size). Returns the new replica's index.

        Serialized against :meth:`swap_plan` (the swap lock): a replica
        added mid-rollout would be invisible to the swap's membership
        snapshot and leave the plane permanently serving mixed plan
        versions."""
        with self._swap_lock:
            return self._add_replica_locked_swap()

    def _add_replica_locked_swap(self) -> int:
        with self._lock:
            if self._closed:
                raise ServerClosed("add_replica() after close()")
            # Donor preference: an IN-ROTATION replica (under the swap
            # lock the only out-of-rotation/busy members are mid-restart
            # — their plan is current too, but rotation members are the
            # unambiguous source of the live version).
            live = [r for r in self._replicas if not r.evicted]
            donor = next(
                (r for r in live if not r.out_of_rotation and not r.busy),
                live[0] if live else None,
            )
            if donor is None:
                raise ServerDegraded(
                    "add_replica: every replica is evicted — no live "
                    "plan to clone"
                )
            plan = donor.plan
            index = self._next_index
            self._next_index += 1
        plan.warm()  # warm BEFORE rotation entry (a no-op when compiled)
        attempts = 0
        budget = max(1, self.restart_budget)
        while True:
            attempts += 1
            try:
                faults.maybe_fail(faults.SITE_AUTOSCALE_SPAWN)
                server = self._build_server(index, plan)
                break
            except BaseException as e:  # noqa: BLE001 — budget-bounded
                logger.warning(
                    "autoscale: replica %d spawn attempt %d failed: %r",
                    index, attempts, e,
                )
                if attempts >= budget:
                    raise ServerDegraded(
                        f"add_replica: spawn failed {attempts} time(s) "
                        f"(restart budget {budget}): {e!r}"
                    ) from e
                if self._spawn_backoff_interrupted(attempts):
                    raise ServerClosed("add_replica() during close()")
        rep = _Replica(index, plan, server)
        with self._lock:
            closed = self._closed
            if not closed:
                self._replicas.append(rep)
                self.num_replicas += 1
                self.replicas_added += 1
        if closed:
            server.close(timeout=1.0)
            raise ServerClosed("add_replica() during close()")
        return index

    def remove_replica(
        self, drain_timeout_s: Optional[float] = None
    ) -> int:
        """Shrink rotation by one replica, ZERO-DROP, via the hot-swap
        drain protocol: the victim leaves rotation (no new admissions),
        every request already admitted to it completes (reservation
        ordering — a drain can never close over an invisible in-flight),
        the server closes on an empty queue, and membership updates
        atomically.

        Victim selection: the least-loaded in-rotation replica, and
        NEVER the half-open-probe replica — its breaker is mid-recovery
        and evicting it would leave the probe outcome unobservable
        (highest index wins ties, so elastic scale-down preferentially
        retires the most recently added capacity). Raises
        :class:`ValueError` at one live replica (the plane never scales
        to zero) and :class:`TimeoutError` if the victim fails to drain
        — in which case it re-enters rotation and nothing was dropped.
        Returns the removed replica's index.

        Serialized against :meth:`swap_plan` (the swap lock), like
        :meth:`add_replica`: a removal mid-rollout could hand the
        swap's ownership wait an already-retired replica — its counters
        would fold into the plane history twice and the swap would
        respawn a worker no membership list tracks."""
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        with self._swap_lock:
            return self._remove_replica_locked_swap(timeout)

    def _remove_replica_locked_swap(self, timeout: float) -> int:
        with self._lock:
            if self._closed:
                raise ServerClosed("remove_replica() after close()")
            live = [r for r in self._replicas if not r.evicted]
            if len(live) <= 1:
                raise ValueError(
                    "remove_replica: refusing to remove the last live "
                    "replica"
                )
            candidates = []
            for r in live:
                if r.out_of_rotation or r.busy:
                    continue
                state, _ = r.server.routing_state
                if state == "half_open":
                    continue  # never the probe replica
                candidates.append(r)
            if not candidates:
                raise ServerDegraded(
                    "remove_replica: no removable replica (all are "
                    "mid-restart, mid-swap, or half-open probes)"
                )
            victim = min(
                candidates, key=lambda r: (r.outstanding, -r.index)
            )
            victim.busy = True
            victim.out_of_rotation = True
        try:
            self._drain(victim, timeout)
        except BaseException:
            with self._lock:  # zero-drop: victim resumes serving
                victim.out_of_rotation = False
                victim.busy = False
            raise
        self._retire_server(victim.server)
        victim.server.close()
        with self._lock:
            if victim in self._replicas:
                self._replicas.remove(victim)
                self.num_replicas -= 1
            self.replicas_removed += 1
            victim.busy = False
        return victim.index

    # -- brownout ladder ---------------------------------------------------

    @property
    def brownout_level(self) -> int:
        with self._lock:
            return len(self._brownout)

    @property
    def brownout_steps(self) -> "tuple[str, ...]":
        """Active brownout steps in entry order (exit pops the tail)."""
        with self._lock:
            return tuple(self._brownout)

    def enter_brownout_step(self) -> Optional[str]:
        """Climb one rung of :data:`BROWNOUT_STEPS`; returns the step
        entered, or None at the ladder top. Effects apply to every live
        worker generation immediately and to every generation spawned
        while the step is active (``_effective_server_kwargs``)."""
        with self._lock:
            if self._closed:
                raise ServerClosed("enter_brownout_step() after close()")
            if len(self._brownout) >= len(BROWNOUT_STEPS):
                return None
            step = BROWNOUT_STEPS[len(self._brownout)]
            self._brownout.append(step)
        self._apply_admission_params()
        return step

    def exit_brownout_step(self) -> Optional[str]:
        """Descend one rung — strictly LIFO: the most recently entered
        step is reverted first (``reject_admissions`` lifts before the
        shed depth restores, before the deadlines narrow). Returns the
        step exited, or None when no step is active."""
        with self._lock:
            if not self._brownout:
                return None
            step = self._brownout.pop()
        self._apply_admission_params()
        return step

    def _apply_admission_params(self) -> None:
        """Push the current effective admission knobs onto every live
        server generation (outside the plane lock — set_admission_params
        takes each server's own condition lock)."""
        kw = self._effective_server_kwargs()
        with self._lock:
            servers = [
                r.server for r in self._replicas if not r.evicted
            ]
        for s in servers:
            s.set_admission_params(
                max_wait_ms=kw["max_wait_ms"],
                max_queue_depth=kw["max_queue_depth"],
            )

    def autoscale_signals(self) -> Dict[str, Any]:
        """The numpy-free signal block the autoscaler's tick consumes:
        live replica count, rotation occupancy (outstanding reservations
        — the same counters hot-swap drains on), total queued-not-
        dispatched depth across replicas, and the brownout state."""
        with self._lock:
            reps = [r for r in self._replicas if not r.evicted]
            n = len(reps)
            in_rotation = sum(1 for r in reps if not r.out_of_rotation)
            outstanding = sum(r.outstanding for r in reps)
            brownout = list(self._brownout)
        queue_depth = sum(r.server.queue_depth for r in reps)
        return {
            "replicas": n,
            "in_rotation": in_rotation,
            "outstanding": outstanding,
            "queue_depth": queue_depth,
            "brownout_level": len(brownout),
            "brownout_steps": brownout,
        }

    # -- observability -----------------------------------------------------

    def live_replica_indices(self) -> List[int]:
        """Sorted indices of live, in-rotation replicas — the canary
        picker's view (the lifecycle controller swaps the lowest live
        index first so canary attribution is deterministic)."""
        with self._lock:
            return sorted(
                r.index for r in self._replicas
                if not r.evicted and not r.out_of_rotation
            )

    def first_completion_times(self) -> Dict[str, float]:
        """``{plan_fingerprint: monotonic stamp}`` of the FIRST response
        successfully served under each plan version this plane has ever
        run — the serving half of the lifecycle plane's model-staleness
        clock. Survives restarts and swaps (stamped at the front-door
        future, like the plane counters)."""
        with self._lock:
            return dict(self._first_completed)

    def _retire_server(self, server: MicroBatchServer) -> None:
        """Fold a closing server generation's counters into the plane's
        history so restarts and swaps never lose completions."""
        s = server.stats()
        with self._lock:
            for k in ("completed", "rejected", "failed", "breaker_opens"):
                self._retired[k] += int(s.get(k) or 0)

    def stats(self) -> Dict[str, Any]:
        """Aggregate plane stats + per-replica attribution.

        Front-door counters (completed / rejected / failed, end-to-end
        p50/p99 over the rolling window) are accounted at the future,
        so they survive replica restarts and swaps; ``replica_*``
        blocks carry each LIVE worker generation's own stats() plus
        lifecycle state, and ``span_summary_by_replica`` attributes
        batch spans to the replica that executed them. ``degraded`` is
        the loud flag: any replica evicted or currently dead."""
        lat = self._latencies.stats_snapshot()
        with self._lock:
            reps = list(self._replicas)
            out: Dict[str, Any] = {
                "num_replicas": self.num_replicas,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                "degraded_rejected": self.degraded_rejected,
                "restarts_total": self.restarts_total,
                "swaps_completed": self.swaps_completed,
                "replicas_added": self.replicas_added,
                "replicas_removed": self.replicas_removed,
                "brownout_level": len(self._brownout),
                "brownout_steps": list(self._brownout),
                "brownout_rejected": self.brownout_rejected,
                "retired_generations": dict(self._retired),
                "num_latency_samples": lat["count"],
            }
            outstanding = {r.index: r.outstanding for r in reps}
        out["p50_latency_s"] = lat["p50"]
        out["p99_latency_s"] = lat["p99"]

        per_replica: Dict[int, Dict[str, Any]] = {}
        span_by_rep: Dict[int, Dict[str, Any]] = {}
        evicted: List[int] = []
        healthy = 0
        for r in reps:
            s = r.server.stats()
            s.update({
                "outstanding": outstanding[r.index],
                "restarts": r.restarts,
                "evicted": r.evicted,
                "in_rotation": not (r.evicted or r.out_of_rotation),
                "plan_fingerprint": r.server.plan.fingerprint,
            })
            per_replica[r.index] = s
            # Each server's span ring holds only its own spans, so the
            # summary stats() already computed IS this replica's group —
            # re-snapshotting the ring here would take the span lock a
            # second time per replica on the serving hot path.
            if s.get("span_summary"):
                span_by_rep[r.index] = s["span_summary"]
            if r.evicted:
                evicted.append(r.index)
            elif s["breaker_state"] not in ("dead",):
                healthy += 1
        out["per_replica"] = per_replica
        out["span_summary_by_replica"] = span_by_rep
        out["evicted_replicas"] = evicted
        out["healthy_replicas"] = healthy
        out["degraded"] = bool(evicted) or healthy < self.num_replicas
        return out

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop the plane: the watchdog joins, then every replica server
        closes (in-flight batches complete, queued requests fail with
        :class:`ServerClosed`). Idempotent."""
        with self._lock:
            already = self._closed
            self._closed = True
        self._stop.set()
        if not already:
            self._watchdog.join(timeout=timeout)
        for rep in list(self._replicas):
            rep.server.close(timeout=timeout)

    def __enter__(self) -> "ReplicatedServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
