"""Online inference subsystem: exported apply plans + deadline-aware
micro-batching + open-loop load tooling (docs/serving.md).

The offline tiers fit pipelines and apply them to whole datasets; this
package turns a :class:`~keystone_tpu.workflow.pipeline.FittedPipeline`
into something that serves streams of single-datum requests:

  - :func:`export_plan` / :class:`ExportedPlan` — apply-only subgraph,
    re-run through the fusion optimizer, weights pinned device-resident,
    pre-compiled at power-of-two padding buckets (warm path never traces).
  - :class:`MicroBatchServer` — deadline-aware request coalescing on a
    background worker thread, bounded queue with explicit
    earliest-deadline load shedding, per-request spans, rolling p50/p99.
  - :class:`ReplicatedServer` — N replicas behind one
    admission-controlled front door: least-loaded routing with
    per-replica breakers, watchdog restarts within a bounded budget,
    zero-drop atomic hot-swap of the plan under live traffic, and the
    zero-drop elasticity + brownout-ladder primitives the autoscaler
    drives (``serving/replicas.py``).
  - :class:`Autoscaler` — the SLO-closed-loop controller: sustained
    WARN/BREACH with rising fast burn adds replicas, sustained OK with
    idle budget removes them, and past ``max_replicas`` admission
    degrades down the named brownout ladder — every decision a
    structured ``autoscale.decision`` event (``serving/autoscale.py``).
  - :class:`ModelZoo` — the multi-tenant tier: many fingerprinted
    plans under one hard device-memory budget, weights paged host-side
    in the bit-exact int16+bf16 split-plane encoding with per-tensor
    CRCs, LRU-priced-by-cost eviction, per-tenant SLOs with
    deficit-weighted fair admission, deadline-bounded cold starts, and
    loud quarantine on corruption (``serving/zoo.py``).
  - :func:`run_open_loop` / :func:`run_multi_tenant_open_loop` /
    :func:`closed_loop_qps` — Poisson load generation (single and
    skewed multi-tenant mixes) and the batch-size-1 baseline the bench
    A/Bs against.
"""

from .autoscale import AutoscaleDecision, Autoscaler
from .batcher import (
    MicroBatchServer,
    ServerClosed,
    ServerDegraded,
    ServerOverloaded,
)
from .export import BatchInfo, ExportedPlan, export_plan, plan_fingerprint
from .lifecycle import LifecycleController, LifecycleDecision
from .loadgen import (
    LoadReport,
    MultiTenantLoadReport,
    closed_loop_qps,
    poisson_arrivals,
    run_multi_tenant_open_loop,
    run_open_loop,
)
from .replicas import BROWNOUT_STEPS, ReplicatedServer
from .zoo import (
    ModelZoo,
    PagedWeights,
    TenantColdStart,
    TenantQuarantined,
    ZooDecision,
)

__all__ = [
    "AutoscaleDecision",
    "Autoscaler",
    "BROWNOUT_STEPS",
    "BatchInfo",
    "ExportedPlan",
    "LifecycleController",
    "LifecycleDecision",
    "LoadReport",
    "MicroBatchServer",
    "ModelZoo",
    "MultiTenantLoadReport",
    "PagedWeights",
    "ReplicatedServer",
    "ServerClosed",
    "ServerDegraded",
    "ServerOverloaded",
    "TenantColdStart",
    "TenantQuarantined",
    "ZooDecision",
    "closed_loop_qps",
    "export_plan",
    "plan_fingerprint",
    "poisson_arrivals",
    "run_multi_tenant_open_loop",
    "run_open_loop",
]
