"""Deadline-aware micro-batching server over an ExportedPlan.

The throughput argument is the same amortize-fixed-costs one the offline
tiers make for compile/pad machinery: a TPU dispatch costs the same
whether it carries 1 row or 256, so a stream of single-datum requests is
served at hardware rate only if something coalesces them. This module is
that something:

  - Submitters call :meth:`MicroBatchServer.submit` and get a
    ``concurrent.futures.Future``; they never touch JAX.
  - ONE background worker thread owns the queue and ALL device
    interaction — the same thread discipline as data/prefetch.py's
    Prefetcher (there the reader owns disk+numpy and the consumer owns
    JAX; here the submitters own numpy and the worker owns JAX). Errors
    raised by the plan re-raise in the submitter through the future.
  - Batches form on whichever comes first: ``max_batch`` requests
    queued, the oldest request has waited ``max_wait_ms``, or a request
    deadline is imminent. The batch runs at the smallest pre-compiled
    padding bucket that fits; padding rows are masked off the response.
  - The queue is bounded. When full, admission sheds by
    earliest-deadline-first: the request with the least remaining
    deadline budget (ties: oldest enqueue) is rejected with
    :class:`ServerOverloaded` — explicitly, through its future (or
    synchronously to the submitter when the new request is the victim).
    Nothing is ever silently dropped.
  - Shutdown (:meth:`close`) is part of the contract, mirroring
    ``tests/test_prefetch.py``'s coverage: the executing batch completes,
    queued-but-unstarted requests fail with :class:`ServerClosed`, the
    worker thread joins — no deadlock, no leak.
  - Degradation is explicit (docs/reliability.md): a CIRCUIT BREAKER
    counts consecutive plan failures and OPENs past ``breaker_threshold``
    — submissions then fail fast with :class:`ServerDegraded` instead of
    queueing against a plan that is failing every batch; after
    ``breaker_reset_s`` one half-open probe batch is admitted and a
    success re-closes the breaker. A worker WATCHDOG catches the worker
    thread dying on an unexpected error: every queued and in-flight
    future fails loudly with :class:`ServerDegraded` (cause chained) and
    later submissions raise immediately — submitters never hang on a
    dead server. The ``serving.execute`` fault site
    (:mod:`keystone_tpu.utils.faults`) drives both paths in chaos tests.

Observability: per-request spans (queue wait / pad fraction / batch exec
time) are recorded through :class:`keystone_tpu.utils.profiling.SpanLog`,
and :meth:`stats` exposes p50/p99 latency plus throughput counters
computed over completions. End-to-end latency lives in a MERGEABLE
log-bucketed histogram (ISSUE 10 — ``obs.BucketedHistogram``): O(1)
memory over an unbounded serve and percentiles over the WHOLE run, not
the last few seconds of ring window; the queue-wait/exec split keeps
the exact sample ring (its window is the span log, a deliberate
recent-window view). When an :class:`~keystone_tpu.obs.slo.SLOTracker`
is attached (``slo=``), every completion/shed/failure feeds it — the
server itself is the SLI source, so the OK/WARN/BREACH verdict is live,
not a post-hoc loadgen artifact. Under tracing, per-request spans are
TAIL-SAMPLED when the tracer carries a sampler (errors/sheds/slow
requests always kept), and kept spans attach ``run_id/span_id``
exemplars to their latency bucket — a p99 breach links directly to
offending traces.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from keystone_tpu import obs
from keystone_tpu.obs.metrics import (
    METRIC_SERVING_BREAKER_OPENS,
    METRIC_SERVING_COMPLETED,
    METRIC_SERVING_DEGRADED_REJECTED,
    METRIC_SERVING_FAILED,
    METRIC_SERVING_LATENCY_S,
    METRIC_SERVING_QUEUE_DEPTH,
    METRIC_SERVING_REJECTED,
)
from keystone_tpu.utils import faults, profiling

__all__ = [
    "MicroBatchServer",
    "ServerClosed",
    "ServerDegraded",
    "ServerOverloaded",
]


class ServerOverloaded(RuntimeError):
    """The bounded request queue shed this request (load exceeded the
    server's configured depth). Submitters should back off or retry
    against another replica — the request was NOT executed."""


class ServerClosed(RuntimeError):
    """The server was shut down before this request executed."""


class ServerDegraded(RuntimeError):
    """The server is failing fast: the circuit breaker is OPEN (the
    plan failed ``breaker_threshold`` consecutive batches) or the worker
    thread died. The request was NOT executed; submitters should back
    off or fail over — queueing more work against a failing plan only
    converts each request into a slow error."""


class _Request:
    __slots__ = ("x", "future", "enqueue_t", "deadline_t", "is_probe")

    def __init__(self, x, future: Future, enqueue_t: float, deadline_t: float):
        self.x = x
        self.future = future
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        self.is_probe = False  # the half-open breaker's single probe

    def shed_key(self):
        # Earliest deadline first; among equal deadlines (including the
        # no-deadline +inf class) the oldest request sheds first.
        return (self.deadline_t, self.enqueue_t)

    def resolve(self, value=None, exc: Optional[BaseException] = None) -> bool:
        """Resolve the future, tolerating client-side ``Future.cancel()``:
        set_result/set_exception raise InvalidStateError on a cancelled
        future, and an unguarded raise here would kill the worker thread
        — every later request would then hang forever. Returns whether
        the value/exception was actually delivered."""
        try:
            if not self.future.set_running_or_notify_cancel():
                return False  # client cancelled before dispatch
        except RuntimeError:
            # Already resolved — the watchdog may sweep a batch whose
            # early members the worker finished before dying.
            return False
        try:
            if exc is not None:
                self.future.set_exception(exc)
            else:
                self.future.set_result(value)
            return True
        except Exception:  # racy double-resolution: never worker-fatal
            return False


class MicroBatchServer:
    """Serve an :class:`~keystone_tpu.serving.export.ExportedPlan` online.

    Knobs (the latency-vs-throughput surface, docs/serving.md):

      - ``max_batch``: coalescing ceiling (clamped to the plan's).
      - ``max_wait_ms``: longest the oldest request waits for co-riders.
        0 disables coalescing-by-wait (dispatch as fast as the worker
        loops — batches still form under backlog).
      - ``max_queue_depth``: bound on queued-not-yet-dispatched requests;
        beyond it admission sheds earliest-deadline-first.
      - ``breaker_threshold`` / ``breaker_reset_s``: consecutive plan
        failures before the circuit breaker OPENs (submit then fails
        fast with :class:`ServerDegraded`), and the cooldown before a
        half-open probe is admitted. ``breaker_threshold=0`` disables
        the breaker (pre-reliability behavior).
      - ``slo``: an :class:`~keystone_tpu.obs.slo.SLOTracker` fed one
        outcome per request — completions with their end-to-end
        latency, sheds/breaker rejects/failures as bad events.
    """

    def __init__(
        self,
        plan,
        max_batch: Optional[int] = None,
        max_wait_ms: float = 2.0,
        max_queue_depth: int = 1024,
        span_log_len: int = 4096,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 1.0,
        replica_index: Optional[int] = None,
        slo=None,
    ):
        if max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if breaker_threshold < 0:
            raise ValueError("breaker_threshold must be >= 0")
        self.plan = plan
        self.max_batch = min(
            int(plan.max_batch if max_batch is None else max_batch),
            plan.max_batch,
        )
        if self.max_batch < 1:
            # A non-positive cap would make the worker pop empty batches
            # in a hot loop while every request hangs — fail at build.
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue_depth = int(max_queue_depth)
        # Span attribution tag for the replicated plane (None standalone).
        self.replica_index = replica_index

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: Deque[_Request] = deque()
        # Count of queued requests carrying a FINITE deadline: when zero
        # (the common case), admission shedding and the worker's
        # coalescing wait skip their O(queue) deadline scans — at depth
        # 4096 those scans run under the same lock the dispatch path
        # needs and would inflate exactly the p99 tail being measured.
        self._finite_deadlines = 0
        self._closed = False

        # Circuit breaker + worker watchdog state (all under _lock).
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self._consecutive_failures = 0
        self._breaker_open = False
        self._breaker_opened_t = 0.0
        self._breaker_probing = False  # ONE half-open probe in flight
        self._worker_dead = False

        # Rolling observability state. The counters and the latency
        # histogram are REGISTERED metrics (ISSUE 9 — obs.MetricsRegistry
        # is the single store stats() reads; the legacy attribute names
        # stay as properties below). The span ring keeps its own
        # SpanLog shape — it carries structured RequestSpans, not
        # scalars — and bridges into the tracer when one is active.
        # End-to-end latency is a BUCKETED histogram (ISSUE 10): the old
        # 4096-sample ring silently biased a multi-hour serve's p99
        # toward the last few seconds; log buckets keep the whole run at
        # O(1) memory and merge exactly across replicas.
        self.span_log = profiling.SpanLog(maxlen=span_log_len)
        self.metrics = obs.MetricsRegistry()
        self._completed = self.metrics.counter(METRIC_SERVING_COMPLETED)
        self._rejected = self.metrics.counter(METRIC_SERVING_REJECTED)
        self._failed = self.metrics.counter(METRIC_SERVING_FAILED)
        self._breaker_opens = self.metrics.counter(
            METRIC_SERVING_BREAKER_OPENS
        )
        self._degraded_rejected = self.metrics.counter(
            METRIC_SERVING_DEGRADED_REJECTED
        )
        self._latencies = self.metrics.bucketed_histogram(
            METRIC_SERVING_LATENCY_S
        )
        self._queue_depth = self.metrics.gauge(METRIC_SERVING_QUEUE_DEPTH)
        self._slo = slo
        self._first_done_t: Optional[float] = None
        self._last_done_t: Optional[float] = None

        self._thread = threading.Thread(
            target=self._worker, name="keystone-serving-batcher", daemon=True
        )
        self._thread.start()

    # -- legacy counter attributes (now registry-backed) -------------------

    @property
    def completed(self) -> int:
        return int(self._completed.value)

    @property
    def rejected(self) -> int:
        return int(self._rejected.value)

    @property
    def failed(self) -> int:
        return int(self._failed.value)

    @property
    def breaker_opens(self) -> int:
        return int(self._breaker_opens.value)

    @property
    def degraded_rejected(self) -> int:
        return int(self._degraded_rejected.value)

    # -- submit side -------------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the plan's
        output row for it. Raises :class:`ServerClosed` after close();
        raises :class:`ServerOverloaded` when the queue is full and this
        request is the shedding victim (otherwise the victim's future
        receives it). Every shed/degraded rejection feeds the attached
        SLO tracker as a bad event — admission control spends error
        budget, visibly."""
        try:
            return self._submit(x, deadline_ms)
        except (ServerOverloaded, ServerDegraded):
            if self._slo is not None:
                self._slo.observe(ok=False)
            raise

    def _submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        now = time.perf_counter()
        deadline_t = (
            now + float(deadline_ms) / 1e3 if deadline_ms is not None
            else math.inf
        )
        req = _Request(np.asarray(x), Future(), now, deadline_t)
        shed: Optional[_Request] = None
        with self._cond:
            if self._closed:
                raise ServerClosed("submit() after close()")
            if self._worker_dead:
                raise ServerDegraded(
                    "serving worker thread died; the server cannot "
                    "execute requests (restart it)"
                )
            if self._breaker_open:
                elapsed = now - self._breaker_opened_t
                if elapsed >= self.breaker_reset_s and not self._breaker_probing:
                    # Half-open: admit EXACTLY ONE probe. The breaker
                    # stays open for everyone else until the probe
                    # batch's outcome lands — otherwise full offered
                    # load would pour in against the still-unverified
                    # plan during the probe's execution. The flag is
                    # only set AFTER the request actually enqueues (a
                    # shed on the full queue below must not leak the
                    # probe slot with no probe in flight).
                    req.is_probe = True
                else:
                    self._degraded_rejected.add(1)
                    raise ServerDegraded(
                        f"circuit breaker open: the plan failed "
                        f"{self._consecutive_failures} consecutive "
                        f"batches; retrying in "
                        f"{self.breaker_reset_s:.3g}s windows"
                    )
            if len(self._pending) >= self.max_queue_depth:
                if self._finite_deadlines:
                    victim = min(self._pending, key=_Request.shed_key)
                else:
                    victim = self._pending[0]  # all +inf: oldest sheds
                if victim.shed_key() <= req.shed_key():
                    self._pending.remove(victim)
                    if victim.deadline_t != math.inf:
                        self._finite_deadlines -= 1
                    if victim.is_probe:
                        # A shed probe never executes: free the slot or
                        # the breaker would reject forever.
                        self._breaker_probing = False
                    shed = victim
                else:
                    self._rejected.add(1)
                    raise ServerOverloaded(
                        f"queue full ({self.max_queue_depth}) and this "
                        f"request holds the earliest deadline"
                    )
            self._pending.append(req)
            if req.is_probe:
                self._breaker_probing = True
            if req.deadline_t != math.inf:
                self._finite_deadlines += 1
            if shed is not None:
                self._rejected.add(1)
            self._queue_depth.set(len(self._pending))
            if obs.enabled():
                # Counter track: queued depth at every admission — the
                # load picture in the Perfetto view (same name as the
                # registered gauge, sampled over time instead of
                # point-in-time).
                obs.counter_track(METRIC_SERVING_QUEUE_DEPTH,
                                  len(self._pending))
            self._cond.notify()
        if shed is not None:
            shed.resolve(exc=ServerOverloaded(
                f"shed (earliest deadline first) at queue depth "
                f"{self.max_queue_depth}"
            ))
            # A shed victim is a bad SLI event and an always-keep trace
            # span (tail sampling never drops sheds): the overload story
            # must survive into both the budget ledger and the trace.
            if self._slo is not None:
                self._slo.observe(ok=False)
            tracer = obs.active_tracer()
            if tracer is not None:
                tracer.add_serving_span(
                    "serving.request", shed.enqueue_t, time.perf_counter(),
                    flagged=True, outcome="shed",
                    replica=self.replica_index,
                )
        return req.future

    def set_admission_params(
        self,
        max_wait_ms: Optional[float] = None,
        max_queue_depth: Optional[int] = None,
    ) -> None:
        """Adjust the admission knobs of a LIVE server — the replicated
        plane's brownout ladder widens the coalescing deadline and
        tightens the shed depth without a worker-generation swap. Takes
        effect immediately: the worker re-reads ``max_wait_s`` on every
        coalescing pass (it is woken here), and the next admission sheds
        against the new depth. Shrinking the depth does NOT retroactively
        shed already-queued requests — each new arrival over the bound
        evicts one earliest-deadline victim, so the queue converges
        without a shed burst."""
        with self._cond:
            if max_wait_ms is not None:
                if max_wait_ms < 0:
                    raise ValueError("max_wait_ms must be >= 0")
                self.max_wait_s = float(max_wait_ms) / 1e3
            if max_queue_depth is not None:
                if max_queue_depth < 1:
                    raise ValueError("max_queue_depth must be >= 1")
                self.max_queue_depth = int(max_queue_depth)
            self._cond.notify_all()

    # -- worker side -------------------------------------------------------

    def _worker(self) -> None:
        batch: Optional[List[_Request]] = None
        try:
            while True:
                batch = self._take_batch()
                if batch is None:
                    return
                if batch:  # empty = a close() drained the queue mid-wait
                    self._execute(batch)
                batch = None
        except BaseException as e:  # noqa: BLE001 — watchdog of last resort
            self._worker_died(e, batch or [])

    def _worker_died(self, exc: BaseException,
                     inflight: List[_Request]) -> None:
        """Watchdog: the worker loop itself failed (not a plan error —
        those are caught in :meth:`_execute`). Fail every in-flight and
        queued future loudly and poison submit, so no submitter ever
        blocks on a Future nothing will resolve."""
        with self._cond:
            self._worker_dead = True
            drained = list(self._pending)
            self._pending.clear()
            self._finite_deadlines = 0
            self._cond.notify_all()
        # The postmortem block: recent spans + cost decisions + whatever
        # was in flight when the worker died, dumped beside the
        # exception (obs flight recorder, ISSUE 9).
        obs.flight.dump_flight_record(
            f"serving worker thread died (replica={self.replica_index}, "
            f"inflight={len(inflight)}, queued={len(drained)})", exc,
        )
        err = ServerDegraded(f"serving worker thread died: {exc!r}")
        err.__cause__ = exc
        for r in inflight + drained:
            r.resolve(exc=err)

    def _take_batch(self) -> Optional[List[_Request]]:
        """Block until a batch is due (fill, wait-out, or deadline), pop
        it FIFO. None = closed and drained (worker exits)."""
        with self._cond:
            while not self._pending:
                if self._closed:
                    return None
                self._cond.wait()
            while (
                self._pending
                and len(self._pending) < self.max_batch
                and not self._closed
            ):
                # Re-read the head each pass: EDF admission shedding may
                # have evicted the request the timer was anchored to, and
                # a stale anchor would cut the coalescing window short
                # exactly under overload.
                first = self._pending[0]
                dispatch_at = first.enqueue_t + self.max_wait_s
                if self._finite_deadlines:
                    dispatch_at = min(
                        dispatch_at,
                        min(r.deadline_t for r in self._pending),
                    )
                remaining = dispatch_at - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            m = min(self.max_batch, len(self._pending))
            batch = [self._pending.popleft() for _ in range(m)]
            self._finite_deadlines -= sum(
                1 for r in batch if r.deadline_t != math.inf
            )
            return batch

    def _execute(self, batch: List[_Request]) -> None:
        t0 = time.perf_counter()
        try:
            faults.maybe_fail(faults.SITE_SERVING_EXECUTE)
            outs, info = self.plan.apply_batch_info([r.x for r in batch])
        except BaseException as e:  # noqa: BLE001 — re-raised submitter-side
            opened = False
            with self._lock:
                self._failed.add(len(batch))
                if self.breaker_threshold:
                    self._consecutive_failures += 1
                    if self._breaker_probing and any(
                        r.is_probe for r in batch
                    ):
                        # THE half-open probe failed: re-open and
                        # restart the cooldown. Both conditions matter:
                        # batch membership keeps a pre-open queued batch
                        # failing during the probe's wait from being
                        # misattributed, and the probing flag keeps a
                        # STALE probe (breaker already re-closed by an
                        # earlier batch's success) from bumping
                        # breaker_opens on a closed breaker — a stale
                        # probe's failure counts like any other.
                        self._breaker_probing = False
                        self._breaker_open = True
                        self._breaker_opened_t = time.perf_counter()
                        self._breaker_opens.add(1)
                        opened = True
                    elif (
                        self._consecutive_failures >= self.breaker_threshold
                        and not self._breaker_open
                    ):
                        self._breaker_open = True
                        self._breaker_opened_t = time.perf_counter()
                        self._breaker_opens.add(1)
                        opened = True
            if opened:
                # Postmortem context rides the log beside the open: the
                # recent spans/decisions and anything still in flight
                # (obs flight recorder, ISSUE 9).
                obs.flight.dump_flight_record(
                    f"serving circuit breaker OPENED (replica="
                    f"{self.replica_index}, consecutive_failures="
                    f"{self._consecutive_failures})", e,
                )
            # Failed requests: always-keep trace spans (errors are never
            # tail-sampled out) and bad SLI events for the budget ledger.
            t_err = time.perf_counter()
            tracer = obs.active_tracer()
            for r in batch:
                if tracer is not None:
                    tracer.add_serving_span(
                        "serving.request", r.enqueue_t, t_err,
                        flagged=True, outcome="error",
                        error=f"{type(e).__name__}: {e}",
                        replica=self.replica_index,
                    )
                r.resolve(exc=e)
                if self._slo is not None:
                    self._slo.observe(ok=False)
            return
        with self._lock:
            # Any successful batch (including the half-open probe)
            # re-closes the breaker.
            self._consecutive_failures = 0
            self._breaker_open = False
            self._breaker_probing = False
        t1 = time.perf_counter()
        exec_s = t1 - t0
        # Bridge into the run trace (one branch when disabled): one span
        # per request (enqueue -> completion, the end-to-end latency the
        # SLO gates) on the serving worker's track, plus a batch span.
        # The rolling SpanLog/stats() machinery keeps working unchanged
        # — the tracer is the correlated view, not a replacement.
        tracer = obs.active_tracer()
        if tracer is not None:
            tracer.add_span(
                "serving.batch", t0, t1, batch_size=info.batch_size,
                bucket=info.bucket, pad_fraction=info.pad_fraction,
                replica=self.replica_index,
            )
        for i, r in enumerate(batch):
            self.span_log.record(profiling.RequestSpan(
                queue_wait_s=t0 - r.enqueue_t,
                exec_s=exec_s,
                batch_size=info.batch_size,
                bucket=info.bucket,
                pad_fraction=info.pad_fraction,
                replica=self.replica_index,
            ))
            lat = t1 - r.enqueue_t
            exemplar = None
            if tracer is not None:
                # Tail-sampled: the tracer's sampler (when installed)
                # head-samples healthy fast requests but always keeps
                # slow ones and breaker probes. A KEPT span's id becomes
                # the exemplar its latency bucket carries — the
                # p99-breach→trace link.
                sid = tracer.add_serving_span(
                    "serving.request", r.enqueue_t, t1,
                    flagged=r.is_probe,
                    queue_wait_s=t0 - r.enqueue_t, exec_s=exec_s,
                    bucket=info.bucket, replica=self.replica_index,
                )
                if sid is not None:
                    exemplar = f"{tracer.run_id}/{sid}"
            with self._lock:
                self._latencies.observe(lat, exemplar=exemplar)
                self._completed.add(1)
                if self._first_done_t is None:
                    self._first_done_t = t1
                self._last_done_t = t1
            r.resolve(outs[i])
            if self._slo is not None:
                self._slo.observe(latency_s=lat, ok=True)

    # -- observability -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        """Requests queued but not yet dispatched (the admission side of
        the load picture; in-flight batches are not counted)."""
        with self._lock:
            return len(self._pending)

    def stats(self) -> Dict[str, Any]:
        """Latency percentiles + throughput counters; None until
        something completes. End-to-end ``p50/p99_latency_s`` come from
        the WHOLE-RUN bucketed histogram (exact to within one ~8%
        bucket — a multi-hour serve's p99 is the run's p99, not the
        last ring window's), while the queue-wait/exec split below
        stays exact over the span-log window.

        End-to-end latency is reported SPLIT into its two sides —
        ``p50/p99_queue_wait_s`` (time queued before the batch
        dispatched) and ``p50/p99_exec_s`` (the batch's execution wall)
        — so admission-control tuning can see which side of the SLO is
        burning budget: queue-wait blowing up wants a lower
        ``max_wait_ms``/``max_queue_depth`` (or another replica), exec
        blowing up wants a smaller ``max_batch`` or a faster plan."""
        with self._lock:
            completed, rejected, failed = (
                self.completed, self.rejected, self.failed
            )
            t_span = (
                self._last_done_t - self._first_done_t
                if self._first_done_t is not None else None
            )
            breaker_state = self._breaker_state_locked()
            breaker_opens = self.breaker_opens
            degraded_rejected = self.degraded_rejected
            consecutive_failures = self._consecutive_failures
        # One consistent histogram read (count + sum + percentiles under
        # a single lock acquisition — the snapshot-vs-observe race the
        # registry regression test pins).
        lat = self._latencies.stats_snapshot()
        # ONE ring copy: the wait/exec percentiles and the summary all
        # derive from the same snapshot (stats() polls contend the span
        # lock with the worker's record() on the serving hot path).
        spans = self.span_log.snapshot()
        wait_pct = profiling.latency_percentiles(
            [s.queue_wait_s for s in spans]
        )
        exec_pct = profiling.latency_percentiles([s.exec_s for s in spans])
        span_summary = profiling.summarize_spans(spans)
        return {
            "completed": completed,
            "rejected": rejected,
            "failed": failed,
            "breaker_state": breaker_state,
            "breaker_opens": breaker_opens,
            "degraded_rejected": degraded_rejected,
            "consecutive_failures": consecutive_failures,
            "p50_latency_s": lat["p50"],
            "p99_latency_s": lat["p99"],
            # The two sides of end-to-end latency, separately (over the
            # span_log window — admission-control tuning reads these).
            "p50_queue_wait_s": wait_pct["p50"] if wait_pct else None,
            "p99_queue_wait_s": wait_pct["p99"] if wait_pct else None,
            "p50_exec_s": exec_pct["p50"] if exec_pct else None,
            "p99_exec_s": exec_pct["p99"] if exec_pct else None,
            "num_latency_samples": lat["count"],
            # completions/second across the observed completion span;
            # needs >= 2 completions to bound a span.
            "achieved_qps": (
                (completed - 1) / t_span if t_span else None
            ),
            "mean_pad_fraction": span_summary.get("mean_pad_fraction"),
            "mean_batch_size": span_summary.get("mean_batch_size"),
            "mean_queue_wait_s": span_summary.get("mean_queue_wait_s"),
            # The full span summary of the same one snapshot, so
            # aggregators (the replicated plane) never re-copy the ring.
            "span_summary": span_summary,
        }

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop the server: the batch currently executing completes,
        queued-but-unstarted requests fail with :class:`ServerClosed`,
        and the worker thread joins. Idempotent."""
        with self._cond:
            already = self._closed
            self._closed = True
            drained = list(self._pending)
            self._pending.clear()
            self._finite_deadlines = 0
            self._cond.notify_all()
        for r in drained:
            r.resolve(exc=ServerClosed(
                "server closed before this request executed"
            ))
        if not already:
            self._thread.join(timeout=timeout)

    def _breaker_state_locked(self) -> str:
        if self._worker_dead:
            return "dead"
        if not self.breaker_threshold:
            return "disabled"
        if self._breaker_open:
            if self._breaker_probing or (
                time.perf_counter() - self._breaker_opened_t
                >= self.breaker_reset_s
            ):
                # Probe in flight, or the next submit is admitted as one.
                return "half_open"
            return "open"
        return "closed"

    @property
    def breaker_state(self) -> str:
        """"closed" / "open" / "half_open" / "disabled" / "dead"."""
        with self._lock:
            return self._breaker_state_locked()

    @property
    def routing_state(self) -> "tuple[str, bool]":
        """``(breaker_state, probe_free)`` in ONE lock acquisition — the
        replicated plane's router reads both per candidate per submit
        while holding its own global lock, so splitting them across two
        property calls would double the contended server-lock traffic
        on the admission path. ``probe_free`` is True only when the
        breaker is half-open with the probe slot FREE: while a probe is
        already in flight the state reads ``half_open`` but every
        further submit fails fast, so a router should not offer this
        server traffic until the slot resolves."""
        with self._lock:
            state = self._breaker_state_locked()
            return state, (state == "half_open"
                           and not self._breaker_probing)

    @property
    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def __enter__(self) -> "MicroBatchServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
