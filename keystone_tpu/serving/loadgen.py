"""Open-loop Poisson load generation + the batch-size-1 baseline.

Open loop is the honest way to measure serving latency: arrivals follow
the schedule regardless of how the server is doing (a closed loop slows
its own offered rate exactly when the server struggles — coordinated
omission — and reports flattering percentiles). The generator sleeps to
each Poisson arrival, submits, and stamps completion via a done-callback
(resolved on the batcher's worker thread at set_result time), so request
latency never includes the harness's own result-collection order.

The batch-size-1 baseline (:func:`closed_loop_qps`) is the A/B the bench
row states its throughput claim against: one request per dispatch, no
coalescing — what serving looks like without the micro-batcher.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from keystone_tpu.utils import profiling

from .batcher import ServerClosed, ServerDegraded, ServerOverloaded

__all__ = [
    "LoadReport",
    "MultiTenantLoadReport",
    "closed_loop_qps",
    "poisson_arrivals",
    "run_multi_tenant_open_loop",
    "run_open_loop",
]


def poisson_arrivals(rate_hz: float, duration_s: float, seed: int = 0):
    """Arrival offsets (seconds from start) of a Poisson process at
    ``rate_hz`` over ``duration_s`` — exponential inter-arrivals."""
    if rate_hz <= 0 or duration_s <= 0:
        raise ValueError("rate_hz and duration_s must be positive")
    rng = np.random.default_rng(seed)
    # Draw enough exponentials to cover the window with slack, then trim.
    n_guess = max(int(rate_hz * duration_s * 1.5) + 16, 16)
    t = np.cumsum(rng.exponential(1.0 / rate_hz, size=n_guess))
    while t[-1] < duration_s:
        t = np.concatenate(
            [t, t[-1] + np.cumsum(rng.exponential(1.0 / rate_hz, size=n_guess))]
        )
    return t[t < duration_s]


@dataclass
class LoadReport:
    """One open-loop run at one offered rate, with everything a latency
    claim needs to be auditable (sample counts + offered rate ride with
    the percentiles — the bench conventions test enforces the same rule
    on emitted rows)."""

    offered_rate_hz: float
    duration_s: float
    num_offered: int
    completed: int
    rejected: int
    failed: int
    p50_latency_s: Optional[float]
    p99_latency_s: Optional[float]
    mean_latency_s: Optional[float]
    achieved_qps: Optional[float]
    latencies_s: List[float] = field(default_factory=list, repr=False)
    # Per-replica / per-plan-version completion attribution, populated
    # when the submit target annotates futures with ``replica_index`` /
    # ``plan_fingerprint`` (the ReplicatedServer contract). Empty dicts
    # against a standalone MicroBatchServer.
    per_replica_completed: Dict[int, int] = field(default_factory=dict)
    per_fingerprint_completed: Dict[str, int] = field(default_factory=dict)
    # The SLO verdict at the end of the run (an SLOTracker.verdict()
    # dict — states, burn rates, budget ledger), populated when the
    # storm is handed the tracker the serving plane feeds. None when no
    # SLO is declared.
    slo: Optional[Dict[str, Any]] = None

    def to_row_dict(self) -> Dict[str, Any]:
        """The bench-facing dict: percentiles WITH their sample count and
        offered rate in the same dict (make_row's latency audit rule)."""
        out = {
            "offered_rate_hz": round(self.offered_rate_hz, 2),
            "duration_s": round(self.duration_s, 3),
            "num_samples": self.completed,
            "num_offered": self.num_offered,
            "rejected": self.rejected,
            "failed": self.failed,
            "p50_latency_ms": (
                round(self.p50_latency_s * 1e3, 3)
                if self.p50_latency_s is not None else None
            ),
            "p99_latency_ms": (
                round(self.p99_latency_s * 1e3, 3)
                if self.p99_latency_s is not None else None
            ),
            "achieved_qps": (
                round(self.achieved_qps, 2)
                if self.achieved_qps is not None else None
            ),
        }
        if self.per_replica_completed:
            # String keys: this dict is JSON-facing (bench rows), and
            # the row auditors walk keys as strings.
            out["per_replica_completed"] = {
                str(k): v
                for k, v in sorted(self.per_replica_completed.items())
            }
        if self.per_fingerprint_completed:
            out["per_fingerprint_completed"] = dict(
                sorted(self.per_fingerprint_completed.items())
            )
        if self.slo is not None:
            # Compact verdict for the row: states + burn rates + budget
            # per objective. The full transition log / ledger stays on
            # ``report.slo`` for rows that publish the whole story.
            out["slo"] = {
                "state": self.slo.get("state"),
                "objectives": {
                    name: {
                        "state": o.get("state"),
                        "burn_fast": o.get("burn_fast"),
                        "burn_slow": o.get("burn_slow"),
                        "budget_spent_fraction": o.get(
                            "budget_spent_fraction"
                        ),
                        "num_transitions": len(o.get("transitions") or []),
                    }
                    for name, o in (self.slo.get("objectives") or {}).items()
                },
            }
        return out


def run_open_loop(
    submit: Callable[[Any], Any],
    make_request: Callable[[int], Any],
    rate_hz: float,
    duration_s: float,
    seed: int = 0,
    result_timeout_s: float = 60.0,
    slo=None,
) -> LoadReport:
    """Drive ``submit`` (e.g. ``server.submit``) with Poisson arrivals at
    ``rate_hz`` for ``duration_s``; block until every outstanding future
    resolves; return the :class:`LoadReport`.

    ``make_request(i)`` produces the i-th request payload. Rejections
    (ServerOverloaded — at submit() or through the future) count as
    ``rejected``; any other failure counts as ``failed``, including a
    submit() that fails fast synchronously (ServerDegraded while a
    breaker is open or every replica is down, ServerClosed) — the
    storm must keep offering through a degraded window and account for
    it, not crash with no report. Latency is submit→completion
    (completion stamped by a done-callback on the resolving thread).

    ``slo``: the :class:`~keystone_tpu.obs.slo.SLOTracker` the serving
    plane under test FEEDS (``MicroBatchServer(slo=...)`` /
    ``ReplicatedServer(slo=...)``); the storm does not feed it — it
    evaluates it once at the end and attaches the verdict block (state,
    burn rates, budget ledger) to the report, so an open-loop run's
    latency claim and its SLO verdict come from the same window."""
    arrivals = poisson_arrivals(rate_hz, duration_s, seed=seed)
    records = []  # (t_submitted, future, stamp_dict)
    rejected = 0
    failed = 0
    t_start = time.perf_counter()
    for i, t_arr in enumerate(arrivals):
        delay = (t_start + t_arr) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        x = make_request(i)
        stamp: Dict[str, float] = {}
        t_sub = time.perf_counter()
        try:
            fut = submit(x)
        except ServerOverloaded:
            rejected += 1
            continue
        except (ServerDegraded, ServerClosed):
            failed += 1
            continue
        fut.add_done_callback(
            lambda f, s=stamp: s.setdefault("t_done", time.perf_counter())
        )
        records.append((t_sub, fut, stamp))

    latencies: List[float] = []
    per_replica: Dict[int, int] = {}
    per_fingerprint: Dict[str, int] = {}
    for t_sub, fut, stamp in records:
        try:
            fut.result(timeout=result_timeout_s)
        except ServerOverloaded:
            rejected += 1
            continue
        except Exception:  # ServerClosed, plan errors, timeouts
            failed += 1
            continue
        latencies.append(stamp.get("t_done", time.perf_counter()) - t_sub)
        # Replicated-plane attribution (absent on a standalone server):
        # which replica completed it, under which plan fingerprint.
        rep = getattr(fut, "replica_index", None)
        if rep is not None:
            per_replica[rep] = per_replica.get(rep, 0) + 1
        fp = getattr(fut, "plan_fingerprint", None)
        if fp is not None:
            per_fingerprint[fp] = per_fingerprint.get(fp, 0) + 1

    pct = profiling.latency_percentiles(latencies)
    completed = len(latencies)
    wall = time.perf_counter() - t_start
    verdict = None
    if slo is not None:
        slo.evaluate()  # one final pass on the post-storm clock
        verdict = slo.verdict()
    return LoadReport(
        offered_rate_hz=rate_hz,
        duration_s=duration_s,
        num_offered=len(arrivals),
        completed=completed,
        rejected=rejected,
        failed=failed,
        p50_latency_s=pct["p50"] if pct else None,
        p99_latency_s=pct["p99"] if pct else None,
        mean_latency_s=(sum(latencies) / completed) if completed else None,
        achieved_qps=(completed / wall) if completed and wall > 0 else None,
        latencies_s=latencies,
        per_replica_completed=per_replica,
        per_fingerprint_completed=per_fingerprint,
        slo=verdict,
    )


@dataclass
class MultiTenantLoadReport:
    """One multi-tenant open-loop run: per-tenant :class:`LoadReport`
    blocks (each auditable on its own — offered rate, sample count, SLO
    verdict) plus the aggregate. ``num_tenants`` and per-tenant
    ``offered_rate_hz`` ride in :meth:`to_row_dict` so the bench's
    tenant-audit rule (any per-tenant p99/SLO claim must carry
    ``num_tenants`` + per-tenant ``offered*``) passes by construction."""

    tenants: Dict[str, LoadReport]
    duration_s: float

    @property
    def num_tenants(self) -> int:
        return len(self.tenants)

    def tenant_states(self) -> Dict[str, Optional[str]]:
        """``{tenant: SLO worst-state}`` (None when no SLO declared) —
        the isolation-contract read: a spike on one tenant must leave
        every OTHER tenant's state OK."""
        return {
            name: (r.slo or {}).get("state")
            for name, r in self.tenants.items()
        }

    def accounting_ok(self) -> bool:
        """Per-tenant zero-silent-drop claim over the LOADGEN's own
        books: every offered request is accounted completed, rejected,
        or failed (the zoo's front-door counters state the same claim
        server-side)."""
        return all(
            r.num_offered == r.completed + r.rejected + r.failed
            for r in self.tenants.values()
        )

    def to_row_dict(self) -> Dict[str, Any]:
        agg_offered = sum(r.num_offered for r in self.tenants.values())
        return {
            "num_tenants": self.num_tenants,
            "duration_s": round(self.duration_s, 3),
            "offered_total": agg_offered,
            "offered_rate_hz_total": round(
                sum(r.offered_rate_hz for r in self.tenants.values()), 2
            ),
            "completed_total": sum(
                r.completed for r in self.tenants.values()
            ),
            "rejected_total": sum(
                r.rejected for r in self.tenants.values()
            ),
            "failed_total": sum(r.failed for r in self.tenants.values()),
            "accounting_ok": self.accounting_ok(),
            "tenants": {
                name: r.to_row_dict()
                for name, r in sorted(self.tenants.items())
            },
        }


def run_multi_tenant_open_loop(
    submit: Callable[..., Any],
    make_request: Callable[[str, int], Any],
    rates_hz: Dict[str, float],
    duration_s: float,
    seed: int = 0,
    deadline_ms: Optional[float] = None,
    result_timeout_s: float = 60.0,
    slos: Optional[Dict[str, Any]] = None,
) -> MultiTenantLoadReport:
    """Drive a multi-tenant front door (``submit(tenant, x,
    deadline_ms)`` — the :class:`~keystone_tpu.serving.zoo.ModelZoo`
    contract) with INDEPENDENT per-tenant open-loop Poisson processes,
    merged into one arrival schedule. Each tenant keeps its own rate
    (the skewed-traffic shape the isolation chaos tests need — e.g. one
    tenant at 8x the others), its own seeded arrival stream
    (deterministic per (seed, tenant)), and its own
    :class:`LoadReport` with per-tenant SLO verdict when ``slos`` maps
    the tenant to the tracker its serving path feeds.

    Classification mirrors :func:`run_open_loop`: ``ServerOverloaded``
    (which the zoo's cold-start fast-fail subclasses) counts
    ``rejected``; any other named failure counts ``failed`` — the storm
    keeps offering through degraded windows and accounts for
    everything, so offered == completed + rejected + failed per tenant
    by construction."""
    if not rates_hz:
        raise ValueError("rates_hz must name at least one tenant")
    arrivals: List[Any] = []  # (t_offset, tenant, per-tenant index)
    for k, tenant in enumerate(sorted(rates_hz)):
        offsets = poisson_arrivals(
            rates_hz[tenant], duration_s, seed=seed * 1009 + k
        )
        arrivals.extend(
            (float(t), tenant, i) for i, t in enumerate(offsets)
        )
    arrivals.sort(key=lambda a: a[0])

    records: Dict[str, List[Any]] = {t: [] for t in rates_hz}
    rejected: Dict[str, int] = {t: 0 for t in rates_hz}
    failed: Dict[str, int] = {t: 0 for t in rates_hz}
    offered: Dict[str, int] = {t: 0 for t in rates_hz}
    t_start = time.perf_counter()
    for t_arr, tenant, i in arrivals:
        delay = (t_start + t_arr) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        x = make_request(tenant, i)
        offered[tenant] += 1
        stamp: Dict[str, float] = {}
        t_sub = time.perf_counter()
        try:
            fut = submit(tenant, x, deadline_ms)
        except ServerOverloaded:
            rejected[tenant] += 1
            continue
        except (ServerDegraded, ServerClosed):
            failed[tenant] += 1
            continue
        fut.add_done_callback(
            lambda f, s=stamp: s.setdefault("t_done", time.perf_counter())
        )
        records[tenant].append((t_sub, fut, stamp))
    wall = time.perf_counter() - t_start

    reports: Dict[str, LoadReport] = {}
    for tenant in sorted(rates_hz):
        latencies: List[float] = []
        for t_sub, fut, stamp in records[tenant]:
            try:
                fut.result(timeout=result_timeout_s)
            except ServerOverloaded:
                rejected[tenant] += 1
                continue
            except Exception:  # ServerClosed, plan errors, timeouts
                failed[tenant] += 1
                continue
            latencies.append(
                stamp.get("t_done", time.perf_counter()) - t_sub
            )
        pct = profiling.latency_percentiles(latencies)
        completed = len(latencies)
        verdict = None
        tracker = (slos or {}).get(tenant)
        if tracker is not None:
            tracker.evaluate()
            verdict = tracker.verdict()
        reports[tenant] = LoadReport(
            offered_rate_hz=rates_hz[tenant],
            duration_s=duration_s,
            num_offered=offered[tenant],
            completed=completed,
            rejected=rejected[tenant],
            failed=failed[tenant],
            p50_latency_s=pct["p50"] if pct else None,
            p99_latency_s=pct["p99"] if pct else None,
            mean_latency_s=(
                sum(latencies) / completed if completed else None
            ),
            achieved_qps=(
                completed / wall if completed and wall > 0 else None
            ),
            latencies_s=latencies,
            slo=verdict,
        )
    return MultiTenantLoadReport(tenants=reports, duration_s=duration_s)


def closed_loop_qps(
    apply_one: Callable[[Any], Any],
    make_request: Callable[[int], Any],
    num_requests: int = 64,
) -> Dict[str, float]:
    """The naive batch-size-1 serving baseline: sequential single-datum
    requests, one dispatch each, no coalescing. Returns achieved qps and
    per-request latency stats (warm — the first request is untimed)."""
    apply_one(make_request(0))  # warm
    lat = []
    t0 = time.perf_counter()
    for i in range(num_requests):
        t1 = time.perf_counter()
        apply_one(make_request(i))
        lat.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    pct = profiling.latency_percentiles(lat)
    return {
        "qps": num_requests / wall,
        "num_samples": num_requests,
        "mean_latency_s": sum(lat) / len(lat),
        "p50_latency_s": pct["p50"],
        "p99_latency_s": pct["p99"],
    }
