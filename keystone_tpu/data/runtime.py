"""The async data-plane runtime: ONE owner for every IO thread (ROADMAP
item 5, ISSUE 8 tentpole).

Before this module, IO thread ownership was scattered: the prefetcher
spawned a reader thread per pass (``data/prefetch.py``), checkpoint
snapshot writes ran synchronously ON the fold loop
(``data/durable.py`` — the fold stalled for the fsync of a ~1.2 GB
carry at Amazon geometry), and the serving worker rolled its own
thread. The measured cost is the gap between the Amazon fold floor
(131.4 s of pure device time, ``BENCH_FULL_r05.json``) and the 223.8 s
measured wall: ~40% of the row is IO that never overlaps compute.

This module centralizes the discipline instead of the threads' code:

  - **Named serial lanes.** ``submit(site, fn, *args)`` runs ``fn`` on
    the worker thread dedicated to ``site`` (created lazily, named
    ``keystone-io-<site>``). One worker per lane means per-lane FIFO
    ordering is a *structural* guarantee — the prefetcher's strict
    segment order and the checkpoint writer's snapshot ordering need no
    extra synchronization — while distinct lanes (``read`` /
    ``checkpoint`` / ``serve``) genuinely overlap each other and device
    compute.
  - **One-thread-owns-JAX, by construction.** This module imports no
    jax and its workers run submitted host work only (disk, numpy,
    checksums). The lint rule ``jax-off-thread`` walks every submitted
    callable exactly like a ``threading.Thread`` target
    (``tools/lint.py``), so a jax call sneaking into a runtime task is
    a lint failure, not a latent race.
  - **Bounded queues.** Each lane's queue is bounded
    (``queue_depth``); a producer that outruns its IO lane blocks at
    ``submit`` — backpressure, never unbounded staging memory.
  - **Fault/retry integration.** The runtime adds no policy of its
    own: submitted callables keep their existing
    :mod:`keystone_tpu.utils.faults` sites and retry wrappers
    (``prefetch.read``, ``shard.load``, ``checkpoint.write``), so every
    chaos drill that held for the hand-rolled threads holds verbatim on
    the pooled ones.
  - **Clean shutdown.** ``close()`` drains nothing silently: queued
    tasks not yet started are cancelled, in-flight tasks complete, and
    EVERY worker is joined (the ``thread-join`` lint contract). The
    process-wide default runtime closes at interpreter exit.

Per-site *accounting* for the overlap report
(``utils.profiling.overlap_report``) deliberately does NOT live here:
busy/wait seconds are attributed to the owning fit's
:class:`~keystone_tpu.data.prefetch.PrefetchStats` by the submitting
layer, because one runtime serves many fits and a per-runtime counter
could not say whose wall was hidden. The runtime's own :meth:`stats`
reports per-lane lifetime totals (tasks, busy seconds, errors, queue
depth) — the ops view, not the per-fit roofline — held in a
:class:`~keystone_tpu.obs.metrics.MetricsRegistry` (ISSUE 9: named,
registered metrics instead of ad-hoc attributes), and every task runs
under a ``runtime.task`` span when the obs plane is tracing (one
branch when it is not — ``keystone_tpu/obs``, which imports no jax
either).
"""

from __future__ import annotations

import atexit
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

from keystone_tpu import obs
from keystone_tpu.obs.metrics import (
    METRIC_RUNTIME_LANE_BUSY_S,
    METRIC_RUNTIME_LANE_ERRORS,
    METRIC_RUNTIME_LANE_QUEUED,
    METRIC_RUNTIME_LANE_TASKS,
)

__all__ = [
    "DataPlaneRuntime",
    "LANE_CHECKPOINT",
    "LANE_READ",
    "LANE_SERVE",
    "default_runtime",
]

# Canonical lane names (free-form strings are allowed; these are the
# ones the data plane itself uses — the docs/data.md ownership table).
LANE_READ = "read"
LANE_CHECKPOINT = "checkpoint"
LANE_SERVE = "serve"

_SENTINEL = object()


class _Lane:
    """One named worker thread + its bounded FIFO queue. Lifetime
    counters are registered metrics on the owning runtime's
    :class:`~keystone_tpu.obs.metrics.MetricsRegistry` (labeled by
    ``site``) — the single store :meth:`DataPlaneRuntime.stats` reads."""

    def __init__(self, site: str, depth: int, metrics):
        self.site = site
        self.queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._tasks = metrics.counter(METRIC_RUNTIME_LANE_TASKS, site=site)
        self._errors = metrics.counter(METRIC_RUNTIME_LANE_ERRORS, site=site)
        self._busy_s = metrics.counter(METRIC_RUNTIME_LANE_BUSY_S, site=site)
        self._queued = metrics.gauge(METRIC_RUNTIME_LANE_QUEUED, site=site)
        # Set (before the sentinel is enqueued) by the runtime's
        # close(); submit() re-checks it AFTER its put so a task that
        # raced behind the sentinel is cancelled loudly, never stranded
        # unresolved on a queue no worker reads.
        self.closed = False
        self._thread = threading.Thread(
            target=self._worker, name=f"keystone-io-{site}", daemon=True
        )
        self._thread.start()

    # Legacy attribute views (the pre-registry stats shape — tests and
    # dashboards read these through snapshot()).
    @property
    def tasks(self) -> int:
        return int(self._tasks.value)

    @property
    def errors(self) -> int:
        return int(self._errors.value)

    @property
    def busy_s(self) -> float:
        return self._busy_s.value

    def _worker(self):
        """Drain the lane FIFO. Runs submitted host work only — no jax
        reachable from here (this module never imports it); device
        interaction stays on the one designated owner thread."""
        while True:
            item = self.queue.get()
            if item is _SENTINEL:
                # A submit racing close() may have landed tasks behind
                # the sentinel; cancel them so their futures resolve
                # (the racing submit sees the cancellation and raises).
                try:
                    while True:
                        late = self.queue.get_nowait()
                        if late is not _SENTINEL:
                            late[0].cancel()
                except queue.Empty:
                    pass
                return
            fut, fn, args, kwargs = item
            if not fut.set_running_or_notify_cancel():
                continue  # cancelled before it started
            t0 = time.perf_counter()
            # The lane-task span: every pooled-IO task is visible in the
            # trace on its worker's own track (one no-op branch when
            # tracing is off). The submitted fn keeps its own deeper
            # spans (prefetch.read, checkpoint.write) as children.
            with obs.span("runtime.task", lane=self.site,
                          fn=getattr(fn, "__name__", type(fn).__name__)):
                try:
                    result = fn(*args, **kwargs)
                except BaseException as e:  # noqa: BLE001 — via future
                    self._errors.add(1)
                    fut.set_exception(e)
                else:
                    fut.set_result(result)
                finally:
                    dt = time.perf_counter() - t0
                    self._tasks.add(1)
                    self._busy_s.add(dt)

    def snapshot(self) -> Dict[str, Any]:
        self._queued.set(self.queue.qsize())
        return {
            "tasks": self.tasks,
            "errors": self.errors,
            "busy_s": self.busy_s,
            "queued": self.queue.qsize(),
            "alive": self._thread.is_alive(),
        }

    def close(self, timeout: float) -> None:
        self.queue.put(_SENTINEL)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # join(timeout=...) returns silently on timeout; a wedged
            # in-flight task (hung NFS read) would otherwise leak this
            # worker invisibly — the exact opposite of the documented
            # "loud, no leaked threads" contract. Warn; raising here
            # would break atexit / best-effort shutdown paths.
            import logging

            logging.getLogger("keystone_tpu.runtime").warning(
                "keystone-io-%s worker did not join within %.1fs "
                "(in-flight task wedged?); thread leaked", self.site,
                timeout,
            )


class DataPlaneRuntime:
    """Submit/future executor over named serial IO lanes.

    >>> rt = DataPlaneRuntime()
    >>> fut = rt.submit("read", load_segment, 3)
    >>> payload = fut.result()   # raises the task's exception, if any
    >>> rt.close()

    Contracts every consumer leans on:

      - per-lane FIFO: two submissions to one site run in submission
        order (one worker per lane);
      - a returned :class:`concurrent.futures.Future` resolves with the
        task's result or exception — never silently;
      - ``submit`` blocks only when the lane's bounded queue is full
        (backpressure) or raises :class:`RuntimeError` after close;
      - ``close()`` cancels queued-but-unstarted tasks, waits out the
        in-flight ones, and joins every worker thread.
    """

    def __init__(self, queue_depth: int = 64, metrics=None):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self._depth = int(queue_depth)
        self._lanes: Dict[str, _Lane] = {}
        self._lock = threading.Lock()
        self._closed = False
        # The runtime's lifetime counters live in ONE registry (ISSUE
        # 9): stats() is a projection of it, and ops tooling can read
        # the flat snapshot() directly.
        self.metrics = metrics if metrics is not None else (
            obs.MetricsRegistry()
        )

    # -- submission --------------------------------------------------------

    def _lane(self, site: str) -> _Lane:
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "DataPlaneRuntime is closed; create a new runtime "
                    "(or use default_runtime(), which replaces a closed "
                    "default)"
                )
            lane = self._lanes.get(site)
            if lane is None:
                lane = _Lane(site, self._depth, self.metrics)
                self._lanes[site] = lane
            return lane

    def submit(self, site: str, fn: Callable, *args, **kwargs) -> Future:
        """Run ``fn(*args, **kwargs)`` on ``site``'s worker; FIFO per
        site. The callable must be host-only work (disk/numpy — the
        jax-off-thread lint rule walks it); its exceptions surface
        through the returned future, never on the worker."""
        lane = self._lane(site)
        fut: Future = Future()
        lane.queue.put((fut, fn, args, kwargs))
        if obs.enabled():
            # Counter track: queue depth per lane at every submit — the
            # backpressure picture in the Perfetto view. Guarded so the
            # disabled path pays one branch, not an f-string.
            obs.counter_track(f"runtime.{site}.queued",
                              lane.queue.qsize())
        # close() may have run between _lane()'s check and our put: it
        # marks the lane closed BEFORE draining/sentinel, so re-checking
        # here catches every interleaving. If the cancel wins (the task
        # has not started — either a drain got it or it sits stranded
        # behind the sentinel), fail the submit loudly instead of
        # handing back a future nobody will ever run; if the worker
        # already started it, the task completes normally.
        if lane.closed and fut.cancel():
            raise RuntimeError(
                "DataPlaneRuntime closed during submit; the task was "
                "cancelled before it started"
            )
        return fut

    def flush(self, site: Optional[str] = None, timeout: float = 60.0) -> None:
        """Block until every task queued so far on ``site`` (or on every
        lane) has finished — a FIFO barrier task per lane. Task errors do
        NOT surface here (they belong to their own futures)."""
        with self._lock:
            lanes = (
                list(self._lanes.values()) if site is None
                else [self._lanes[site]] if site in self._lanes else []
            )
        barriers = []
        for lane in lanes:
            fut: Future = Future()
            lane.queue.put((fut, lambda: None, (), {}))
            barriers.append(fut)
        for fut in barriers:
            fut.result(timeout=timeout)

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-lane lifetime counters: tasks run, errors, busy seconds,
        current queue depth, worker liveness. The ops view — per-FIT
        overlap accounting rides PrefetchStats instead (module
        docstring). A projection of :attr:`metrics`
        (``metrics.snapshot()`` is the same data flat)."""
        with self._lock:
            lanes = dict(self._lanes)
        return {site: lane.snapshot() for site, lane in lanes.items()}

    def metrics_snapshot(self) -> Dict[str, Any]:
        """The flat registry view of the same counters (``name{site=...}``
        keys) — what dashboards and bench rows read."""
        for lane in list(self._lanes.values()):
            lane.snapshot()  # refresh queue-depth gauges
        return self.metrics.snapshot()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Idempotent shutdown: refuse new submissions, cancel queued
        tasks that have not started, let in-flight tasks finish, and
        join every worker thread (the thread-join lint contract: no
        leaked runtime threads, ever)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            lanes = list(self._lanes.values())
        for lane in lanes:
            # Mark closed BEFORE draining: submit() re-checks this flag
            # after its put, so a task racing past _lane()'s check is
            # cancelled (by this drain, or by the worker's post-sentinel
            # sweep) instead of stranded unresolved.
            lane.closed = True
            # Cancel everything still queued; the sentinel then lands
            # behind the (at most one) in-flight task.
            try:
                while True:
                    item = lane.queue.get_nowait()
                    if item is not _SENTINEL:
                        item[0].cancel()
            except queue.Empty:
                pass
        for lane in lanes:
            lane.close(timeout)

    def __enter__(self) -> "DataPlaneRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[DataPlaneRuntime] = None


def default_runtime() -> DataPlaneRuntime:
    """The process-wide shared runtime (created lazily; a closed default
    is replaced — tests may close it freely). This is what the
    prefetcher and the write-behind checkpoint layer use when no
    explicit runtime is passed, so one pool of named IO workers serves
    the whole process instead of one thread per component."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None or _DEFAULT.closed:
            _DEFAULT = DataPlaneRuntime()
        return _DEFAULT


@atexit.register
def _close_default() -> None:  # pragma: no cover - interpreter exit
    with _DEFAULT_LOCK:
        if _DEFAULT is not None and not _DEFAULT.closed:
            _DEFAULT.close(timeout=5.0)
