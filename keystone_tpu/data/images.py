"""Image-tier data plane (ISSUE 18 tentpole): encoded images in, decoded
row segments out, at every storage tier.

The reference's image loaders (`ImageNetLoader`/`VOCLoader`) hand Spark
an RDD of lazily-decoded images and let lineage re-decode on demand. The
TPU-native analog is a :class:`~keystone_tpu.data.prefetch.ShardSource`
whose ``load(s)`` DECODES one segment of encoded images on the caller's
thread — which, under a :class:`~keystone_tpu.data.prefetch.Prefetcher`,
is the data-plane runtime's read lane, so decode + augmentation hide
behind the device fold exactly like disk reads do. Decode and augment
are first-class fault/observability sites (``image.decode`` /
``image.augment``): chaos plans can kill them mid-stream and the
per-site busy accounting feeds ``profiling.overlap_report``.

Storage-tier routing (`cost.choose_image_tier`, a recorded
``CostDecision``) is what lets ``Pipeline.fit`` take a past-host-RAM
image set with no flag: ``load_images`` prices the tiers and either
keeps decoded rows resident (f32, or the uint8 compressed-resident form
— exact for 8-bit sources; both fill preallocated buffers one segment
at a time, so peak residency is the priced form, never a transient f32
copy) or spills storage-to-storage through
:class:`~keystone_tpu.data.shards.DiskDenseShardWriter` (uint8 rows on
disk by default — the same compressed form), host residency bounded by
one segment.

Row layout: each decoded (and augmented) image flattens row-major over
``(x, y, c)`` to one f32 row — the same order ``Convolver.pack_filters``
uses, so a shard-backed image set reshapes straight into the featurizer.
"""

from __future__ import annotations

import math
import time
from typing import Optional, Tuple

import numpy as np

from keystone_tpu.data.prefetch import ShardSource
from keystone_tpu.utils import faults

__all__ = [
    "EncodedImageSource",
    "SyntheticEncodedImages",
    "images_to_disk_shards",
    "load_images",
]


class SyntheticEncodedImages:
    """A deterministic corpus of PPM(P6)-encoded synthetic images with
    integer class labels — the image-tier test/bench stand-in for a tar
    of JPEGs, with the same decode cost profile (the native PNM decoder
    is the hot path ``decode_image_bytes`` takes).

    Pixels follow the ``synthetic_cifar`` recipe: a class-dependent
    low-frequency pattern plus per-image noise, quantized to uint8 — so
    conv featurizers have signal to find and the uint8 resident tier is
    exact. ``encoded(i)`` is pure in ``i``: two providers with the same
    constructor arguments yield identical bytes (replayable ingest).
    """

    def __init__(
        self,
        n: int,
        x: int = 32,
        y: int = 32,
        channels: int = 3,
        num_classes: int = 10,
        seed: int = 0,
    ):
        self.n = int(n)
        self.x = int(x)
        self.y = int(y)
        self.channels = int(channels)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        pat = np.random.default_rng((self.seed, 0xC1FA))
        self._freqs = pat.uniform(0.2, 1.2, size=(num_classes, 2))
        self._phases = pat.uniform(0, 2 * np.pi, size=(num_classes, channels))
        yy, xx = np.meshgrid(np.arange(self.y), np.arange(self.x), indexing="ij")
        self._grid = (xx, yy)

    def label(self, i: int) -> int:
        return int(
            np.random.default_rng((self.seed, 1, int(i))).integers(
                0, self.num_classes
            )
        )

    def _pixels(self, i: int) -> np.ndarray:
        """(x, y, c) uint8 pixels of image ``i``."""
        c = self.label(i)
        xx, yy = self._grid
        base = np.stack(
            [
                np.sin(
                    self._freqs[c, 0] * xx
                    + self._freqs[c, 1] * yy
                    + self._phases[c, ch]
                )
                for ch in range(self.channels)
            ],
            axis=-1,
        )
        noise = np.random.default_rng((self.seed, 2, int(i))).normal(
            0.0, 0.35, size=base.shape
        )
        img = (base * 0.5 + 0.5 + noise) * 255.0
        return np.clip(img, 0, 255).astype(np.uint8).transpose(1, 0, 2)

    def encoded(self, i: int) -> bytes:
        """PPM P6 bytes of image ``i`` (grayscale sources use P5)."""
        px = self._pixels(i)  # (x, y, c) raster: h=x rows of w=y samples
        h, w = px.shape[0], px.shape[1]
        if self.channels == 1:
            return b"P5\n%d %d\n255\n" % (w, h) + px[:, :, 0].tobytes()
        return b"P6\n%d %d\n255\n" % (w, h) + px.tobytes()

    def encoded_nbytes(self, i: int) -> int:
        return len(self.encoded(i))


class EncodedImageSource(ShardSource):
    """Encoded images as a ShardSource: ``load(s) -> (X_seg (rows, d),
    Y_seg (rows, k), valid_rows)`` with decode + deterministic
    augmentation happening INSIDE ``load`` — on the prefetcher's read
    lane, where the overlap accounting and the fault sites live.

    ``provider`` supplies ``n``, ``encoded(i) -> bytes`` and
    ``label(i) -> int`` (:class:`SyntheticEncodedImages`, or any tar/dir
    adapter with the same surface). Augmentation is a seeded crop to
    ``crop`` (x', y') plus a seeded horizontal flip, derived from
    ``(augment_seed, i)`` — the i-th row is identical across epochs,
    processes, and resume boundaries (the ZCA bit-identity contract
    extends through ingest). Labels one-hot encode to ±1 (the
    ``ClassLabelIndicators`` convention).

    Ragged tails zero-pad to the fixed segment shape; streamed folds see
    zero rows (exact for sums/grams) and ``valid_rows`` carries the true
    count.
    """

    load_retries_transients = False  # the Prefetcher wraps retries

    def __init__(
        self,
        provider,
        images_per_segment: int = 256,
        crop: Optional[Tuple[int, int]] = None,
        augment_seed: int = 0,
        flip: bool = True,
    ):
        self.provider = provider
        self.images_per_segment = int(images_per_segment)
        self.crop = None if crop is None else (int(crop[0]), int(crop[1]))
        self.augment_seed = int(augment_seed)
        self.flip = bool(flip)
        self.n_true = int(provider.n)
        self.num_segments = max(
            1, math.ceil(self.n_true / self.images_per_segment)
        )
        cx, cy = self.out_shape[:2]
        self.d = cx * cy * provider.channels
        self.k = int(provider.num_classes)

    @property
    def out_shape(self) -> Tuple[int, int, int]:
        """Decoded-and-augmented image shape (x', y', c)."""
        if self.crop is not None:
            return (self.crop[0], self.crop[1], self.provider.channels)
        return (self.provider.x, self.provider.y, self.provider.channels)

    @property
    def row_bytes(self) -> Optional[float]:
        return 4.0 * (self.d + self.k)

    @property
    def segment_bytes(self) -> Optional[float]:
        return self.images_per_segment * self.row_bytes

    def segment_encoded_bytes(self, s: int) -> int:
        """Encoded (pre-decode) bytes of segment ``s`` — the ingest-
        bandwidth numerator for bench rows."""
        lo = s * self.images_per_segment
        hi = min(lo + self.images_per_segment, self.n_true)
        return sum(self.provider.encoded_nbytes(i) for i in range(lo, hi))

    def _augment(self, img: np.ndarray, i: int) -> np.ndarray:
        if self.crop is None and not self.flip:
            return img
        r = np.random.default_rng((self.augment_seed, int(i)))
        if self.crop is not None:
            cx, cy = self.crop
            ox = int(r.integers(0, img.shape[0] - cx + 1))
            oy = int(r.integers(0, img.shape[1] - cy + 1))
            img = img[ox:ox + cx, oy:oy + cy, :]
        if self.flip and int(r.integers(0, 2)):
            img = img[:, ::-1, :]
        return img

    def load(self, s: int):
        from keystone_tpu.data.loaders import decode_image_bytes

        lo = s * self.images_per_segment
        hi = min(lo + self.images_per_segment, self.n_true)
        valid = hi - lo

        faults.maybe_fail(faults.SITE_IMAGE_DECODE)
        t0 = time.perf_counter()
        decoded = []
        for i in range(lo, hi):
            img = decode_image_bytes(self.provider.encoded(i))
            if img is None:
                raise ValueError(f"image {i} failed to decode")
            if img.ndim == 2:
                img = img[:, :, None]
            decoded.append(np.asarray(img, np.float32))
        faults.observe_busy("decode", time.perf_counter() - t0)

        faults.maybe_fail(faults.SITE_IMAGE_AUGMENT)
        t0 = time.perf_counter()
        X = np.zeros((self.images_per_segment, self.d), dtype=np.float32)
        Y = np.zeros((self.images_per_segment, self.k), dtype=np.float32)
        Y[:valid] = -1.0
        for j, img in enumerate(decoded):
            X[j] = self._augment(img, lo + j).reshape(-1)
            Y[j, self.provider.label(lo + j)] = 1.0
        faults.observe_busy("augment", time.perf_counter() - t0)
        return X, Y, valid

    def materialize(self):
        xs, ys = [], []
        rows = 0
        for s in range(self.num_segments):
            X, Y, valid = self.load(s)
            xs.append(X[:valid])
            ys.append(Y[:valid])
            rows += valid
        return np.concatenate(xs, axis=0), np.concatenate(ys, axis=0)


def images_to_disk_shards(
    source: EncodedImageSource,
    out_dir: str,
    tile_rows: int = 256,
    tiles_per_segment: int = 4,
    x_dtype=np.float32,
):
    """Spill a decoded image stream storage-to-storage: one segment
    decodes at a time, appends to a :class:`DiskDenseShardWriter`, and
    the dataset is never host-resident. ``x_dtype=np.uint8`` stores the
    compressed-resident pixel form (exact for 8-bit sources, 4× smaller
    on disk and over the read lane). Returns the shard-backed
    :class:`~keystone_tpu.data.dataset.LabeledData`."""
    from keystone_tpu.data.shards import DiskDenseShardWriter

    writer = DiskDenseShardWriter(
        out_dir,
        capacity_rows=source.n_true,
        d_in=source.d,
        k=source.k,
        tile_rows=tile_rows,
        tiles_per_segment=tiles_per_segment,
        x_dtype=x_dtype,
    )
    for s in range(source.num_segments):
        X, Y, valid = source.load(s)
        writer.append(np.asarray(X[:valid], dtype=x_dtype), Y[:valid])
    return writer.close().as_labeled_data()


def _materialize_resident(source: EncodedImageSource, x_dtype):
    """Stream-decode a source into preallocated ``(n, d)`` ``x_dtype``
    rows and ``(n, k)`` f32 labels: one segment decodes at a time and
    casts into place, so peak host residency is the PRICED resident form
    plus a single staged f32 segment — never the full f32 dataset. The
    ``resident_u8`` tier engages exactly when that f32 form busts the
    host budget, so this path must not build it."""
    X = np.empty((source.n_true, source.d), dtype=x_dtype)
    Y = np.empty((source.n_true, source.k), dtype=np.float32)
    row = 0
    for s in range(source.num_segments):
        X_seg, Y_seg, valid = source.load(s)
        X[row:row + valid] = X_seg[:valid]  # exact u8 cast: 8-bit sources
        Y[row:row + valid] = Y_seg[:valid]
        row += valid
    return X, Y


def load_images(
    provider,
    *,
    images_per_segment: int = 256,
    crop: Optional[Tuple[int, int]] = None,
    augment_seed: int = 0,
    flip: bool = True,
    spill_dir: Optional[str] = None,
    spill_dtype=None,
    tile_rows: int = 256,
    tiles_per_segment: int = 4,
    prefetch_depth: int = 2,
    host_budget_bytes: Optional[float] = None,
):
    """The image-tier loader entry point: decode-and-augment an encoded
    corpus into a :class:`LabeledData` at the storage tier the cost
    model selects (a recorded ``image_tier`` CostDecision) — resident
    f32 rows, resident uint8 rows, or disk shards — with NO flag. A
    past-host-RAM corpus requires ``spill_dir`` (raises otherwise: the
    only honest alternative would be an OOM). ``spill_dtype`` is the
    on-disk row dtype for the spill tier; the ``None`` default stores
    uint8 — the compressed-resident form, exact for 8-bit sources with
    value-preserving augmentation, and the 4×-smaller write + per-epoch
    re-read traffic the cost model's disk pricing assumes. Pass
    ``np.float32`` for deeper-than-8-bit providers."""
    from keystone_tpu.data.dataset import LabeledData
    from keystone_tpu.ops.learning import cost

    source = EncodedImageSource(
        provider,
        images_per_segment=images_per_segment,
        crop=crop,
        augment_seed=augment_seed,
        flip=flip,
    )
    tier, ref = cost.choose_image_tier(
        source.n_true, source.d, source.k,
        images_per_segment=images_per_segment,
        prefetch_depth=prefetch_depth,
        host_budget_bytes=host_budget_bytes,
    )
    if tier == "disk_shards":
        if spill_dir is None:
            raise ValueError(
                "the cost model routed this image set to disk shards "
                f"({source.n_true} images × {source.row_bytes:.0f} B rows "
                "exceed the host budget) — pass spill_dir="
            )
        return images_to_disk_shards(
            source, spill_dir,
            tile_rows=tile_rows, tiles_per_segment=tiles_per_segment,
            x_dtype=(np.uint8 if spill_dtype is None else spill_dtype),
        ), tier, ref
    X, Y = _materialize_resident(
        source, np.uint8 if tier == "resident_u8" else np.float32
    )
    return LabeledData(X, Y), tier, ref
