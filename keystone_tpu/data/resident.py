"""Compressed-resident COO tier: int16 indices + bf16 values, chunk-tiled
(ISSUE 8 tentpole — the r05 probe promoted to a real storage class).

The Amazon working set at padded-COO int32+f32 is 8 bytes per stored
cell — 43 GB at n=65e6, far past one chip's HBM. The r05 bench probe
showed the same data lives at **4 bytes/cell** (int16 index + bf16
value) with the decode *fused into the fold*: the gram fold's densify
step already casts indices to int32 and values to the fold's
``val_dtype`` inside the compiled program
(``ops/sparse.py::sparse_gram_fold``), so compressed chunks cost ZERO
extra passes — the "decompression" is the cast the fold was doing
anyway. This module makes that encoding a first-class tier:

  - :class:`CompressedCOOChunks` — host-side encode/decode with the
    overflow boundary enforced (an index that does not fit int16
    raises; it must never wrap silently) and a stated value-drift
    policy, plus chunk-tiled device operands in exactly the
    ``_resident_chunk_fn`` contract of
    ``ops/learning/lbfgs.py::run_lbfgs_gram_streamed``.
  - The cost model (``ops/learning/cost.py``) prices this as a third
    storage class between HBM-raw and disk:
    :data:`COMPRESSED_BYTES_PER_NNZ` (4.0) vs the raw 8.0, feasible
    only while :func:`compressible_dim` holds — so ``Pipeline.fit``
    routes a working set chip-resident whenever the compressed form
    fits and streams only what truly cannot.

**Value-drift policy** (stated, tested — tests/test_resident.py):
indices round-trip EXACTLY or :meth:`CompressedCOOChunks.encode`
raises — index quantization is never lossy. Values quantize f32→bf16
with round-to-nearest-even: values already bf16-representable (±1
labels, the intercept's 1.0, anything with ≤8 significant mantissa
bits) round-trip exactly; general f32 values drift by at most 2⁻⁸
relative (one bf16 ulp). This is the SAME quantization the
``gram_dtype="bf16"`` fold applies transiently inside its densify — a
compressed-resident fit is bit-identical to the bf16-engine streamed
fit over the same rows, which is how the tier's correctness is pinned.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "COMPRESSED_BYTES_PER_NNZ",
    "CompressedCOOChunks",
    "INT16_MAX_INDEX",
    "compressible_dim",
]

# int16 index (2 B) + bf16 value (2 B) per stored cell — the storage
# class cost.py prices between HBM-raw (8 B: int32+f32) and disk.
COMPRESSED_BYTES_PER_NNZ = 4.0
# Largest column index an int16 lane can carry. The append-ones
# intercept column lives at index d, so a d-wide problem with intercept
# needs d <= INT16_MAX_INDEX.
INT16_MAX_INDEX = np.iinfo(np.int16).max  # 32767


def compressible_dim(d: int, index_base: int = 0) -> bool:
    """Whether a feature width fits the int16 index encoding (indices
    0..d-1; callers appending an intercept lane at index d must pass
    d+1). Past it the compressed tier is infeasible — cost.py prices it
    at infinity rather than wrapping indices.

    ``index_base`` is the partition-local rebase (ISSUE 16): a mesh
    partition that stores indices relative to its own column base must
    gate on the REBASED width ``d - index_base``, not the global dim —
    the global check passing says nothing about a shifted local range.
    """
    return int(d) - 1 - int(index_base) <= INT16_MAX_INDEX


def _bf16_dtype() -> np.dtype:
    import ml_dtypes  # jax dependency; host-side bfloat16

    return np.dtype(ml_dtypes.bfloat16)


def raw_chunk_tiles(indices, values, labels, chunk_rows: int):
    """Tile uncompressed padded-COO rows (plus labels) into the
    ``(nchunks, chunk_rows, ·)`` operand triple every streamed fold
    consumes (``run_lbfgs_gram_streamed``, the sketch engines' scans).

    Ragged-tail rows are padded with index −1 / value 0 — the same
    out-of-range convention the fold's densify masks — so the pad rows
    contribute nothing to any accumulated product. Dtypes pass through
    untouched; this is the raw (non-:class:`CompressedCOOChunks`)
    sibling of ``.operands()``.
    """
    import jax.numpy as jnp

    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    labels = jnp.asarray(labels)
    npad = int(indices.shape[0])
    c = int(chunk_rows)
    nchunks = -(-npad // c)
    pad = nchunks * c - npad
    idx_t = jnp.pad(indices, ((0, pad), (0, 0)), constant_values=-1).reshape(
        nchunks, c, indices.shape[1]
    )
    val_t = jnp.pad(values, ((0, pad), (0, 0))).reshape(
        nchunks, c, values.shape[1]
    )
    y_t = jnp.pad(labels, ((0, pad), (0, 0))).reshape(
        nchunks, c, labels.shape[1]
    )
    return idx_t, val_t, y_t


class CompressedCOOChunks:
    """Padded-COO rows encoded int16+bf16 and tiled into fold chunks.

    ``idx_t (nchunks, chunk_rows, w) int16`` (−1 = inactive lane),
    ``val_t (nchunks, chunk_rows, w) bf16``,
    ``y_t (nchunks, chunk_rows, k) f32`` — exactly the operand triple
    ``ops/learning/lbfgs.py::_resident_chunk_fn`` slices, so a
    compressed set rides ``run_lbfgs_gram_streamed(operands=
    chunks.operands(), val_dtype=jnp.bfloat16)`` with no solver
    changes: the fold's densify casts int16→int32 / upcasts bf16 in
    the compiled program (the fused decode).
    """

    def __init__(self, idx_t: np.ndarray, val_t: np.ndarray,
                 y_t: np.ndarray, n_true: int, d: int,
                 index_base: int = 0):
        self.idx_t = idx_t
        self.val_t = val_t
        self.y_t = y_t
        self.n_true = int(n_true)
        self.d = int(d)
        # Partition-local column rebase: stored lanes hold
        # ``global_index - index_base`` (0 for the whole-set encoding).
        self.index_base = int(index_base)

    # -- encode ------------------------------------------------------------

    @classmethod
    def encode(
        cls,
        indices,
        values,
        labels,
        chunk_rows: int,
        d: Optional[int] = None,
        n_true: Optional[int] = None,
        index_base: int = 0,
    ) -> "CompressedCOOChunks":
        """Encode (n, w) padded-COO rows + (n, k) labels.

        Raises :class:`ValueError` at the int16 overflow boundary (any
        active index > :data:`INT16_MAX_INDEX`) — the one failure mode
        that must be impossible to hit silently: a wrapped index would
        scatter a value into the wrong Gramian row and corrupt the fit
        without a single NaN. Values quantize f32→bf16 per the module's
        drift policy. The ragged tail pads with inactive (−1) lanes and
        zero labels to whole chunks.

        ``index_base`` (ISSUE 16): a mesh partition stores its lanes
        REBASED to its own column base (``stored = index - base``). The
        boundary is then checked on the rebased, PARTITION-LOCAL range —
        active indices below the base or at ``base + 32768`` and past
        raise here, at encode, because a wrapped rebased index would
        corrupt that one device's Gramian partial while every other
        device's stays clean (no NaN, no global signal).
        """
        indices = np.asarray(indices)
        values = np.asarray(values)
        labels = np.asarray(labels)
        if labels.ndim == 1:
            labels = labels[:, None]
        n, w = indices.shape
        n_true = n if n_true is None else int(n_true)
        index_base = int(index_base)
        active = indices >= 0
        if index_base:
            # Rebase only active lanes; -1 stays the inactive marker.
            if active.any() and int(indices[active].min()) < index_base:
                raise ValueError(
                    f"active index {int(indices[active].min())} < "
                    f"index_base {index_base}: this partition does not "
                    f"own that column — rebasing would wrap negative"
                )
            indices = np.where(active, indices - index_base, -1)
        max_idx = int(indices.max()) if indices.size else -1
        d = max_idx + 1 + index_base if d is None else int(d)
        if max_idx > INT16_MAX_INDEX:
            raise ValueError(
                f"index {max_idx + index_base} (rebased {max_idx} at "
                f"base {index_base}) does not fit the int16 encoding "
                f"(max {INT16_MAX_INDEX}); the compressed-resident tier "
                f"is infeasible at this width — use the raw int32 tier "
                f"or the streamed path (a wrapped index would silently "
                f"corrupt the Gramian)"
            )
        if indices.size and int(indices.min()) < -1:
            raise ValueError(
                f"index {int(indices.min())} < -1: only -1 marks an "
                f"inactive lane"
            )
        idx16 = indices.astype(np.int16)
        # The boundary check above makes this structural; assert the
        # round-trip anyway — index quantization is NEVER allowed loss.
        assert (idx16.astype(indices.dtype) == indices).all()
        val_bf = values.astype(_bf16_dtype())
        c = int(chunk_rows)
        nchunks = max(-(-n // c), 1)
        idx_t = np.full((nchunks * c, w), -1, np.int16)
        idx_t[:n] = idx16
        val_t = np.zeros((nchunks * c, w), _bf16_dtype())
        val_t[:n] = val_bf
        y_t = np.zeros((nchunks * c, labels.shape[1]), np.float32)
        y_t[:n] = labels.astype(np.float32)
        return cls(
            idx_t.reshape(nchunks, c, w),
            val_t.reshape(nchunks, c, w),
            y_t.reshape(nchunks, c, labels.shape[1]),
            n_true=n_true, d=d, index_base=index_base,
        )

    # -- decode (the round-trip oracle) ------------------------------------

    def decode(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Back to (n, w) int32 indices / f32 values / (n, k) labels —
        what the fold's in-program casts produce, host-side, for the
        round-trip equality tests (indices exact; values exact iff the
        input was bf16-representable)."""
        _, c, w = self.idx_t.shape
        rows = self.num_chunks * c
        keep = min(rows, self.n_true) if self.n_true else rows
        idx = self.idx_t.reshape(-1, w).astype(np.int32)
        if self.index_base:
            idx = np.where(idx >= 0, idx + self.index_base, -1)
        val = self.val_t.reshape(-1, w).astype(np.float32)
        y = self.y_t.reshape(rows, -1)
        return idx[:keep], val[:keep], np.asarray(y[:keep], np.float32)

    # -- mesh partitioning (ISSUE 16) --------------------------------------

    def _validate_boundary(self) -> None:
        """Re-run the int16 boundary check on THIS partition's buffers.

        ``compressible_dim`` gating on the global dim is not enough once
        chunks partition across device HBM: each partition re-validates
        at its own (d, index_base) so a shifted local base can never
        smuggle a wrapped index into one device's Gramian partial.
        """
        if not compressible_dim(self.d, self.index_base):
            raise ValueError(
                f"partition at index_base {self.index_base} cannot "
                f"represent width {self.d} in int16 (local range "
                f"{self.d - self.index_base} > {INT16_MAX_INDEX + 1})"
            )
        if self.idx_t.size:
            lo = int(self.idx_t.min())
            hi = int(self.idx_t.max())
            if lo < -1 or hi + self.index_base >= self.d:
                raise ValueError(
                    f"partition holds indices [{lo}, {hi}] at base "
                    f"{self.index_base} outside width {self.d} — "
                    f"refusing to build a corrupt per-device Gramian"
                )

    def partition(self, num_partitions: int) -> "list[CompressedCOOChunks]":
        """Split the chunk axis into ``num_partitions`` CONTIGUOUS
        per-device partitions — the 8-chip residency layout: partition j
        feeds device j's HBM (``ops/learning/lbfgs.py``'s mesh fold owns
        chunks ``[j·cpd, (j+1)·cpd)``). Ragged tails pad with dead
        chunks (inactive lanes, zero labels) so every partition carries
        exactly ``cpd`` chunks; ``n_true`` splits by true-row ownership.
        Every partition re-validates the int16 boundary — per partition,
        not globally."""
        m = int(num_partitions)
        if m < 1:
            raise ValueError(f"num_partitions must be >= 1, got {m}")
        cpd = -(-self.num_chunks // m)
        c, w = self.chunk_rows, self.idx_t.shape[2]
        k = self.y_t.shape[2]
        parts = []
        for j in range(m):
            lo = j * cpd
            # Wholly-dead trailing partitions (m·cpd > num_chunks) clamp
            # to an empty [lo, lo) range — a negative hi-lo would flow a
            # negative n_true through np.clip below.
            hi = max(min((j + 1) * cpd, self.num_chunks), lo)
            idx = np.full((cpd, c, w), -1, np.int16)
            val = np.zeros((cpd, c, w), self.val_t.dtype)
            y = np.zeros((cpd, c, k), np.float32)
            if hi > lo:
                idx[: hi - lo] = self.idx_t[lo:hi]
                val[: hi - lo] = self.val_t[lo:hi]
                y[: hi - lo] = self.y_t[lo:hi]
            n_local = int(np.clip(self.n_true - lo * c, 0, (hi - lo) * c))
            part = CompressedCOOChunks(
                idx, val, y, n_true=n_local, d=self.d,
                index_base=self.index_base,
            )
            part._validate_boundary()
            parts.append(part)
        return parts

    # -- capacity / device views -------------------------------------------

    @property
    def num_chunks(self) -> int:
        return int(self.idx_t.shape[0])

    @property
    def chunk_rows(self) -> int:
        return int(self.idx_t.shape[1])

    @property
    def nbytes(self) -> int:
        """Resident footprint of the compressed operands (indices +
        values + labels) — what cost.py's capacity cut prices."""
        return int(self.idx_t.nbytes + self.val_t.nbytes + self.y_t.nbytes)

    @property
    def bytes_per_nnz(self) -> float:
        return float(self.idx_t.dtype.itemsize + self.val_t.dtype.itemsize)

    def operands(self):
        """Device operand triple for ``run_lbfgs_gram_streamed(
        _resident_chunk_fn, ...)`` — placed as jnp arrays (int16/bf16
        stay compressed in HBM; the fold's densify is the decode)."""
        import jax.numpy as jnp

        return (
            jnp.asarray(self.idx_t),
            jnp.asarray(self.val_t),
            jnp.asarray(self.y_t),
        )

    @staticmethod
    def value_drift(values) -> float:
        """Max absolute bf16 quantization error over ``values`` — the
        drift-policy audit helper (0.0 for bf16-representable input)."""
        values = np.asarray(values, np.float32)
        q = values.astype(_bf16_dtype()).astype(np.float32)
        return float(np.max(np.abs(q - values))) if values.size else 0.0
