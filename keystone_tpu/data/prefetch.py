"""Out-of-core ingestion: ShardSource protocol + double-buffered prefetch.

The reference streams from storage *by construction* (``CsvDataLoader``
is a lazy ``textFile``, CsvDataLoader.scala:10-31; image archives decode
per partition, ImageLoaderUtils.scala:21-94), so its fits are bounded by
disk. This module makes the disk tier a first-class, *pipelined* data
path here:

  - :class:`ShardSource` — the protocol unifying in-RAM segment sources
    and the memory-mapped :class:`~keystone_tpu.data.shards.DiskCOOShards`
    / :class:`~keystone_tpu.data.shards.DiskDenseShards` files: ordered
    segments of READY host buffers, delivered one at a time.
  - :class:`Prefetcher` — loads segment k+1 (disk read + mmap-page copy
    into a contiguous host staging buffer) on the data-plane runtime's
    ``read`` lane (:mod:`keystone_tpu.data.runtime`) while the
    consumer's ``jax.device_put`` + device fold for segment k are in
    flight. Double-buffered with bounded depth and backpressure: at most
    ``depth`` load tasks are outstanding at once, and the runtime lane's
    single worker guarantees they complete in submission order. The
    graph executor is documented non-thread-safe, so NOTHING JAX-side
    runs on the IO workers — they hand finished numpy buffers back
    through futures, and the consumer thread does every device
    interaction (the jax-off-thread lint rule walks every submitted
    callable).

The producer/consumer overlap is the same discipline as tf.data-style
input pipelines and the async-dispatch throttling the streamed folds
already use device-side (``BoundedInflight``): with depth d, at most d
segments of host staging memory exist at once, and the disk→host latency
of segment k+1 hides behind the fold of segment k.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Optional, Tuple

import numpy as np

from keystone_tpu import obs
from keystone_tpu.data import runtime as runtime_mod
from keystone_tpu.obs.metrics import (
    METRIC_PREFETCH_BACKOFF_S,
    METRIC_PREFETCH_LOAD_S,
    METRIC_PREFETCH_RETRIES,
    METRIC_PREFETCH_SEGMENTS,
    METRIC_PREFETCH_WAIT_S,
    METRIC_SITE_BUSY_S,
    METRIC_SITE_WAIT_S,
)
from keystone_tpu.utils import faults


class ShardSource:
    """Ordered segments of ready host buffers feeding a streamed fold.

    The contract every streamed consumer (``streaming_bcd_fit_segments``,
    ``run_lbfgs_gram_streamed``, the shard-backed ``Dataset``) reads:

      - ``num_segments``: how many segments exist,
      - ``n_true``: the true (unpadded) example count across all segments,
      - ``load(s)``: materialize ONLY segment ``s`` as host numpy buffers
        (same shape for every s — ragged tails are padded by the source).

    ``load`` must be safe to call from a background thread: it may touch
    the filesystem and numpy, never JAX (the executor and the dispatch
    queue are single-consumer).
    """

    num_segments: int
    n_true: int

    # True when load() already retries transient IO internally (the
    # disk-shard views — shards.py's RetryPolicy at the shard.load
    # site). The Prefetcher then does NOT wrap load in its own retry:
    # nesting two policies would multiply attempts and compound backoff
    # (a dead disk would cost attempts² reads before surfacing).
    load_retries_transients: bool = False

    def load(self, s: int):
        raise NotImplementedError

    # -- capacity metadata (the cost model prices the disk tier on these) --

    @property
    def row_bytes(self) -> Optional[float]:
        """Approximate host bytes per example row (None when unknown)."""
        return None

    @property
    def segment_bytes(self) -> Optional[float]:
        """Approximate host bytes one staged segment occupies."""
        return None

    def materialize(self) -> Any:
        """Concatenate every segment into resident arrays (small sources
        only — the escape hatch that keeps shard-backed Datasets usable
        by resident solvers when they DO fit)."""
        raise NotImplementedError


class DenseShardSource(ShardSource):
    """:class:`~keystone_tpu.data.shards.DiskDenseShards` as a ShardSource:
    ``load(s) -> (X_seg (T, tile_rows, d_in), Y_seg (T, tile_rows, k),
    valid_rows)`` — exactly the ``segment_source`` contract of
    ``streaming_bcd_fit_segments``."""

    load_retries_transients = True  # shards.py retries at shard.load

    def __init__(self, shards):
        self.shards = shards

    @property
    def num_segments(self) -> int:
        return self.shards.num_segments

    @property
    def n_true(self) -> int:
        return self.shards.n_true

    @property
    def tile_rows(self) -> int:
        return self.shards.tile_rows

    @property
    def d_in(self) -> int:
        return int(self.shards._x.shape[-1])

    @property
    def k(self) -> int:
        return int(self.shards._y.shape[-1])

    @property
    def row_bytes(self) -> Optional[float]:
        return float(
            self.d_in * self.shards._x.dtype.itemsize
            + self.k * self.shards._y.dtype.itemsize
        )

    @property
    def segment_bytes(self) -> Optional[float]:
        rb = self.row_bytes
        return rb * self.shards.tiles_per_segment * self.tile_rows

    def load(self, s: int):
        return self.shards.segment_source(s)

    def materialize(self):
        """(X (n_true, d_in), Y (n_true, k)) resident."""
        xs, ys = [], []
        for s in range(self.num_segments):
            X_seg, Y_seg, _ = self.load(s)
            xs.append(X_seg.reshape(-1, X_seg.shape[-1]))
            ys.append(Y_seg.reshape(-1, Y_seg.shape[-1]))
        X = np.concatenate(xs)[: self.n_true]
        Y = np.concatenate(ys)[: self.n_true]
        return X, Y


class DenseShardView(ShardSource):
    """One FIELD (rows or labels) of a :class:`DenseShardSource`, flattened
    to per-row form — what a shard-backed ``Dataset`` wraps, so the typed
    Pipeline API can carry (data, labels) as two Datasets that share one
    set of disk files. ``load(s)`` returns the (seg_rows, width) slice of
    the field; the paired (X, Y, valid) form the solvers fold lives on
    ``.paired`` (the underlying :class:`DenseShardSource`)."""

    load_retries_transients = True  # shards.py retries at shard.load

    def __init__(self, paired: DenseShardSource, field: str):
        if field not in ("x", "y"):
            raise ValueError(f"field must be 'x' or 'y', got {field!r}")
        self.paired = paired
        self.field = field

    @property
    def num_segments(self) -> int:
        return self.paired.num_segments

    @property
    def n_true(self) -> int:
        return self.paired.n_true

    @property
    def width(self) -> int:
        return self.paired.d_in if self.field == "x" else self.paired.k

    @property
    def row_bytes(self) -> Optional[float]:
        sh = self.paired.shards
        arr = sh._x if self.field == "x" else sh._y
        return float(self.width * arr.dtype.itemsize)

    @property
    def segment_bytes(self) -> Optional[float]:
        sh = self.paired.shards
        return self.row_bytes * sh.tiles_per_segment * sh.tile_rows

    def load(self, s: int):
        """Field-only segment read: the row view never pays the label
        read and — the big one — the label view never pays the much
        wider row read (the cost-model sampler loads label segments)."""
        sh = self.paired.shards
        seg, _ = (
            sh.segment_source_x(s) if self.field == "x"
            else sh.segment_source_y(s)
        )
        return seg.reshape(-1, seg.shape[-1])

    def materialize(self):
        segs = [self.load(s) for s in range(self.num_segments)]
        return np.concatenate(segs)[: self.n_true]


class ResidentDenseSource(ShardSource):
    """In-RAM (X, Y) presented through the ShardSource protocol — the
    resident end of the unification: the same fold/prefetch machinery runs
    whether segments come from memory-mapped disk files or live arrays
    (used by parity tests and the prefetch-off bench leg)."""

    def __init__(self, X, Y, tile_rows: int, tiles_per_segment: int):
        self.X = np.asarray(X)
        self.Y = np.asarray(Y)
        self.tile_rows = int(tile_rows)
        self.tiles_per_segment = int(tiles_per_segment)
        self.n_true = int(self.X.shape[0])
        self.num_tiles = -(-self.n_true // self.tile_rows)

    @property
    def num_segments(self) -> int:
        return -(-self.num_tiles // self.tiles_per_segment)

    @property
    def d_in(self) -> int:
        return int(self.X.shape[-1])

    @property
    def k(self) -> int:
        return int(self.Y.shape[-1])

    @property
    def row_bytes(self) -> Optional[float]:
        return float(
            self.X.shape[-1] * self.X.dtype.itemsize
            + self.Y.shape[-1] * self.Y.dtype.itemsize
        )

    def load(self, s: int):
        tps, tr = self.tiles_per_segment, self.tile_rows
        lo_row = s * tps * tr
        hi_row = min(lo_row + tps * tr, self.n_true)
        m = hi_row - lo_row
        X_seg = np.zeros((tps * tr, self.X.shape[-1]), self.X.dtype)
        Y_seg = np.zeros((tps * tr, self.Y.shape[-1]), self.Y.dtype)
        X_seg[:m] = self.X[lo_row:hi_row]
        Y_seg[:m] = self.Y[lo_row:hi_row]
        valid = max(m, 0)
        return (
            X_seg.reshape(tps, tr, -1),
            Y_seg.reshape(tps, tr, -1),
            valid,
        )

    def materialize(self):
        return self.X, self.Y


class PairedDenseSource(ShardSource):
    """(X_seg, Y_seg, valid_rows) segments assembled from a shard-backed
    data view plus labels that live EITHER in the same disk shards (the
    common spill-path case — zero extra reads) or as a small resident
    array sliced per segment (labels usually fit host RAM even when rows
    don't)."""

    load_retries_transients = True  # shards.py retries at shard.load

    def __init__(self, data_view: DenseShardView, labels=None):
        if data_view.field != "x":
            # A y-view as "data" would silently fit labels against labels.
            raise ValueError(
                "PairedDenseSource needs the rows ('x') view as data, "
                f"got the {data_view.field!r} view"
            )
        self.paired = data_view.paired
        if labels is None:
            self._labels = None
        else:
            Y = np.asarray(labels)
            if Y.ndim == 1:
                Y = Y[:, None]
            if Y.shape[0] != self.paired.n_true:
                raise ValueError(
                    f"labels rows {Y.shape[0]} != shard rows "
                    f"{self.paired.n_true}"
                )
            self._labels = Y

    @property
    def num_segments(self) -> int:
        return self.paired.num_segments

    @property
    def n_true(self) -> int:
        return self.paired.n_true

    @property
    def tile_rows(self) -> int:
        return self.paired.tile_rows

    @property
    def d_in(self) -> int:
        return self.paired.d_in

    @property
    def k(self) -> int:
        if self._labels is not None:
            return int(self._labels.shape[-1])
        return self.paired.k

    def load(self, s: int):
        if self._labels is None:
            return self.paired.load(s)
        # Resident labels: read ONLY the X tiles from disk (the shard
        # labels would be discarded) and slice the label rows host-side.
        sh = self.paired.shards
        X_seg, valid = sh.segment_source_x(s)
        tps, tr = sh.tiles_per_segment, sh.tile_rows
        lo = s * tps * tr
        hi = min(lo + tps * tr, self.n_true)
        Yp = np.zeros((tps * tr, self._labels.shape[-1]),
                      self._labels.dtype)
        Yp[: hi - lo] = self._labels[lo:hi]
        return X_seg, Yp.reshape(tps, tr, -1), valid


class COOShardSource(ShardSource):
    """:class:`~keystone_tpu.data.shards.DiskCOOShards` grouped into
    fixed-width segments: ``load(s) -> (idx, val, y)`` for chunks
    [s·cps, (s+1)·cps) — the per-segment operand contract of
    ``run_lbfgs_gram_streamed(segment_source=...)``."""

    load_retries_transients = True  # shards.py retries at shard.load

    def __init__(self, shards, chunks_per_segment: int):
        self.shards = shards
        self.chunks_per_segment = int(chunks_per_segment)

    @property
    def num_segments(self) -> int:
        return -(-self.shards.num_chunks // self.chunks_per_segment)

    @property
    def n_true(self) -> int:
        return self.shards.n_true

    @property
    def num_chunks(self) -> int:
        return self.shards.num_chunks

    @property
    def d(self) -> int:
        return self.shards.d

    def load(self, s: int):
        return self.shards.segment_source(
            s * self.chunks_per_segment, self.chunks_per_segment
        )


class FunctionSource(ShardSource):
    """Wrap a plain ``load_fn(s)`` (plus counts) as a ShardSource — lets
    the prefetcher drive legacy callable segment sources unchanged."""

    def __init__(self, load_fn: Callable[[int], Any], num_segments: int,
                 n_true: int = 0):
        self._fn = load_fn
        self.num_segments = int(num_segments)
        self.n_true = int(n_true)

    def load(self, s: int):
        return self._fn(s)


def is_shard_source(obj: Any) -> bool:
    return isinstance(obj, ShardSource)


class PrefetchStats:
    """Where the ingestion time went, for the overlap accounting
    (``utils.profiling.prefetch_overlap_fraction``): ``load_s`` sums time
    spent inside ``source.load`` (reader thread — disk + staging copies),
    ``wait_s`` sums time the CONSUMER blocked waiting on the queue
    (latency the prefetch failed to hide). ``prefetched`` records whether
    a background reader actually ran — a serial (depth-0) pass fills
    load_s with no waits, which must read as zero overlap, not full.

    Reliability counters (docs/reliability.md, surfaced through
    ``utils.profiling.prefetch_retry_counters``): ``retries`` counts
    transient read failures the reader recovered from, ``backoff_s``
    sums the backoff it slept — nonzero values mean the fit SUCCEEDED
    over flaky IO and say how much wall that cost.

    Per-SITE accounting (``site_busy_s`` / ``site_wait_s``, surfaced
    through ``utils.profiling.overlap_report``): busy seconds a named
    phase spent working (``read`` on an IO worker, ``verify`` inside the
    shard checksum pass, ``checkpoint`` on the write-behind worker,
    ``compute`` on the consumer's fold dispatch) and the seconds the
    CONSUMER was blocked waiting on that phase — the per-site form of
    the load/wait pair, so the 131.4 s fold-floor claim is auditable
    phase by phase. Thread-safe: IO workers and the consumer thread
    both report.

    The store is a :class:`~keystone_tpu.obs.metrics.MetricsRegistry`
    (ISSUE 9: the ad-hoc attribute counters became named, registered
    metrics — ``registry.snapshot()`` is the flat view bench rows and
    the profiling report functions read). The historical attribute
    surface (``stats.load_s += dt`` and friends) is preserved as
    properties over the registered counters, so every existing call
    site and test reads/writes the same numbers through either door."""

    def __init__(self):
        self.registry = obs.MetricsRegistry()
        self._load_s = self.registry.counter(METRIC_PREFETCH_LOAD_S)
        self._wait_s = self.registry.counter(METRIC_PREFETCH_WAIT_S)
        self._segments = self.registry.counter(METRIC_PREFETCH_SEGMENTS)
        self._retries = self.registry.counter(METRIC_PREFETCH_RETRIES)
        self._backoff_s = self.registry.counter(METRIC_PREFETCH_BACKOFF_S)
        self.prefetched = False

    # -- attribute compatibility over the registry -------------------------

    @property
    def load_s(self) -> float:
        return self._load_s.value

    @load_s.setter
    def load_s(self, v: float) -> None:
        self._load_s.set_(v)

    @property
    def wait_s(self) -> float:
        return self._wait_s.value

    @wait_s.setter
    def wait_s(self, v: float) -> None:
        self._wait_s.set_(v)

    @property
    def segments(self) -> int:
        return int(self._segments.value)

    @segments.setter
    def segments(self, v: int) -> None:
        self._segments.set_(v)

    @property
    def retries(self) -> int:
        return int(self._retries.value)

    @retries.setter
    def retries(self, v: int) -> None:
        self._retries.set_(v)

    @property
    def backoff_s(self) -> float:
        return self._backoff_s.value

    @backoff_s.setter
    def backoff_s(self, v: float) -> None:
        self._backoff_s.set_(v)

    @property
    def site_busy_s(self) -> dict:
        """``{site: seconds}`` view of the labeled busy counters (the
        shape ``utils.profiling.overlap_report`` documents)."""
        return self.registry.values_by_label(METRIC_SITE_BUSY_S, "site")

    @property
    def site_wait_s(self) -> dict:
        return self.registry.values_by_label(METRIC_SITE_WAIT_S, "site")

    def add_busy(self, site: str, seconds: float) -> None:
        self.registry.counter(METRIC_SITE_BUSY_S, site=site).add(
            float(seconds)
        )

    def add_wait(self, site: str, seconds: float) -> None:
        self.registry.counter(METRIC_SITE_WAIT_S, site=site).add(
            float(seconds)
        )


class _Cancelled:
    """Sentinel a load task returns when close() raced its start."""


class Prefetcher:
    """Double-buffered background segment reader with bounded depth.

    Iterating yields ``(s, payload)`` in strict segment order. Loads run
    as tasks on the data-plane runtime's ``read`` lane
    (:mod:`keystone_tpu.data.runtime` — one pooled worker per lane,
    ``source.load`` touches numpy/disk, never JAX); at most ``depth``
    load tasks are outstanding at once (backpressure: host staging
    memory is bounded by depth × segment size), and the lane's FIFO
    makes segment order structural. Clean shutdown is part of the
    contract: closing (or breaking out of / raising inside the
    consuming loop, via the context manager or generator finalizer)
    cancels every queued load and waits out the in-flight one — no
    task of this pass survives close(). Load exceptions re-raise in
    the consumer at the segment that failed.

    Transient read failures (``OSError``) retry on the IO worker
    with bounded exponential backoff (``retry_policy``, default
    :func:`keystone_tpu.utils.faults.default_retry_policy`): a single
    flaky IO no longer kills an hours-long fit. Exhaustion re-raises
    consumer-side exactly as an unretried error would; retry/backoff
    totals accumulate into :class:`PrefetchStats`. The ``prefetch.read``
    fault site fires once per load ATTEMPT, so chaos tests can place
    errors under and past the retry budget deterministically.
    """

    def __init__(self, source: ShardSource, depth: int = 2,
                 stats: Optional[PrefetchStats] = None,
                 retry_policy=None, runtime=None, segment_offset: int = 0,
                 lane: Optional[str] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.source = source
        self.depth = int(depth)
        # Runtime lane the loads run on. The default shared `read` lane
        # serves single-device fits; mesh ingestion gives each device
        # group its OWN lane (`read.d<k>`) so per-device shards load
        # concurrently with backpressure PER LANE (one hot device's slow
        # disk cannot stall its siblings' queues) — ISSUE 16.
        self.lane = lane or runtime_mod.LANE_READ
        # Trace-label offset only (a resumed fit hands us a source
        # rebased to its checkpoint cursor): spans must name ABSOLUTE
        # segment ids, matching the serial leg's s + start labels.
        self.segment_offset = int(segment_offset)
        self.stats = stats if stats is not None else PrefetchStats()
        self.retry_policy = retry_policy or faults.default_retry_policy()
        # None -> the process-wide shared runtime, resolved at iteration
        # time (a test may close/replace the default between passes).
        self.runtime = runtime
        self._pending: "deque" = deque()  # outstanding load futures
        self._stop = threading.Event()
        self._started = False

    # -- reader side (runs on the runtime's `read` worker) -----------------

    def _load_segment(self, s: int):
        """One load task: retry-wrapped ``source.load`` with busy/retry
        accounting into this pass's stats. Host-only work — the
        jax-off-thread lint rule walks this function as the submitted
        target."""
        if self._stop.is_set():
            return _Cancelled()
        try:
            # The trace span covers EXACTLY the region the busy counter
            # covers (retry-wrapped load), so per-site busy totals and
            # span sums agree — the trace-correctness contract
            # tests/test_obs_trace.py audits.
            with faults.observing_retries(self.stats), \
                    obs.span("prefetch.read",
                             segment=s + self.segment_offset):
                t0 = time.perf_counter()
                payload = self._load_with_retry(s)
                dt = time.perf_counter() - t0
        except BaseException:
            # A load that exhausted its retries kills the PASS: queued
            # sibling tasks short-circuit instead of burning their own
            # retry budgets against the same dead disk (the failure cost
            # stays one bounded retry cycle, as with the serial reader).
            self._stop.set()
            raise
        self.stats.load_s += dt
        self.stats.add_busy("read", dt)
        return payload

    def _load_with_retry(self, s: int):
        def on_retry(_attempt, delay_s, _exc):
            self.stats.retries += 1
            self.stats.backoff_s += delay_s

        if getattr(self.source, "load_retries_transients", False):
            # The shard layer already owns disk retries (shard.load
            # site); wrapping load() again would multiply attempts and
            # compound backoff on a genuinely dead disk. The outer
            # policy then covers only this site's own injected faults.
            self.retry_policy.call(
                lambda: faults.maybe_fail(faults.SITE_PREFETCH_READ),
                key=f"prefetch:{s}", on_retry=on_retry,
            )
            return self.source.load(s)

        def attempt():
            faults.maybe_fail(faults.SITE_PREFETCH_READ)
            return self.source.load(s)

        return self.retry_policy.call(
            attempt, key=f"prefetch:{s}", on_retry=on_retry
        )

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> Iterator[Tuple[int, Any]]:
        # Single-use by contract: after close() the stop flag is set and
        # a fresh pass would see every task return the cancel sentinel,
        # silently truncating the stream — fail loud instead (including
        # close()-before-first-iteration, where _started is still False
        # but every load would come back cancelled).
        if self._started or self._stop.is_set():
            raise RuntimeError(
                "Prefetcher is single-use (and unusable once closed); "
                "create a new one per pass"
            )
        self._started = True
        self.stats.prefetched = True
        rt = self.runtime or runtime_mod.default_runtime()
        num = self.source.num_segments
        next_submit = 0
        try:
            while next_submit < min(self.depth, num):
                self._pending.append(
                    rt.submit(self.lane, self._load_segment, next_submit)
                )
                next_submit += 1
            for s in range(num):
                fut = self._pending.popleft()
                t0 = time.perf_counter()
                with obs.span("prefetch.wait",
                              segment=s + self.segment_offset):
                    payload = fut.result()  # re-raises the load's error
                dt = time.perf_counter() - t0
                self.stats.wait_s += dt
                self.stats.add_wait("read", dt)
                if isinstance(payload, _Cancelled):  # close() raced us
                    return
                if next_submit < num and not self._stop.is_set():
                    self._pending.append(
                        rt.submit(self.lane, self._load_segment,
                                  next_submit)
                    )
                    next_submit += 1
                self.stats.segments += 1
                yield s, payload
        finally:
            self.close()

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def staged_count(self) -> int:
        """Outstanding load tasks (staged or in flight) — zero after
        close(); the shutdown regression tests' leak probe."""
        return len(self._pending)

    def close(self) -> None:
        """Stop the pass: cancel every queued load, wait out the (at
        most one) in-flight load, and release every staged payload.
        Idempotent; called automatically when the consuming loop exits
        for ANY reason (completion, break, or a consumer-side
        exception). The runtime's pooled worker outlives this pass by
        design — per-pass state does not."""
        self._stop.set()
        while self._pending:
            fut = self._pending.popleft()
            if not fut.cancel():
                # Already running (or done): bound the wait by one load;
                # its error belongs to the pass that died — swallow.
                try:
                    fut.result(timeout=30.0)
                except Exception:
                    pass


def iter_segments(
    source,
    num_segments: Optional[int] = None,
    prefetch_depth: int = 2,
    stats: Optional[PrefetchStats] = None,
    start: int = 0,
) -> Iterator[Tuple[int, Any]]:
    """Uniform segment iteration for the streamed folds: ``source`` is a
    :class:`ShardSource` or a plain ``load_fn(s)`` callable (then
    ``num_segments`` is required). ``prefetch_depth >= 1`` runs the
    double-buffered background reader; ``0`` loads serially on the
    consumer thread (the prefetch-off A/B leg — identical order and
    payloads by construction). ``start`` skips the first segments and
    yields ABSOLUTE ids from ``start`` on — the checkpoint-resume entry
    point: a resumed fold sees exactly the segment stream the
    interrupted run had left."""
    if not is_shard_source(source):
        if num_segments is None:
            raise ValueError("callable segment sources need num_segments")
        source = FunctionSource(source, num_segments)
    elif num_segments is not None and num_segments < source.num_segments:
        # An explicit cap folds a PREFIX of the source (partial-fold
        # callers); the wrapped loads stay thread-safe for prefetch —
        # and the rebox must carry the retry-ownership flag, or the
        # Prefetcher would nest a second policy over shard loads.
        inner = source
        source = FunctionSource(inner.load, num_segments, inner.n_true)
        source.load_retries_transients = inner.load_retries_transients
    if start:
        if start >= source.num_segments:
            return
        base = source
        source = FunctionSource(
            lambda s: base.load(s + start),
            base.num_segments - start, base.n_true,
        )
        source.load_retries_transients = base.load_retries_transients
    if prefetch_depth and source.num_segments > 1:
        for s, payload in Prefetcher(source, depth=prefetch_depth,
                                     stats=stats, segment_offset=start):
            yield s + start, payload
        return
    for s in range(source.num_segments):
        t0 = time.perf_counter()
        if stats is not None:
            # Serial leg: the same span name as the prefetched reader so
            # the trace's read-site sum matches site_busy_s either way.
            with faults.observing_retries(stats), \
                    obs.span("prefetch.read", segment=s + start,
                             serial=True):
                payload = source.load(s)
            dt = time.perf_counter() - t0
            stats.load_s += dt
            # Inline loads are fully waited-on by construction: busy ==
            # wait, so the per-site report reads 0 overlap — the serial
            # oracle leg must never look overlapped.
            stats.add_busy("read", dt)
            stats.add_wait("read", dt)
            stats.segments += 1
        else:
            payload = source.load(s)
        yield s + start, payload


def mesh_read_lane(device: int) -> str:
    """The per-device-group read lane name (``read.d<k>``) mesh
    ingestion submits device ``k``'s loads on — the data-plane runtime
    creates the lane (own pooled worker + bounded queue) on first
    submit, so per-lane backpressure needs no runtime changes."""
    return f"{runtime_mod.LANE_READ}.d{int(device)}"


def iter_mesh_segments(
    sources,
    prefetch_depth: int = 2,
    stats: Optional[PrefetchStats] = None,
) -> Iterator[Tuple[int, list]]:
    """Lock-step iteration over per-device segment sources (ISSUE 16).

    ``sources[k]`` is device k's :class:`ShardSource` (or a
    ``(load_fn, num_segments)`` pair); segment ``s`` of every device
    loads CONCURRENTLY, each on its own runtime lane (``read.d<k>`` —
    :func:`mesh_read_lane`), each lane's outstanding loads bounded by
    ``prefetch_depth``. Yields ``(s, [payload_0, ..., payload_{m-1}])``
    in strict segment order — the consumer stacks the payloads into the
    mesh fold's sharded operand. All sources must agree on
    ``num_segments`` (pad ragged per-device tails source-side: the mesh
    fold masks phantom chunks dead). ``prefetch_depth=0`` loads serially
    in device order — the byte-identical overlap-off oracle leg.
    """
    boxed = []
    for src in sources:
        if not is_shard_source(src):
            load_fn, num = src
            src = FunctionSource(load_fn, num)
        boxed.append(src)
    if not boxed:
        raise ValueError("iter_mesh_segments needs at least one source")
    nums = {s.num_segments for s in boxed}
    if len(nums) != 1:
        raise ValueError(
            f"per-device sources disagree on num_segments: {sorted(nums)} "
            f"— pad ragged device tails source-side"
        )
    num = nums.pop()
    if prefetch_depth and num > 0:
        readers = [
            Prefetcher(src, depth=prefetch_depth, stats=stats,
                       lane=mesh_read_lane(k))
            for k, src in enumerate(boxed)
        ]
        try:
            for rows in zip(*readers):
                s = rows[0][0]
                yield s, [payload for _, payload in rows]
        finally:
            for r in readers:
                r.close()
        return
    for s in range(num):
        yield s, [src.load(s) for src in boxed]
