"""Data loaders (reference: loaders/ — CsvDataLoader.scala, CifarLoader.scala,
TimitFeaturesDataLoader.scala, NewsgroupsDataLoader.scala, ...).

Loaders read host-side (files → numpy) and produce Datasets; placement onto
the device mesh happens via ``Dataset.shard``. Synthetic generators stand in
for each workload's data so pipelines and benchmarks run hermetically.
"""

from __future__ import annotations

import os
import struct
import tarfile
from typing import List, Optional, Tuple

import numpy as np

from .dataset import Dataset, LabeledData


def csv_data_loader(path: str) -> Dataset:
    """CSV of comma-separated numbers -> Dataset of rows
    (reference: loaders/CsvDataLoader.scala:10-31)."""
    rows = np.loadtxt(path, delimiter=",", dtype=np.float64, ndmin=2)
    return Dataset.of(rows)


def load_labeled_csv(path: str, label_offset: int = 0) -> LabeledData:
    """CSV rows of [label, features...] -> LabeledData.

    label_offset shifts labels (the MNIST files are 1-indexed; the pipelines
    subtract 1, reference: pipelines/images/mnist/MnistRandomFFT.scala:34-37).
    """
    rows = np.loadtxt(path, delimiter=",", dtype=np.float64, ndmin=2)
    labels = rows[:, 0].astype(np.int64) + label_offset
    return LabeledData(rows[:, 1:], labels)


CIFAR_LABEL_SIZE = 1
CIFAR_IMAGE_BYTES = 3072  # 32*32*3
CIFAR_RECORD_BYTES = CIFAR_LABEL_SIZE + CIFAR_IMAGE_BYTES


def load_cifar_binary(path: str) -> LabeledData:
    """CIFAR-10 binary format: 3073-byte records of [label, 3072 pixel bytes]
    (reference: loaders/CifarLoader.scala:14-53). Images come out as
    (n, 32, 32, 3) float64 in [0, 255]."""
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % CIFAR_RECORD_BYTES != 0:
        raise ValueError(f"{path}: not a multiple of {CIFAR_RECORD_BYTES} bytes")
    records = raw.reshape(-1, CIFAR_RECORD_BYTES)
    labels = records[:, 0].astype(np.int64)
    # CIFAR stores channel-planar (RGB planes); convert to HWC.
    images = (
        records[:, 1:]
        .reshape(-1, 3, 32, 32)
        .transpose(0, 2, 3, 1)
        .astype(np.float64)
    )
    return LabeledData(images, labels)


class TimitFeaturesDataLoader:
    """TIMIT: CSV feature frames (440 dims) + sparse label files, 147 classes
    (reference: loaders/TimitFeaturesDataLoader.scala:16-70)."""

    num_classes = 147
    num_features = 440

    def __init__(self, feature_path: str, label_path: str):
        feats = np.loadtxt(feature_path, delimiter=",", dtype=np.float64, ndmin=2)
        labels = self._parse_sparse_labels(label_path, feats.shape[0])
        self.labeled = LabeledData(feats, labels)

    @staticmethod
    def _parse_sparse_labels(path: str, n: int) -> np.ndarray:
        """Label file lines: ``row_index label`` (sparse row labels)."""
        labels = np.zeros(n, dtype=np.int64)
        with open(path) as f:
            for line in f:
                parts = line.replace(",", " ").split()
                if len(parts) >= 2:
                    labels[int(parts[0])] = int(parts[1])
        return labels


def load_newsgroups(path: str, class_dirs: Optional[List[str]] = None) -> LabeledData:
    """20-newsgroups layout: one directory per class of text files
    (reference: loaders/NewsgroupsDataLoader.scala:9-57)."""
    class_dirs = class_dirs or sorted(
        d for d in os.listdir(path) if os.path.isdir(os.path.join(path, d))
    )
    texts, labels = [], []
    for label, cls in enumerate(class_dirs):
        cls_path = os.path.join(path, cls)
        for fname in sorted(os.listdir(cls_path)):
            with open(os.path.join(cls_path, fname), errors="replace") as f:
                texts.append(f.read())
            labels.append(label)
    return LabeledData(Dataset(texts), Dataset.of(np.asarray(labels)))


# ---------------------------------------------------------------------------
# Synthetic data (hermetic stand-ins for the reference workloads)
# ---------------------------------------------------------------------------


def synthetic_classification(
    n: int,
    d: int,
    num_classes: int,
    seed: int = 0,
    class_sep: float = 1.0,
    means_seed: int = 1234,
) -> LabeledData:
    """Gaussian blobs: one mean per class, unit covariance.

    The class means are drawn from ``means_seed`` (fixed across train/test
    splits); ``seed`` only drives the sampling, so different seeds give i.i.d.
    draws from the *same* distribution.
    """
    means = np.random.default_rng(means_seed).normal(
        scale=class_sep, size=(num_classes, d)
    )
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n)
    X = means[labels] + rng.normal(size=(n, d))
    return LabeledData(X, labels.astype(np.int64))


def synthetic_mnist(n: int = 4096, seed: int = 0) -> LabeledData:
    """MNIST-shaped synthetic data: 784-dim, 10 classes."""
    return synthetic_classification(n, 784, 10, seed=seed, class_sep=0.5)


def synthetic_timit(n: int = 8192, seed: int = 0) -> LabeledData:
    """TIMIT-shaped synthetic data: 440-dim frames, 147 classes."""
    return synthetic_classification(
        n, TimitFeaturesDataLoader.num_features, TimitFeaturesDataLoader.num_classes,
        seed=seed, class_sep=0.6,
    )
