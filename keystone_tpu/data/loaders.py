"""Data loaders (reference: loaders/ — CsvDataLoader.scala, CifarLoader.scala,
TimitFeaturesDataLoader.scala, NewsgroupsDataLoader.scala, ...).

Loaders read host-side (files → numpy) and produce Datasets; placement onto
the device mesh happens via ``Dataset.shard``. Synthetic generators stand in
for each workload's data so pipelines and benchmarks run hermetically.
"""

from __future__ import annotations

import json
import os
import tarfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from keystone_tpu import native

from .dataset import Dataset, LabeledData


def _check_rect(vals, ncols: int, nrows: int, where: str) -> np.ndarray:
    if ncols <= 0 or vals.size != ncols * nrows:
        raise ValueError(
            f"{where}: ragged CSV — {vals.size} values over {nrows} rows "
            f"do not form a rectangular {nrows}x{ncols} matrix"
        )
    return vals.reshape(nrows, ncols)


def _read_csv_matrix(path: str) -> np.ndarray:
    """CSV -> (rows, cols) float matrix via the native parser when available
    (keystone_tpu/native — the host-side data-plane tier), else numpy."""
    with open(path, "rb") as f:
        text = f.read()
    vals, ncols, nrows = native.parse_csv_floats(text)
    return _check_rect(vals, ncols, nrows, path)


def _read_csv_matrices(paths: List[str]) -> List[np.ndarray]:
    """Parse many CSV files through the native thread pool (one task per
    file), falling back to sequential parsing without the native library.
    Empty files contribute no rows (sc.textFile semantics — e.g. Spark
    _SUCCESS markers)."""
    texts = []
    for p in paths:
        with open(p, "rb") as f:
            texts.append(f.read())
    many = native.parse_csv_floats_many(texts)
    if many is None:
        many = [native.parse_csv_floats(t) for t in texts]
    return [
        _check_rect(vals, ncols, nrows, path)
        for path, (vals, ncols, nrows) in zip(paths, many)
        if nrows > 0
    ]


def csv_data_loader(path: str) -> Dataset:
    """CSV of comma-separated numbers -> Dataset of rows
    (reference: loaders/CsvDataLoader.scala:10-31).

    Like the reference's ``sc.textFile``, ``path`` may be a directory: every
    regular file inside is parsed (concurrently, in the native thread pool)
    and the row blocks are concatenated in sorted-filename order."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if os.path.isfile(os.path.join(path, f)) and not f.startswith(".")
        )
        if not files:
            raise ValueError(f"{path}: directory contains no files")
        mats = _read_csv_matrices(files)
        if not mats:
            raise ValueError(f"{path}: no data rows in any file")
        widths = {m.shape[1] for m in mats}
        if len(widths) != 1:
            raise ValueError(f"{path}: files disagree on column count {widths}")
        return Dataset.of(np.concatenate(mats, axis=0))
    return Dataset.of(_read_csv_matrix(path))


def load_labeled_csv(path: str, label_offset: int = 0) -> LabeledData:
    """CSV rows of [label, features...] -> LabeledData.

    label_offset shifts labels (the MNIST files are 1-indexed; the pipelines
    subtract 1, reference: pipelines/images/mnist/MnistRandomFFT.scala:34-37).
    """
    rows = _read_csv_matrix(path)
    labels = rows[:, 0].astype(np.int64) + label_offset
    return LabeledData(rows[:, 1:], labels)


def csv_to_disk_shards(
    path: str,
    out_dir: str,
    shard_rows: int,
    tiles_per_segment: int = 4,
    label_col: Optional[int] = 0,
    label_offset: int = 0,
    num_classes: Optional[int] = None,
) -> LabeledData:
    """The loaders' out-of-core spill path: CSV file(s) -> pre-tiled disk
    shards, ONE FILE RESIDENT AT A TIME, returning a shard-backed
    LabeledData (reference analog: CsvDataLoader's lazy ``textFile`` never
    collects either — the dataset goes storage-to-storage).

    ``path`` may be a directory (files parsed in sorted order, matching
    ``csv_data_loader``); host residency is bounded by the largest single
    file plus the shard memmap pages being filled. ``label_col`` selects
    the label column (None: all columns are features and labels must come
    from elsewhere — unsupported here); integer class labels become ±1
    one-hot targets when ``num_classes`` is given, else a (n, 1) float
    column. ``shard_rows`` need not divide the row count — the ragged
    final shard is zero-padded and masked by ``n_true`` at fold time.
    """
    if label_col is None:
        raise ValueError("csv_to_disk_shards needs a label column")
    from .shards import DiskDenseShardWriter

    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if os.path.isfile(os.path.join(path, f)) and not f.startswith(".")
        )
        if not files:
            raise ValueError(f"{path}: directory contains no files")
    else:
        files = [path]

    # Capacity pass: a newline count upper-bounds the row count per file
    # (blank lines overcount; the +1 covers a missing trailing newline).
    # The writer tolerates overshoot — unwritten tail tiles stay sparse
    # zero-fill and close() records only the rows actually appended.
    # Counted in fixed-size chunks: this path exists for files too big
    # to hold, so the counting pass must not become the residency peak.
    capacity = 0
    for p in files:
        last = b""
        with open(p, "rb") as f:
            while True:
                buf = f.read(16 << 20)
                if not buf:
                    break
                capacity += buf.count(b"\n")
                last = buf[-1:]
        if last and last != b"\n":
            capacity += 1
    if capacity == 0:
        raise ValueError(f"{path}: no data rows in any file")

    writer = None
    width = None
    for p in files:
        if os.path.getsize(p) == 0:
            continue  # sc.textFile semantics: empty files contribute nothing
        rows = _read_csv_matrix(p)
        if rows.shape[0] == 0:
            continue
        if width is None:
            width = rows.shape[1]
        elif rows.shape[1] != width:
            raise ValueError(
                f"{path}: files disagree on column count "
                f"{{{width}, {rows.shape[1]}}}"
            )
        feats = np.delete(rows, label_col, axis=1).astype(
            np.float32, copy=False
        )
        if num_classes is not None:
            from .dataset import one_hot_pm1

            Y = one_hot_pm1(
                rows[:, label_col].astype(np.int64) + label_offset,
                num_classes,
            )
        else:
            # Continuous targets: keep the float column exactly as read
            # (label_offset still applies — it is additive either way).
            Y = (rows[:, label_col] + label_offset).astype(
                np.float32
            )[:, None]
        if writer is None:
            writer = DiskDenseShardWriter(
                out_dir, capacity, feats.shape[1], Y.shape[1],
                tile_rows=int(shard_rows),
                tiles_per_segment=tiles_per_segment,
            )
        writer.append(feats, Y)
    if writer is None:
        raise ValueError(f"{path}: no data rows in any file")
    return writer.close().as_labeled_data()


CIFAR_LABEL_SIZE = 1
CIFAR_IMAGE_BYTES = 3072  # 32*32*3
CIFAR_RECORD_BYTES = CIFAR_LABEL_SIZE + CIFAR_IMAGE_BYTES


def load_cifar_binary(path: str) -> LabeledData:
    """CIFAR-10 binary format: 3073-byte records of [label, 3072 pixel bytes]
    (reference: loaders/CifarLoader.scala:14-53). Images come out as
    (n, 32, 32, 3) float32 in [0, 255] (pixel bytes are exact in float32).

    The record deinterleave + planar->HWC conversion runs in the threaded
    native data plane when available."""
    with open(path, "rb") as f:
        raw_bytes = f.read()
    if len(raw_bytes) % CIFAR_RECORD_BYTES != 0:
        raise ValueError(f"{path}: not a multiple of {CIFAR_RECORD_BYTES} bytes")
    split = native.split_records(raw_bytes, CIFAR_LABEL_SIZE, 3, 32, 32)
    if split is not None:
        labels, images = split
        return LabeledData(images, labels)
    records = np.frombuffer(raw_bytes, dtype=np.uint8).reshape(
        -1, CIFAR_RECORD_BYTES
    )
    labels = records[:, 0].astype(np.int64)
    # CIFAR stores channel-planar (RGB planes); convert to HWC.
    images = (
        records[:, 1:]
        .reshape(-1, 3, 32, 32)
        .transpose(0, 2, 3, 1)
        .astype(np.float32)
    )
    return LabeledData(images, labels)


class TimitFeaturesDataLoader:
    """TIMIT: CSV feature frames (440 dims) + sparse label files, 147 classes
    (reference: loaders/TimitFeaturesDataLoader.scala:16-70)."""

    num_classes = 147
    num_features = 440

    def __init__(self, feature_path: str, label_path: str):
        feats = _read_csv_matrix(feature_path)
        labels = self._parse_sparse_labels(label_path, feats.shape[0])
        self.labeled = LabeledData(feats, labels)

    @staticmethod
    def _parse_sparse_labels(path: str, n: int) -> np.ndarray:
        """Label file lines: ``row_index label`` (sparse row labels)."""
        labels = np.zeros(n, dtype=np.int64)
        with open(path) as f:
            for line in f:
                parts = line.replace(",", " ").split()
                if len(parts) >= 2:
                    labels[int(parts[0])] = int(parts[1])
        return labels


def load_newsgroups(path: str, class_dirs: Optional[List[str]] = None) -> LabeledData:
    """20-newsgroups layout: one directory per class of text files
    (reference: loaders/NewsgroupsDataLoader.scala:9-57)."""
    class_dirs = class_dirs or sorted(
        d for d in os.listdir(path) if os.path.isdir(os.path.join(path, d))
    )
    texts, labels = [], []
    for label, cls in enumerate(class_dirs):
        cls_path = os.path.join(path, cls)
        for fname in sorted(os.listdir(cls_path)):
            with open(os.path.join(cls_path, fname), errors="replace") as f:
                texts.append(f.read())
            labels.append(label)
    return LabeledData(Dataset(texts), Dataset.of(np.asarray(labels)))


def load_amazon_reviews(path: str, threshold: float = 3.5) -> LabeledData:
    """Amazon product reviews: JSON-lines with "overall" and "reviewText";
    rating >= threshold -> label 1 else 0
    (reference: loaders/AmazonReviewsDataLoader.scala:7-28)."""
    paths = [path]
    if os.path.isdir(path):
        paths = [
            p
            for f in sorted(os.listdir(path))
            if os.path.isfile(p := os.path.join(path, f))
        ]
    texts: List[str] = []
    labels: List[int] = []
    for p in paths:
        with open(p, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                texts.append(rec.get("reviewText", ""))
                labels.append(1 if float(rec.get("overall", 0.0)) >= threshold else 0)
    return LabeledData(Dataset(texts), Dataset.of(np.asarray(labels, dtype=np.int64)))


# ---------------------------------------------------------------------------
# Image archive loading (reference: loaders/ImageLoaderUtils.scala:21-94,
# VOCLoader.scala:16-53, ImageNetLoader.scala:12-39)
# ---------------------------------------------------------------------------


@dataclass
class LabeledImage:
    """(image, int label, filename) (reference: utils/LabeledImage)."""

    image: np.ndarray
    label: int
    filename: str = ""


@dataclass
class MultiLabeledImage:
    """(image, multi-label array, filename) (reference: utils/MultiLabeledImage)."""

    image: np.ndarray
    labels: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.int64))
    filename: str = ""


def decode_image_bytes(data: bytes) -> Optional[np.ndarray]:
    """Decode image bytes to float32 (x, y, c). PNM rides the native C++
    decoder (keystone_tpu/native); other formats decode via PIL — the role
    javax.imageio plays in the reference (ImageLoaderUtils.scala:60-84)."""
    if data[:2] in (b"P5", b"P6"):
        from keystone_tpu import native

        arr = native.decode_pnm(data)
        if arr is not None:
            return arr
    try:
        from keystone_tpu.utils.images import load_image

        return np.asarray(load_image(data))
    except Exception:
        return None


def iter_tar_images(tar_path: str):
    """Yield (member_name, decoded image) from a tar of image files
    (reference: ImageLoaderUtils.loadTarFiles).

    PNM members are batch-decoded through the native thread pool (the
    data-plane analog of the reference's per-worker JNI decodes); other
    formats fall back to per-member PIL decode.
    """
    CHUNK = 64  # bound peak memory: raws + decode buffers per chunk only

    def flush(names, raws):
        pnm_idx = [i for i, d in enumerate(raws) if d[:2] in (b"P5", b"P6")]
        decoded: Dict[int, Optional[np.ndarray]] = {}
        if pnm_idx:
            many = native.decode_pnm_many([raws[i] for i in pnm_idx])
            if many is not None:
                decoded = dict(zip(pnm_idx, many))
        for i, (name, data) in enumerate(zip(names, raws)):
            img = decoded.get(i)
            if img is None:
                img = decode_image_bytes(data)
            if img is not None:
                yield name, img

    names: List[str] = []
    raws: List[bytes] = []
    with tarfile.open(tar_path) as tf:
        for member in tf.getmembers():
            if not member.isfile():
                continue
            f = tf.extractfile(member)
            if f is None:
                continue
            names.append(member.name)
            raws.append(f.read())
            if len(raws) >= CHUNK:
                yield from flush(names, raws)
                names, raws = [], []
    yield from flush(names, raws)


def _tar_paths(data_path: str) -> List[str]:
    if os.path.isdir(data_path):
        return [
            os.path.join(data_path, f)
            for f in sorted(os.listdir(data_path))
            if f.endswith(".tar")
        ]
    return [data_path]


def load_imagenet(data_path: str, labels_path: str) -> Dataset:
    """Tars of JPEGs under class-name directories + "classname label" map
    file -> Dataset of LabeledImage (reference: ImageNetLoader.scala:12-39)."""
    labels_map: Dict[str, int] = {}
    with open(labels_path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                labels_map[parts[0]] = int(parts[1])

    from keystone_tpu.utils.images import crop_to_multiple

    out: List[LabeledImage] = []
    for tar_path in _tar_paths(data_path):
        for name, img in iter_tar_images(tar_path):
            cls = name.split("/")[0]
            if cls in labels_map:
                # Shape-bucket photos so similar sizes share XLA executables.
                out.append(LabeledImage(crop_to_multiple(img), labels_map[cls], name))
    return Dataset(out)


VOC_NUM_CLASSES = 20


def load_voc(data_path: str, labels_path: str, name_prefix: str = "") -> Dataset:
    """VOC2007 tar + CSV multi-labels -> Dataset of MultiLabeledImage
    (reference: VOCLoader.scala:29-50, ImageLoaderUtils.scala:72-92). The CSV
    has a header; column 4 is the quoted filename — the FULL tar entry path,
    which is also the label-map key and the stored filename — and column 1 the
    1-based class id. ``name_prefix`` filters full entry names (the
    reference's namePrefix, e.g. "VOCdevkit/VOC2007/JPEGImages/")."""
    from keystone_tpu.utils.images import crop_to_multiple

    labels_map: Dict[str, List[int]] = {}
    with open(labels_path) as f:
        next(f)  # header
        for line in f:
            parts = line.strip().split(",")
            if len(parts) >= 5:
                fname = parts[4].replace('"', "")
                labels_map.setdefault(fname, []).append(int(parts[1]) - 1)

    out: List[MultiLabeledImage] = []
    for tar_path in _tar_paths(data_path):
        for name, img in iter_tar_images(tar_path):
            if name_prefix and not name.startswith(name_prefix):
                continue
            if name in labels_map:
                # Shape-bucket photos so similar sizes share XLA executables.
                out.append(
                    MultiLabeledImage(
                        crop_to_multiple(img),
                        np.asarray(sorted(labels_map[name])),
                        name,
                    )
                )
    return Dataset(out)


# ---------------------------------------------------------------------------
# Synthetic data (hermetic stand-ins for the reference workloads)
# ---------------------------------------------------------------------------


def synthetic_classification(
    n: int,
    d: int,
    num_classes: int,
    seed: int = 0,
    class_sep: float = 1.0,
    means_seed: int = 1234,
) -> LabeledData:
    """Gaussian blobs: one mean per class, unit covariance.

    The class means are drawn from ``means_seed`` (fixed across train/test
    splits); ``seed`` only drives the sampling, so different seeds give i.i.d.
    draws from the *same* distribution.
    """
    means = np.random.default_rng(means_seed).normal(
        scale=class_sep, size=(num_classes, d)
    )
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n)
    X = means[labels] + rng.normal(size=(n, d))
    return LabeledData(X, labels.astype(np.int64))


def synthetic_mnist(n: int = 4096, seed: int = 0) -> LabeledData:
    """MNIST-shaped synthetic data: 784-dim, 10 classes."""
    return synthetic_classification(n, 784, 10, seed=seed, class_sep=0.5)


def load_digits_real(train_fraction: float = 0.8, seed: int = 0):
    """Real handwritten-digit data (UCI optical digits, 1797 8×8 images,
    bundled with scikit-learn — the real-data stand-in for MNIST in this
    offline environment). Returns (train: LabeledData, test: LabeledData)
    with pixel values scaled to [0, 1], deterministic shuffled split.
    """
    from sklearn.datasets import load_digits

    bunch = load_digits()
    X = bunch.data.astype(np.float64) / 16.0
    y = bunch.target.astype(np.int64)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(y))
    X, y = X[order], y[order]
    n_train = int(len(y) * train_fraction)
    return (
        LabeledData(X[:n_train], y[:n_train]),
        LabeledData(X[n_train:], y[n_train:]),
    )


def synthetic_timit(n: int = 8192, seed: int = 0) -> LabeledData:
    """TIMIT-shaped synthetic data: 440-dim frames, 147 classes."""
    return synthetic_classification(
        n, TimitFeaturesDataLoader.num_features, TimitFeaturesDataLoader.num_classes,
        seed=seed, class_sep=0.6,
    )


def synthetic_cifar(n: int = 256, seed: int = 0, num_classes: int = 10) -> LabeledData:
    """CIFAR-shaped synthetic images: (n, 32, 32, 3) in [0, 255] with a
    class-dependent low-frequency pattern plus noise, so convolutional
    featurizers have signal to find."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n)
    yy, xx = np.meshgrid(np.arange(32), np.arange(32), indexing="ij")
    # One spatial frequency/phase pattern per class (fixed across splits).
    pat_rng = np.random.default_rng(1234)
    freqs = pat_rng.uniform(0.2, 1.2, size=(num_classes, 2))
    phases = pat_rng.uniform(0, 2 * np.pi, size=(num_classes, 3))
    images = np.empty((n, 32, 32, 3), dtype=np.float64)
    for c in range(num_classes):
        base = np.stack(
            [
                np.sin(freqs[c, 0] * xx + freqs[c, 1] * yy + phases[c, ch])
                for ch in range(3)
            ],
            axis=-1,
        )
        mask = labels == c
        images[mask] = 127.5 + 90.0 * base
    images += rng.normal(scale=25.0, size=images.shape)
    return LabeledData(np.clip(images, 0, 255), labels.astype(np.int64))


def synthetic_documents(
    n: int,
    num_classes: int,
    seed: int = 0,
    doc_len: int = 40,
    vocab_per_class: int = 30,
    shared_vocab: int = 60,
) -> LabeledData:
    """Synthetic text classification corpus: each class has a private vocab
    mixed with a shared vocab; documents are whitespace-joined word samples.
    Data is a host list of strings (the loaders' wholeTextFiles analog)."""
    rng = np.random.default_rng(seed)
    shared = [f"word{i}" for i in range(shared_vocab)]
    private = [
        [f"c{c}term{i}" for i in range(vocab_per_class)] for c in range(num_classes)
    ]
    labels = rng.integers(0, num_classes, size=n)
    docs = []
    for lab in labels:
        k_private = rng.binomial(doc_len, 0.5)
        words = list(rng.choice(private[lab], size=k_private)) + list(
            rng.choice(shared, size=doc_len - k_private)
        )
        rng.shuffle(words)
        docs.append(" ".join(words))
    return LabeledData(list(docs), labels.astype(np.int64))


def synthetic_sentences(n: int = 200, seed: int = 0, sentence_len: int = 12) -> Dataset:
    """Synthetic corpus of sentences over a small Zipf-ish vocabulary (for the
    StupidBackoff language-model pipeline)."""
    rng = np.random.default_rng(seed)
    vocab = [f"w{i}" for i in range(50)]
    probs = 1.0 / np.arange(1, len(vocab) + 1)
    probs /= probs.sum()
    sents = [
        " ".join(rng.choice(vocab, size=sentence_len, p=probs)) for _ in range(n)
    ]
    return Dataset.of(sents)
