"""Dataset: the distributed-collection abstraction replacing RDDs.

The reference moves `RDD[DenseVector]` (or `RDD[Image]`, `RDD[String]`...)
through pipelines, packing rows into per-partition matrices for BLAS-3
(reference: utils/MatrixUtils.scala:48-61, workflow/Operator.scala:25-38).
The TPU-native analog is batch-major arrays:

  - **Array form** (the common case): ``data`` is a pytree of arrays sharing a
    leading example axis, usually one ``(n, d)`` array; it may be zero-padded
    to a multiple of the mesh ``data`` axis and sharded over the mesh. Padding
    rows are all-zero so Gramians/moment sums are unaffected; ``n`` tracks the
    true example count.
  - **Host form**: a Python list of arbitrary objects (images before decode,
    token sequences) for stages that must run host-side.
  - **Shard form**: ``data`` is a :class:`~keystone_tpu.data.prefetch.
    ShardSource` — ordered disk/host segments delivered one at a time, for
    datasets whose resident size exceeds the host-RAM budget. Streamed
    solvers consume the source directly (prefetched, never resident);
    anything else triggers ``materialize()``, which only small sources
    should ever hit.

Transformers consume and produce Datasets; solvers read ``.array`` +
``.n`` directly and run jit-compiled sharded computations on them.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as mesh_lib

from .prefetch import ShardSource


def _is_arraylike(x: Any) -> bool:
    return isinstance(x, (np.ndarray, jax.Array)) or (
        hasattr(x, "shape") and hasattr(x, "dtype")
    )


class Dataset:
    """A batch of n examples, in device-array or host-list form."""

    def __init__(self, data: Any, n: Optional[int] = None, mesh=None):
        if isinstance(data, Dataset):
            raise TypeError("Dataset(data) may not wrap another Dataset")
        self.data = data
        self.mesh = mesh
        if isinstance(data, list):
            self.n = len(data) if n is None else n
        elif isinstance(data, ShardSource):
            self.n = data.n_true if n is None else n
        else:
            leaves = jax.tree_util.tree_leaves(data)
            if not leaves:
                raise ValueError("Array dataset must contain at least one array")
            self.n = int(leaves[0].shape[0]) if n is None else n

    # -- constructors -------------------------------------------------------

    @staticmethod
    def of(data: Any, mesh=None) -> "Dataset":
        """Wrap a list (host form) or array-like / pytree (array form)."""
        if isinstance(data, Dataset):
            return data
        if isinstance(data, list) and not (data and _is_arraylike(data[0])):
            return Dataset(list(data))
        if isinstance(data, list):
            # list of per-example arrays with identical shapes -> stack;
            # ragged -> host form
            shapes = {np.shape(x) for x in data}
            if len(shapes) == 1:
                return Dataset(np.stack([np.asarray(x) for x in data]), mesh=mesh)
            return Dataset(list(data))
        return Dataset(data, mesh=mesh)

    @staticmethod
    def gather(branches: List["Dataset"]) -> "Dataset":
        """Zip branches into a dataset of tuples (GatherTransformerOperator.scala:9-18)."""
        ns = {b.n for b in branches}
        if len(ns) != 1:
            raise ValueError(f"Gathered branches must have equal sizes, got {ns}")
        if all(not b.is_host for b in branches):
            return Dataset(tuple(b.data for b in branches), n=branches[0].n,
                           mesh=branches[0].mesh)
        items = [b.to_list() for b in branches]
        return Dataset([tuple(vals) for vals in zip(*items)])

    @staticmethod
    def from_shards(source: ShardSource, n: Optional[int] = None) -> "Dataset":
        """A Dataset backed by an out-of-core :class:`ShardSource`."""
        return Dataset(source, n=n)

    # -- properties ---------------------------------------------------------

    @property
    def is_host(self) -> bool:
        return isinstance(self.data, list)

    @property
    def is_shard_backed(self) -> bool:
        return isinstance(self.data, ShardSource)

    @property
    def shard_source(self) -> ShardSource:
        if not self.is_shard_backed:
            raise ValueError("Dataset is not shard-backed")
        return self.data

    def materialize(self) -> "Dataset":
        """Shard form -> array form (concatenates every segment; only
        sources that fit host RAM should ever reach this — the streamed
        solvers consume the source directly instead)."""
        if not self.is_shard_backed:
            return self
        mat = self.data.materialize()
        if isinstance(mat, tuple):
            mat = mat[0]  # a paired (X, Y) source read as a data Dataset
        return Dataset(np.asarray(mat), n=self.n, mesh=self.mesh)

    @property
    def array(self):
        """The single underlying array (errors for host/tuple datasets)."""
        if self.is_shard_backed:
            return self.materialize().array
        if self.is_host:
            arr = np.stack([np.asarray(x) for x in self.data])
            return arr
        leaves = jax.tree_util.tree_leaves(self.data)
        if isinstance(self.data, (tuple, list)) or len(leaves) != 1:
            raise ValueError("Dataset holds a pytree; use .data")
        return leaves[0]

    @property
    def num_padded(self) -> int:
        if self.is_host:
            return len(self.data)
        if self.is_shard_backed:
            return self.n
        return int(jax.tree_util.tree_leaves(self.data)[0].shape[0])

    def __len__(self) -> int:
        return self.n

    # -- transforms ---------------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Apply `fn` per example. Host form: Python map. Array form: vmap,
        falling back to a host loop if `fn` is not traceable."""
        if self.is_shard_backed:
            return self.materialize().map(fn)
        if self.is_host:
            out = [fn(x) for x in self.data]
            return Dataset.of(out)
        try:
            mapped = jax.vmap(fn)(self.data)
            return Dataset(mapped, n=self.n, mesh=self.mesh)._rezero_padding()
        except Exception:
            items = self.to_list()
            return Dataset.of([fn(x) for x in items])

    def map_batch(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Apply a whole-batch (vectorized) function to the array form."""
        if self.is_shard_backed:
            return self.materialize().map_batch(fn)
        out = fn(self.data)
        return Dataset(out, n=self.n, mesh=self.mesh)._rezero_padding()

    def _rezero_padding(self) -> "Dataset":
        """Restore the all-zero-padding invariant after a non-zero-preserving
        transform (padding rows must not pollute Gramians/moment sums)."""
        if self.is_host or self.num_padded == self.n:
            return self
        mask = jnp.arange(self.num_padded) < self.n

        def zero(leaf):
            m = mask.reshape((-1,) + (1,) * (leaf.ndim - 1))
            return jnp.where(m, leaf, jnp.zeros((), dtype=leaf.dtype))

        data = jax.tree_util.tree_map(zero, self.data)
        return Dataset(data, n=self.n, mesh=self.mesh)

    def to_list(self) -> List[Any]:
        """Materialize as a host list of per-example values (padding dropped)."""
        if self.is_shard_backed:
            return self.materialize().to_list()
        if self.is_host:
            return list(self.data)
        if isinstance(self.data, tuple):
            parts = [np.asarray(leaf)[: self.n] for leaf in self.data]
            return [tuple(p[i] for p in parts) for i in range(self.n)]
        return list(np.asarray(self.array)[: self.n])

    def to_numpy(self) -> np.ndarray:
        """The underlying array with padding rows dropped, as numpy."""
        return np.asarray(self.array)[: self.n]

    # -- distribution -------------------------------------------------------

    def shard(self, mesh=None, axis: str = mesh_lib.DATA_AXIS) -> "Dataset":
        """Pad to divisibility and shard the leading axis over the mesh."""
        if self.is_shard_backed:
            return self.materialize().shard(mesh, axis)
        if self.is_host:
            raise ValueError("Host datasets cannot be device-sharded; vectorize first")
        mesh = mesh or mesh_lib.default_mesh()
        size = mesh_lib.axis_size(mesh, axis)

        def place(leaf):
            padded, _ = mesh_lib.pad_rows(np.asarray(leaf), size)
            return mesh_lib.shard_rows(padded, mesh, axis)

        data = jax.tree_util.tree_map(place, self.data)
        return Dataset(data, n=self.n, mesh=mesh)

    def cache(self) -> "Dataset":
        """Force materialization now (the Cacher analog). Device arrays are
        already materialized eagerly by JAX; this just blocks until ready."""
        if not self.is_host and not self.is_shard_backed:
            jax.block_until_ready(jax.tree_util.tree_leaves(self.data))
        return self

    def valid_mask(self):
        """(num_padded,) float mask: 1 for real rows, 0 for padding."""
        npad = self.num_padded
        return (jnp.arange(npad) < self.n).astype(jnp.float32)

    def __repr__(self) -> str:
        if self.is_shard_backed:
            return (
                f"Dataset(shards, n={self.n}, "
                f"segments={self.data.num_segments})"
            )
        if self.is_host:
            return f"Dataset(host, n={self.n})"
        shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), self.data)
        return f"Dataset(array, n={self.n}, shapes={shapes})"


def one_hot_pm1(labels: np.ndarray, num_classes: int) -> np.ndarray:
    """Integer class labels -> the ±1 one-hot regression targets every LS
    pipeline here fits against (the host-side twin of
    ``ClassLabelIndicatorsFromIntLabels``): one shared encoding for every
    spill/bench site instead of hand-rolled copies."""
    return (
        2.0 * np.eye(num_classes, dtype=np.float32)[
            np.asarray(labels, dtype=np.int64).reshape(-1)
        ] - 1.0
    )


class LabeledData:
    """A (data, labels) pair of aligned Datasets (loaders/LabeledData.scala:12-15)."""

    def __init__(self, data: Any, labels: Any):
        self.data = Dataset.of(data)
        self.labels = Dataset.of(labels)
        if self.data.n != self.labels.n:
            raise ValueError(
                f"data ({self.data.n}) and labels ({self.labels.n}) must align"
            )

    def to_disk_shards(
        self,
        path: str,
        shard_rows: int,
        tiles_per_segment: int = 4,
        num_classes: Optional[int] = None,
    ) -> "LabeledData":
        """Spill this (data, labels) pair to pre-tiled disk shards and
        return a SHARD-BACKED LabeledData over the files — the loaders'
        materialize-to-disk-instead-of-RAM path. Integer class labels
        become ±1 one-hot regression targets when ``num_classes`` is
        given (the convention every LS pipeline here uses); otherwise
        labels are stored as-is, reshaped to (n, k)."""
        from .shards import DiskDenseShards

        X = np.asarray(self.data.array)[: self.data.n]
        Y = np.asarray(self.labels.array)[: self.labels.n]
        if num_classes is not None:
            Y = one_hot_pm1(Y, num_classes)
        elif Y.ndim == 1:
            Y = Y[:, None]
        shards = DiskDenseShards.write(
            path, X, Y.astype(np.float32, copy=False),
            tile_rows=int(shard_rows), tiles_per_segment=tiles_per_segment,
        )
        return shards.as_labeled_data()
