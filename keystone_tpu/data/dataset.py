"""Dataset: the distributed-collection abstraction replacing RDDs.

The reference moves `RDD[DenseVector]` (or `RDD[Image]`, `RDD[String]`...)
through pipelines, packing rows into per-partition matrices for BLAS-3
(reference: utils/MatrixUtils.scala:48-61, workflow/Operator.scala:25-38).
The TPU-native analog is batch-major arrays:

  - **Array form** (the common case): ``data`` is a pytree of arrays sharing a
    leading example axis, usually one ``(n, d)`` array; it may be zero-padded
    to a multiple of the mesh ``data`` axis and sharded over the mesh. Padding
    rows are all-zero so Gramians/moment sums are unaffected; ``n`` tracks the
    true example count.
  - **Host form**: a Python list of arbitrary objects (images before decode,
    token sequences) for stages that must run host-side.

Transformers consume and produce Datasets; solvers read ``.array`` +
``.n`` directly and run jit-compiled sharded computations on them.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as mesh_lib


def _is_arraylike(x: Any) -> bool:
    return isinstance(x, (np.ndarray, jax.Array)) or (
        hasattr(x, "shape") and hasattr(x, "dtype")
    )


class Dataset:
    """A batch of n examples, in device-array or host-list form."""

    def __init__(self, data: Any, n: Optional[int] = None, mesh=None):
        if isinstance(data, Dataset):
            raise TypeError("Dataset(data) may not wrap another Dataset")
        self.data = data
        self.mesh = mesh
        if isinstance(data, list):
            self.n = len(data) if n is None else n
        else:
            leaves = jax.tree_util.tree_leaves(data)
            if not leaves:
                raise ValueError("Array dataset must contain at least one array")
            self.n = int(leaves[0].shape[0]) if n is None else n

    # -- constructors -------------------------------------------------------

    @staticmethod
    def of(data: Any, mesh=None) -> "Dataset":
        """Wrap a list (host form) or array-like / pytree (array form)."""
        if isinstance(data, Dataset):
            return data
        if isinstance(data, list) and not (data and _is_arraylike(data[0])):
            return Dataset(list(data))
        if isinstance(data, list):
            # list of per-example arrays with identical shapes -> stack;
            # ragged -> host form
            shapes = {np.shape(x) for x in data}
            if len(shapes) == 1:
                return Dataset(np.stack([np.asarray(x) for x in data]), mesh=mesh)
            return Dataset(list(data))
        return Dataset(data, mesh=mesh)

    @staticmethod
    def gather(branches: List["Dataset"]) -> "Dataset":
        """Zip branches into a dataset of tuples (GatherTransformerOperator.scala:9-18)."""
        ns = {b.n for b in branches}
        if len(ns) != 1:
            raise ValueError(f"Gathered branches must have equal sizes, got {ns}")
        if all(not b.is_host for b in branches):
            return Dataset(tuple(b.data for b in branches), n=branches[0].n,
                           mesh=branches[0].mesh)
        items = [b.to_list() for b in branches]
        return Dataset([tuple(vals) for vals in zip(*items)])

    # -- properties ---------------------------------------------------------

    @property
    def is_host(self) -> bool:
        return isinstance(self.data, list)

    @property
    def array(self):
        """The single underlying array (errors for host/tuple datasets)."""
        if self.is_host:
            arr = np.stack([np.asarray(x) for x in self.data])
            return arr
        leaves = jax.tree_util.tree_leaves(self.data)
        if isinstance(self.data, (tuple, list)) or len(leaves) != 1:
            raise ValueError("Dataset holds a pytree; use .data")
        return leaves[0]

    @property
    def num_padded(self) -> int:
        if self.is_host:
            return len(self.data)
        return int(jax.tree_util.tree_leaves(self.data)[0].shape[0])

    def __len__(self) -> int:
        return self.n

    # -- transforms ---------------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Apply `fn` per example. Host form: Python map. Array form: vmap,
        falling back to a host loop if `fn` is not traceable."""
        if self.is_host:
            out = [fn(x) for x in self.data]
            return Dataset.of(out)
        try:
            mapped = jax.vmap(fn)(self.data)
            return Dataset(mapped, n=self.n, mesh=self.mesh)._rezero_padding()
        except Exception:
            items = self.to_list()
            return Dataset.of([fn(x) for x in items])

    def map_batch(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Apply a whole-batch (vectorized) function to the array form."""
        out = fn(self.data)
        return Dataset(out, n=self.n, mesh=self.mesh)._rezero_padding()

    def _rezero_padding(self) -> "Dataset":
        """Restore the all-zero-padding invariant after a non-zero-preserving
        transform (padding rows must not pollute Gramians/moment sums)."""
        if self.is_host or self.num_padded == self.n:
            return self
        mask = jnp.arange(self.num_padded) < self.n

        def zero(leaf):
            m = mask.reshape((-1,) + (1,) * (leaf.ndim - 1))
            return jnp.where(m, leaf, jnp.zeros((), dtype=leaf.dtype))

        data = jax.tree_util.tree_map(zero, self.data)
        return Dataset(data, n=self.n, mesh=self.mesh)

    def to_list(self) -> List[Any]:
        """Materialize as a host list of per-example values (padding dropped)."""
        if self.is_host:
            return list(self.data)
        if isinstance(self.data, tuple):
            parts = [np.asarray(leaf)[: self.n] for leaf in self.data]
            return [tuple(p[i] for p in parts) for i in range(self.n)]
        return list(np.asarray(self.array)[: self.n])

    def to_numpy(self) -> np.ndarray:
        """The underlying array with padding rows dropped, as numpy."""
        return np.asarray(self.array)[: self.n]

    # -- distribution -------------------------------------------------------

    def shard(self, mesh=None, axis: str = mesh_lib.DATA_AXIS) -> "Dataset":
        """Pad to divisibility and shard the leading axis over the mesh."""
        if self.is_host:
            raise ValueError("Host datasets cannot be device-sharded; vectorize first")
        mesh = mesh or mesh_lib.default_mesh()
        size = mesh_lib.axis_size(mesh, axis)

        def place(leaf):
            padded, _ = mesh_lib.pad_rows(np.asarray(leaf), size)
            return mesh_lib.shard_rows(padded, mesh, axis)

        data = jax.tree_util.tree_map(place, self.data)
        return Dataset(data, n=self.n, mesh=mesh)

    def cache(self) -> "Dataset":
        """Force materialization now (the Cacher analog). Device arrays are
        already materialized eagerly by JAX; this just blocks until ready."""
        if not self.is_host:
            jax.block_until_ready(jax.tree_util.tree_leaves(self.data))
        return self

    def valid_mask(self):
        """(num_padded,) float mask: 1 for real rows, 0 for padding."""
        npad = self.num_padded
        return (jnp.arange(npad) < self.n).astype(jnp.float32)

    def __repr__(self) -> str:
        if self.is_host:
            return f"Dataset(host, n={self.n})"
        shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), self.data)
        return f"Dataset(array, n={self.n}, shapes={shapes})"


class LabeledData:
    """A (data, labels) pair of aligned Datasets (loaders/LabeledData.scala:12-15)."""

    def __init__(self, data: Any, labels: Any):
        self.data = Dataset.of(data)
        self.labels = Dataset.of(labels)
        if self.data.n != self.labels.n:
            raise ValueError(
                f"data ({self.data.n}) and labels ({self.labels.n}) must align"
            )
