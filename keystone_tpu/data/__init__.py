"""Data plane: the Dataset abstraction, data loaders, and the out-of-core
shard/prefetch tier (disk-backed Datasets streamed through the solvers) —
checksummed, atomically written, and retry-wrapped (docs/reliability.md)."""

from .dataset import Dataset, LabeledData, one_hot_pm1
from .durable import CheckpointSpec, ShardCorrupted
from .resident import CompressedCOOChunks, raw_chunk_tiles
from .runtime import DataPlaneRuntime, default_runtime
from .prefetch import (
    COOShardSource,
    DenseShardSource,
    DenseShardView,
    PairedDenseSource,
    Prefetcher,
    PrefetchStats,
    ResidentDenseSource,
    ShardSource,
    iter_segments,
)
from .shards import DiskCOOShards, DiskDenseShards, DiskDenseShardWriter
from .images import (
    EncodedImageSource,
    SyntheticEncodedImages,
    images_to_disk_shards,
    load_images,
)

__all__ = [
    "CheckpointSpec",
    "CompressedCOOChunks",
    "DataPlaneRuntime",
    "Dataset",
    "default_runtime",
    "LabeledData",
    "ShardCorrupted",
    "one_hot_pm1",
    "ShardSource",
    "DenseShardSource",
    "DenseShardView",
    "PairedDenseSource",
    "ResidentDenseSource",
    "COOShardSource",
    "Prefetcher",
    "PrefetchStats",
    "iter_segments",
    "DiskCOOShards",
    "DiskDenseShards",
    "DiskDenseShardWriter",
    "EncodedImageSource",
    "SyntheticEncodedImages",
    "images_to_disk_shards",
    "load_images",
]
