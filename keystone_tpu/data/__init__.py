"""Data plane: the Dataset abstraction and data loaders."""

from .dataset import Dataset, LabeledData

__all__ = ["Dataset", "LabeledData"]
