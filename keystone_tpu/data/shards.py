"""Disk-backed chunk shards for the streamed solvers.

The reference streams from storage by construction — ``CsvDataLoader`` is
a lazy ``textFile`` (CsvDataLoader.scala:10-31) and image loaders decode
per partition (ImageLoaderUtils.scala:21-94) — so its fits are bounded by
disk, not RAM. The round-4 streamed folds here took their chunks from
HOST-RESIDENT arrays, bounding n by host RAM instead. This module closes
that gap: pre-tiled padded-COO shards live in ``.npy`` files, are opened
memory-mapped, and feed the segmented Gramian folds one SEGMENT at a time
(``run_lbfgs_gram_streamed(segment_source=...)``) — peak host residency
is the mmap page cache (OS-evictable) plus ``seg`` chunks of copy buffer,
regardless of dataset size.

Durability contract (docs/reliability.md): the reference inherited fault
tolerance from Spark lineage; raw ``.npy`` files inherit nothing, so the
formats here carry it explicitly —

  - **Meta is written last, atomically** (temp name + ``os.replace``,
    arrays fsync'd first): a killed writer leaves a directory with no
    (or the previous) metadata, never one that parses as a
    valid-but-short dataset. Writers also DELETE stale metadata before
    touching array files, so re-ingesting over an old directory can't
    resurrect the old meta against new partial arrays.
  - **Per-tile/chunk checksums** ride in the metadata and are verified
    on every ``segment_source`` read: torn or bit-flipped bytes raise
    :class:`~keystone_tpu.data.durable.ShardCorrupted` instead of
    feeding garbage into a fit. Directories written before this scheme
    (no ``checksums`` key) still load, unverified.
  - **Retrying reads**: transient ``OSError`` during a segment read is
    retried with bounded exponential backoff
    (:class:`~keystone_tpu.utils.faults.RetryPolicy`); exhaustion
    re-raises exactly as before. The ``shard.load`` fault site
    (:mod:`keystone_tpu.utils.faults`) makes both paths chaos-testable.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu.data.durable import (
    ShardCorrupted,
    atomic_write_json,
    checksum_algo,
    corrupted,
    crc_of_array,
    fsync_file,
    verify_array,
)
from keystone_tpu.utils import faults

_META = "shards.json"
_FILES = {"indices": "indices.npy", "values": "values.npy", "labels": "labels.npy"}


def _chunk_checksums(arr, num: int) -> List[int]:
    """Per-leading-index digests of ``arr[:num]`` (one CRC per chunk or
    tile — the verification granularity of a segment read)."""
    return [int(crc_of_array(arr[i])) for i in range(num)]


def _read_verified(arr, lo: int, hi: int, *, what: str, key: str,
                   checksums: Optional[List[int]], algo: str,
                   retry) -> np.ndarray:
    """THE durable read protocol, shared by both shard formats: copy
    units [lo, hi) out of the mmap with transient-retry (recovered
    retries reported to the consuming fit's stats via
    ``faults.observe_retry``) and per-unit checksum verification. The
    ``shard.load`` fault site fires once per read attempt; corruption
    injections land AFTER the copy so the checksum layer (not the mmap)
    is what catches them."""
    def read():
        faults.maybe_fail(faults.SITE_SHARD_LOAD)
        return np.asarray(arr[lo:hi])

    seg = retry.call(
        read, key=key,
        on_retry=lambda _a, delay_s, _e: faults.observe_retry(delay_s),
    )
    seg = faults.corrupt_array(faults.SITE_SHARD_LOAD, seg)
    if checksums is not None:
        import time as _time

        t0 = _time.perf_counter()
        for i in range(lo, hi):
            verify_array(seg[i - lo], checksums[i], algo, f"{what} {i}")
        # The `verify` site of the per-site overlap report: CRC time is
        # attributed to the consuming fit through the same thread-local
        # observer the retry counters ride.
        faults.observe_busy("verify", _time.perf_counter() - t0)
    return seg


# Write-path checksum convention: ingestion loops digest each tile/chunk
# from the memmap IMMEDIATELY after writing it — the pages are still
# dirty in the page cache, so the digest is a RAM-speed read of exactly
# the file's bytes, and sealing a multi-GB shard directory never has to
# read the dataset back off disk. The read-back in seal()/_final_meta
# remains only as the fallback for externally-filled memmaps
# (DiskCOOShards.create + caller fill), where write order is unknown.


def _meta_checksums(meta: dict) -> Tuple[Optional[Dict[str, List[int]]], str]:
    return meta.get("checksums"), meta.get("checksum_algo", "crc32")


class DiskCOOShards:
    """Pre-tiled padded-COO chunks on disk, mmap-read per segment.

    Layout on disk (one directory):
      indices.npy  (num_chunks, chunk_rows, w)  int16/int32  (-1 = inactive)
      values.npy   (num_chunks, chunk_rows, w)  f32/bf16-as-u16 is NOT used;
                   values keep their numpy dtype (float32 or float16-like)
      labels.npy   (num_chunks, chunk_rows, k)
      shards.json  {n_true, d, num_chunks, chunk_rows, checksum_algo,
                    checksums: {indices: [per chunk], values: [...],
                    labels: [...]}}

    ``write`` builds the files with ``open_memmap`` so the full dataset
    never needs to exist in RAM either at write time (callers may fill
    chunk ranges incrementally via the memmaps :meth:`create` returns —
    then :meth:`seal` computes the checksums and publishes the final
    metadata atomically; loading an unsealed directory raises
    :class:`ShardCorrupted`, never silently short data).
    """

    def __init__(self, directory: str, verify: bool = True,
                 retry_policy=None):
        self.directory = os.path.abspath(directory)
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        if meta.get("building"):
            raise corrupted(
                f"{self.directory}: shard directory was never sealed "
                f"(writer killed mid-build, or DiskCOOShards.seal() not "
                f"called after an incremental fill)"
            )
        self.n_true = int(meta["n_true"])
        self.d = int(meta["d"])
        self.num_chunks = int(meta["num_chunks"])
        self.chunk_rows = int(meta["chunk_rows"])
        self._checksums, self._algo = _meta_checksums(meta)
        if not verify:
            self._checksums = None
        self._retry = retry_policy or faults.default_retry_policy()
        self._idx = np.load(
            os.path.join(directory, _FILES["indices"]), mmap_mode="r"
        )
        self._val = np.load(
            os.path.join(directory, _FILES["values"]), mmap_mode="r"
        )
        self._y = np.load(
            os.path.join(directory, _FILES["labels"]), mmap_mode="r"
        )

    # ------------------------------------------------------------------
    @staticmethod
    def write(
        directory: str,
        indices: np.ndarray,
        values: np.ndarray,
        labels: np.ndarray,
        chunk_rows: int,
        n_true: int = None,
        d: int = None,
    ) -> "DiskCOOShards":
        """Tile row-major (n, w) COO + (n, k) labels into on-disk chunks.

        Rows past the last full chunk are padded with inactive (-1)
        lanes / zero labels. For datasets too big to hold even once,
        build the memmaps with :meth:`create`, fill ranges, then
        :meth:`seal`.
        """
        n, w = indices.shape
        k = labels.shape[1]
        n_true = n if n_true is None else int(n_true)
        d = int(indices.max()) + 1 if d is None else int(d)
        num_chunks = -(-n // chunk_rows)
        mm_i, mm_v, mm_y = DiskCOOShards.create(
            directory, num_chunks, chunk_rows, w, k,
            idx_dtype=indices.dtype, val_dtype=values.dtype,
            y_dtype=labels.dtype, n_true=n_true, d=d,
        )
        sums: Dict[str, List[int]] = {
            "indices": [], "values": [], "labels": []
        }
        for c in range(num_chunks):
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            m = hi - lo
            mm_i[c, :m] = indices[lo:hi]
            mm_v[c, :m] = values[lo:hi]
            mm_y[c, :m] = labels[lo:hi]
            # Digest while the chunk's pages are hot (see convention
            # note above) — no read-back pass at seal time.
            sums["indices"].append(int(crc_of_array(mm_i[c])))
            sums["values"].append(int(crc_of_array(mm_v[c])))
            sums["labels"].append(int(crc_of_array(mm_y[c])))
        for mm in (mm_i, mm_v, mm_y):
            mm.flush()
        del mm_i, mm_v, mm_y
        return DiskCOOShards.seal(directory, _precomputed=sums)

    @staticmethod
    def create(
        directory: str,
        num_chunks: int,
        chunk_rows: int,
        w: int,
        k: int,
        idx_dtype=np.int32,
        val_dtype=np.float32,
        y_dtype=np.float32,
        n_true: int = 0,
        d: int = 0,
    ) -> Tuple[np.memmap, np.memmap, np.memmap]:
        """Allocate the on-disk chunk files and return writable memmaps
        (indices prefilled with -1, values/labels with 0). The metadata
        written here carries ``building: true`` — the directory will not
        LOAD until :meth:`seal` publishes the final meta (atomically,
        with checksums), so a writer killed mid-fill leaves a directory
        that fails loudly instead of parsing as short-but-valid data."""
        os.makedirs(directory, exist_ok=True)
        # Stale final meta from a previous complete build must not pair
        # with the new (partially filled) arrays.
        try:
            os.unlink(os.path.join(directory, _META))
        except OSError:
            pass
        shape2 = (num_chunks, chunk_rows)
        mm_i = np.lib.format.open_memmap(
            os.path.join(directory, _FILES["indices"]), mode="w+",
            dtype=idx_dtype, shape=shape2 + (w,),
        )
        mm_i[...] = -1
        mm_v = np.lib.format.open_memmap(
            os.path.join(directory, _FILES["values"]), mode="w+",
            dtype=val_dtype, shape=shape2 + (w,),
        )
        mm_y = np.lib.format.open_memmap(
            os.path.join(directory, _FILES["labels"]), mode="w+",
            dtype=y_dtype, shape=shape2 + (k,),
        )
        atomic_write_json(
            os.path.join(directory, _META),
            {"n_true": int(n_true), "d": int(d),
             "num_chunks": int(num_chunks),
             "chunk_rows": int(chunk_rows),
             "building": True},
        )
        return mm_i, mm_v, mm_y

    @staticmethod
    def seal(directory: str, _precomputed=None) -> "DiskCOOShards":
        """Finish a build: fsync the array files, compute per-chunk
        checksums (read-back — callers that filled the memmaps
        themselves are the only ones who must pay it; ``write`` digests
        during its fill and passes them in), and atomically replace the
        ``building`` metadata with the final one — meta last, so the
        directory becomes loadable only once everything it describes is
        durably on disk."""
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        sums: Dict[str, List[int]] = {}
        for field, fname in _FILES.items():
            path = os.path.join(directory, fname)
            fsync_file(path)
            if _precomputed is not None:
                sums[field] = list(_precomputed[field])
            else:
                arr = np.load(path, mmap_mode="r")
                sums[field] = _chunk_checksums(arr, int(meta["num_chunks"]))
                del arr
        meta.pop("building", None)
        meta["checksum_algo"] = checksum_algo()
        meta["checksums"] = sums
        atomic_write_json(os.path.join(directory, _META), meta)
        return DiskCOOShards(directory)

    # ------------------------------------------------------------------
    def _read_chunks(self, arr, lo: int, hi: int, field: str) -> np.ndarray:
        return _read_verified(
            arr, lo, hi,
            what=f"{self.directory}/{_FILES[field]} chunk",
            key=f"{self.directory}:{field}:{lo}",
            checksums=(
                None if self._checksums is None
                else self._checksums.get(field)
            ),
            algo=self._algo, retry=self._retry,
        )

    def segment_source(self, cid0: int, seg: int):
        """The ``segment_source`` contract of ``run_lbfgs_gram_streamed``:
        materialize ONLY chunks [cid0, cid0+seg) as host arrays (phantom
        chunks past the end are inactive/-1 padded — the fold masks them
        by absolute id anyway)."""
        hi = min(cid0 + seg, self.num_chunks)
        idx = self._read_chunks(self._idx, cid0, hi, "indices")
        val = self._read_chunks(self._val, cid0, hi, "values")
        y = self._read_chunks(self._y, cid0, hi, "labels")
        pad = seg - (hi - cid0)
        if pad:
            idx = np.concatenate(
                [idx, np.full((pad,) + idx.shape[1:], -1, idx.dtype)]
            )
            val = np.concatenate(
                [val, np.zeros((pad,) + val.shape[1:], val.dtype)]
            )
            y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        return idx, val, y

    @property
    def is_memory_mapped(self) -> bool:
        return all(
            isinstance(a, np.memmap) for a in (self._idx, self._val, self._y)
        )

    @property
    def is_checksummed(self) -> bool:
        return self._checksums is not None

    def as_source(self, chunks_per_segment: int):
        """This shard set as a prefetchable ShardSource of
        ``chunks_per_segment``-chunk segments (the
        ``run_lbfgs_gram_streamed`` operand contract)."""
        from .prefetch import COOShardSource

        return COOShardSource(self, chunks_per_segment)


class DiskDenseShards:
    """Pre-tiled DENSE rows on disk, mmap-read per segment — the dense
    analog of :class:`DiskCOOShards`, feeding
    ``parallel.streaming.streaming_bcd_fit_segments``.

    Layout: ``x.npy`` (num_tiles, tile_rows, d_in), ``y.npy``
    (num_tiles, tile_rows, k), ``dense_shards.json``
    {n_true, tile_rows, num_tiles, tiles_per_segment, checksum_algo,
    checksums: {x: [per tile], y: [per tile]}}.
    """

    _META = "dense_shards.json"

    def __init__(self, directory: str, verify: bool = True,
                 retry_policy=None):
        self.directory = os.path.abspath(directory)
        with open(os.path.join(directory, self._META)) as f:
            meta = json.load(f)
        self.n_true = int(meta["n_true"])
        self.tile_rows = int(meta["tile_rows"])
        self.num_tiles = int(meta["num_tiles"])
        self.tiles_per_segment = int(meta["tiles_per_segment"])
        self._checksums, self._algo = _meta_checksums(meta)
        if not verify:
            self._checksums = None
        self._retry = retry_policy or faults.default_retry_policy()
        self._x = np.load(os.path.join(directory, "x.npy"), mmap_mode="r")
        self._y = np.load(os.path.join(directory, "y.npy"), mmap_mode="r")

    @property
    def num_segments(self) -> int:
        return -(-self.num_tiles // self.tiles_per_segment)

    @staticmethod
    def _final_meta(directory: str, n_true: int, tile_rows: int,
                    num_tiles: int, tiles_per_segment: int,
                    checksums: Optional[Dict[str, List[int]]] = None,
                    ) -> None:
        """Fsync the arrays, then publish metadata LAST and atomically —
        the commit point of a dense shard build. Checksums cover the
        tiles the metadata claims (capacity tiles past ``num_tiles``,
        e.g. an overshooting writer's sparse tail, are not claimed and
        not digested); both writers digest tiles hot during the fill and
        pass them here, so the read-back below is only a fallback."""
        sums: Dict[str, List[int]] = {}
        for field in ("x", "y"):
            path = os.path.join(directory, f"{field}.npy")
            fsync_file(path)
            if checksums is not None:
                sums[field] = list(checksums[field])
            else:
                arr = np.load(path, mmap_mode="r")
                sums[field] = _chunk_checksums(arr, num_tiles)
                del arr
        atomic_write_json(
            os.path.join(directory, DiskDenseShards._META),
            {"n_true": int(n_true), "tile_rows": int(tile_rows),
             "num_tiles": int(num_tiles),
             "tiles_per_segment": int(tiles_per_segment),
             "checksum_algo": checksum_algo(),
             "checksums": sums},
        )

    @staticmethod
    def write(
        directory: str,
        X: np.ndarray,
        Y: np.ndarray,
        tile_rows: int,
        tiles_per_segment: int,
    ) -> "DiskDenseShards":
        """Tile (n, d_in) rows + (n, k) labels into on-disk tiles (the
        ragged tail is zero-padded; n_true masks it at fold time)."""
        n, d_in = X.shape
        k = Y.shape[1]
        num_tiles = -(-n // tile_rows)
        os.makedirs(directory, exist_ok=True)
        # A stale meta from a previous build must never describe the new
        # partially-written arrays (kill-mid-write would otherwise load
        # as a valid-but-wrong dataset).
        try:
            os.unlink(os.path.join(directory, DiskDenseShards._META))
        except OSError:
            pass
        mm_x = np.lib.format.open_memmap(
            os.path.join(directory, "x.npy"), mode="w+", dtype=X.dtype,
            shape=(num_tiles, tile_rows, d_in),
        )
        mm_y = np.lib.format.open_memmap(
            os.path.join(directory, "y.npy"), mode="w+", dtype=Y.dtype,
            shape=(num_tiles, tile_rows, k),
        )
        # open_memmap('w+') creates the file zero-filled via ftruncate
        # (sparse allocation) — the ragged tail needs no explicit pass.
        sums: Dict[str, List[int]] = {"x": [], "y": []}
        for t in range(num_tiles):
            lo, hi = t * tile_rows, min((t + 1) * tile_rows, n)
            mm_x[t, : hi - lo] = X[lo:hi]
            mm_y[t, : hi - lo] = Y[lo:hi]
            # Digest while the tile's pages are hot (convention note at
            # the top of the module).
            sums["x"].append(int(crc_of_array(mm_x[t])))
            sums["y"].append(int(crc_of_array(mm_y[t])))
        mm_x.flush(); mm_y.flush()
        del mm_x, mm_y
        DiskDenseShards._final_meta(
            directory, n, tile_rows, num_tiles, tiles_per_segment,
            checksums=sums,
        )
        return DiskDenseShards(directory)

    def segment_source(self, s: int):
        """``streaming_bcd_fit_segments`` contract: materialize ONLY this
        segment's tiles (phantom tiles past the end are zero-padded and
        masked by valid_rows=0)."""
        X_seg, valid_rows = self.segment_source_x(s)
        Y_seg, _ = self.segment_source_y(s)
        return X_seg, Y_seg, valid_rows

    def _segment_field(self, arr, s: int, field: str):
        tps = self.tiles_per_segment
        lo, hi = s * tps, min((s + 1) * tps, self.num_tiles)
        seg = _read_verified(
            arr, lo, hi,
            what=f"{self.directory}/{field}.npy tile",
            key=f"{self.directory}:{field}:{lo}",
            checksums=(
                None if self._checksums is None
                else self._checksums.get(field)
            ),
            algo=self._algo, retry=self._retry,
        )
        pad = tps - (hi - lo)
        if pad:
            seg = np.concatenate(
                [seg, np.zeros((pad,) + seg.shape[1:], seg.dtype)]
            )
        valid_rows = max(
            min(self.n_true - lo * self.tile_rows, tps * self.tile_rows), 0
        )
        return seg, valid_rows

    def segment_source_x(self, s: int):
        """(X_seg, valid_rows) only — pairings that bring their own
        resident labels skip the on-disk label read entirely."""
        return self._segment_field(self._x, s, "x")

    def segment_source_y(self, s: int):
        """(Y_seg, valid_rows) only — label views (e.g. the cost-model
        sample collector) skip the much wider row read."""
        return self._segment_field(self._y, s, "y")

    @property
    def is_memory_mapped(self) -> bool:
        return isinstance(self._x, np.memmap) and isinstance(
            self._y, np.memmap
        )

    @property
    def is_checksummed(self) -> bool:
        return self._checksums is not None

    def as_source(self):
        """This shard set as a prefetchable ShardSource delivering the
        (X_seg, Y_seg, valid_rows) segments
        ``streaming_bcd_fit_segments`` folds."""
        from .prefetch import DenseShardSource

        return DenseShardSource(self)

    def as_labeled_data(self):
        """(data, labels) shard-backed Datasets over these files — the
        typed-pipeline entry point: both Datasets view ONE set of disk
        shards, so ``Pipeline.fit`` can route the pair through the
        capacity selector with no resident copy ever existing."""
        from .dataset import Dataset, LabeledData
        from .prefetch import DenseShardView

        paired = self.as_source()
        return LabeledData(
            Dataset(DenseShardView(paired, "x")),
            Dataset(DenseShardView(paired, "y")),
        )


class DiskDenseShardWriter:
    """Incremental row-appending writer for :class:`DiskDenseShards`.

    Loaders stream rows in (one CSV file / archive member batch at a
    time) and the writer fills on-disk tiles in place — host residency is
    the incoming block, never the dataset. ``capacity_rows`` may OVERSHOOT
    the true count (e.g. a newline-count upper bound): unwritten tail
    tiles stay sparse zero-fill on disk and the metadata written at
    ``close`` records only the rows actually appended.

    Crash safety: any previous metadata is deleted at open, and the new
    metadata (with per-tile checksums) is written atomically, LAST, at
    :meth:`close` — a writer killed mid-append leaves a directory that
    refuses to load rather than one that silently truncates the data.
    """

    def __init__(
        self,
        directory: str,
        capacity_rows: int,
        d_in: int,
        k: int,
        tile_rows: int,
        tiles_per_segment: int = 4,
        x_dtype=np.float32,
        y_dtype=np.float32,
    ):
        if capacity_rows <= 0:
            raise ValueError("capacity_rows must be positive")
        self.directory = directory
        self.tile_rows = int(tile_rows)
        self.tiles_per_segment = int(tiles_per_segment)
        cap_tiles = -(-int(capacity_rows) // self.tile_rows)
        os.makedirs(directory, exist_ok=True)
        try:
            os.unlink(os.path.join(directory, DiskDenseShards._META))
        except OSError:
            pass
        self._mm_x = np.lib.format.open_memmap(
            os.path.join(directory, "x.npy"), mode="w+", dtype=x_dtype,
            shape=(cap_tiles, self.tile_rows, int(d_in)),
        )
        self._mm_y = np.lib.format.open_memmap(
            os.path.join(directory, "y.npy"), mode="w+", dtype=y_dtype,
            shape=(cap_tiles, self.tile_rows, int(k)),
        )
        self._rows = 0
        self._closed = False
        # Tiles digested so far (hot, as appends complete them — the
        # module's write-path checksum convention).
        self._sums: Dict[str, List[int]] = {"x": [], "y": []}

    def append(self, X_block: np.ndarray, Y_block: np.ndarray) -> None:
        X_block = np.asarray(X_block)
        Y_block = np.asarray(Y_block)
        if Y_block.ndim == 1:
            Y_block = Y_block[:, None]
        m = X_block.shape[0]
        if Y_block.shape[0] != m:
            raise ValueError(
                f"rows disagree: X {m} vs Y {Y_block.shape[0]}"
            )
        if self._rows + m > self._mm_x.shape[0] * self.tile_rows:
            raise ValueError(
                f"writer capacity {self._mm_x.shape[0] * self.tile_rows} "
                f"rows exceeded at {self._rows + m}"
            )
        flat_x = self._mm_x.reshape(-1, self._mm_x.shape[-1])
        flat_y = self._mm_y.reshape(-1, self._mm_y.shape[-1])
        flat_x[self._rows : self._rows + m] = X_block
        flat_y[self._rows : self._rows + m] = Y_block
        self._rows += m
        # Digest tiles this block COMPLETED while their pages are hot.
        for t in range(len(self._sums["x"]), self._rows // self.tile_rows):
            self._sums["x"].append(int(crc_of_array(self._mm_x[t])))
            self._sums["y"].append(int(crc_of_array(self._mm_y[t])))

    def close(self) -> "DiskDenseShards":
        """Flush + fsync the arrays, write checksummed metadata for the
        rows actually appended (atomically, last), and reopen read-only
        as :class:`DiskDenseShards`."""
        if self._closed:
            raise RuntimeError("writer already closed")
        self._closed = True
        if self._rows == 0:
            raise ValueError("no rows were appended")
        num_tiles = -(-self._rows // self.tile_rows)
        # Digest the trailing partial tile (its zero tail reads straight
        # from the sparse file's hole pages — no disk IO).
        for t in range(len(self._sums["x"]), num_tiles):
            self._sums["x"].append(int(crc_of_array(self._mm_x[t])))
            self._sums["y"].append(int(crc_of_array(self._mm_y[t])))
        self._mm_x.flush(); self._mm_y.flush()
        del self._mm_x, self._mm_y
        DiskDenseShards._final_meta(
            self.directory, self._rows, self.tile_rows, num_tiles,
            self.tiles_per_segment, checksums=self._sums,
        )
        return DiskDenseShards(self.directory)
