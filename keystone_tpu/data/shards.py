"""Disk-backed chunk shards for the streamed solvers.

The reference streams from storage by construction — ``CsvDataLoader`` is
a lazy ``textFile`` (CsvDataLoader.scala:10-31) and image loaders decode
per partition (ImageLoaderUtils.scala:21-94) — so its fits are bounded by
disk, not RAM. The round-4 streamed folds here took their chunks from
HOST-RESIDENT arrays, bounding n by host RAM instead. This module closes
that gap: pre-tiled padded-COO shards live in ``.npy`` files, are opened
memory-mapped, and feed the segmented Gramian folds one SEGMENT at a time
(``run_lbfgs_gram_streamed(segment_source=...)``) — peak host residency
is the mmap page cache (OS-evictable) plus ``seg`` chunks of copy buffer,
regardless of dataset size.
"""

from __future__ import annotations

import json
import os
from typing import Tuple

import numpy as np

_META = "shards.json"
_FILES = {"indices": "indices.npy", "values": "values.npy", "labels": "labels.npy"}


class DiskCOOShards:
    """Pre-tiled padded-COO chunks on disk, mmap-read per segment.

    Layout on disk (one directory):
      indices.npy  (num_chunks, chunk_rows, w)  int16/int32  (-1 = inactive)
      values.npy   (num_chunks, chunk_rows, w)  f32/bf16-as-u16 is NOT used;
                   values keep their numpy dtype (float32 or float16-like)
      labels.npy   (num_chunks, chunk_rows, k)
      shards.json  {n_true, d, num_chunks, chunk_rows}

    ``write`` builds the files with ``open_memmap`` so the full dataset
    never needs to exist in RAM either at write time (callers may fill
    chunk ranges incrementally via the returned memmaps).
    """

    def __init__(self, directory: str):
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        self.n_true = int(meta["n_true"])
        self.d = int(meta["d"])
        self.num_chunks = int(meta["num_chunks"])
        self.chunk_rows = int(meta["chunk_rows"])
        self._idx = np.load(
            os.path.join(directory, _FILES["indices"]), mmap_mode="r"
        )
        self._val = np.load(
            os.path.join(directory, _FILES["values"]), mmap_mode="r"
        )
        self._y = np.load(
            os.path.join(directory, _FILES["labels"]), mmap_mode="r"
        )

    # ------------------------------------------------------------------
    @staticmethod
    def write(
        directory: str,
        indices: np.ndarray,
        values: np.ndarray,
        labels: np.ndarray,
        chunk_rows: int,
        n_true: int = None,
        d: int = None,
    ) -> "DiskCOOShards":
        """Tile row-major (n, w) COO + (n, k) labels into on-disk chunks.

        Rows past the last full chunk are padded with inactive (-1)
        lanes / zero labels. For datasets too big to hold even once,
        build the memmaps with :meth:`create` and fill ranges instead.
        """
        n, w = indices.shape
        k = labels.shape[1]
        n_true = n if n_true is None else int(n_true)
        d = int(indices.max()) + 1 if d is None else int(d)
        num_chunks = -(-n // chunk_rows)
        mm_i, mm_v, mm_y = DiskCOOShards.create(
            directory, num_chunks, chunk_rows, w, k,
            idx_dtype=indices.dtype, val_dtype=values.dtype,
            y_dtype=labels.dtype, n_true=n_true, d=d,
        )
        for c in range(num_chunks):
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            m = hi - lo
            mm_i[c, :m] = indices[lo:hi]
            mm_v[c, :m] = values[lo:hi]
            mm_y[c, :m] = labels[lo:hi]
        for mm in (mm_i, mm_v, mm_y):
            mm.flush()
        del mm_i, mm_v, mm_y
        return DiskCOOShards(directory)

    @staticmethod
    def create(
        directory: str,
        num_chunks: int,
        chunk_rows: int,
        w: int,
        k: int,
        idx_dtype=np.int32,
        val_dtype=np.float32,
        y_dtype=np.float32,
        n_true: int = 0,
        d: int = 0,
    ) -> Tuple[np.memmap, np.memmap, np.memmap]:
        """Allocate the on-disk chunk files and return writable memmaps
        (indices prefilled with -1, values/labels with 0)."""
        os.makedirs(directory, exist_ok=True)
        shape2 = (num_chunks, chunk_rows)
        mm_i = np.lib.format.open_memmap(
            os.path.join(directory, _FILES["indices"]), mode="w+",
            dtype=idx_dtype, shape=shape2 + (w,),
        )
        mm_i[...] = -1
        mm_v = np.lib.format.open_memmap(
            os.path.join(directory, _FILES["values"]), mode="w+",
            dtype=val_dtype, shape=shape2 + (w,),
        )
        mm_y = np.lib.format.open_memmap(
            os.path.join(directory, _FILES["labels"]), mode="w+",
            dtype=y_dtype, shape=shape2 + (k,),
        )
        with open(os.path.join(directory, _META), "w") as f:
            json.dump(
                {"n_true": int(n_true), "d": int(d),
                 "num_chunks": int(num_chunks),
                 "chunk_rows": int(chunk_rows)},
                f,
            )
        return mm_i, mm_v, mm_y

    # ------------------------------------------------------------------
    def segment_source(self, cid0: int, seg: int):
        """The ``segment_source`` contract of ``run_lbfgs_gram_streamed``:
        materialize ONLY chunks [cid0, cid0+seg) as host arrays (phantom
        chunks past the end are inactive/-1 padded — the fold masks them
        by absolute id anyway)."""
        hi = min(cid0 + seg, self.num_chunks)
        idx = np.asarray(self._idx[cid0:hi])
        val = np.asarray(self._val[cid0:hi])
        y = np.asarray(self._y[cid0:hi])
        pad = seg - (hi - cid0)
        if pad:
            idx = np.concatenate(
                [idx, np.full((pad,) + idx.shape[1:], -1, idx.dtype)]
            )
            val = np.concatenate(
                [val, np.zeros((pad,) + val.shape[1:], val.dtype)]
            )
            y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        return idx, val, y

    @property
    def is_memory_mapped(self) -> bool:
        return all(
            isinstance(a, np.memmap) for a in (self._idx, self._val, self._y)
        )


class DiskDenseShards:
    """Pre-tiled DENSE rows on disk, mmap-read per segment — the dense
    analog of :class:`DiskCOOShards`, feeding
    ``parallel.streaming.streaming_bcd_fit_segments``.

    Layout: ``x.npy`` (num_tiles, tile_rows, d_in), ``y.npy``
    (num_tiles, tile_rows, k), ``dense_shards.json``
    {n_true, tile_rows, num_tiles, tiles_per_segment}.
    """

    _META = "dense_shards.json"

    def __init__(self, directory: str):
        with open(os.path.join(directory, self._META)) as f:
            meta = json.load(f)
        self.n_true = int(meta["n_true"])
        self.tile_rows = int(meta["tile_rows"])
        self.num_tiles = int(meta["num_tiles"])
        self.tiles_per_segment = int(meta["tiles_per_segment"])
        self._x = np.load(os.path.join(directory, "x.npy"), mmap_mode="r")
        self._y = np.load(os.path.join(directory, "y.npy"), mmap_mode="r")

    @property
    def num_segments(self) -> int:
        return -(-self.num_tiles // self.tiles_per_segment)

    @staticmethod
    def write(
        directory: str,
        X: np.ndarray,
        Y: np.ndarray,
        tile_rows: int,
        tiles_per_segment: int,
    ) -> "DiskDenseShards":
        """Tile (n, d_in) rows + (n, k) labels into on-disk tiles (the
        ragged tail is zero-padded; n_true masks it at fold time)."""
        n, d_in = X.shape
        k = Y.shape[1]
        num_tiles = -(-n // tile_rows)
        os.makedirs(directory, exist_ok=True)
        mm_x = np.lib.format.open_memmap(
            os.path.join(directory, "x.npy"), mode="w+", dtype=X.dtype,
            shape=(num_tiles, tile_rows, d_in),
        )
        mm_y = np.lib.format.open_memmap(
            os.path.join(directory, "y.npy"), mode="w+", dtype=Y.dtype,
            shape=(num_tiles, tile_rows, k),
        )
        # open_memmap('w+') creates the file zero-filled via ftruncate
        # (sparse allocation) — the ragged tail needs no explicit pass.
        for t in range(num_tiles):
            lo, hi = t * tile_rows, min((t + 1) * tile_rows, n)
            mm_x[t, : hi - lo] = X[lo:hi]
            mm_y[t, : hi - lo] = Y[lo:hi]
        mm_x.flush(); mm_y.flush()
        del mm_x, mm_y
        with open(os.path.join(directory, DiskDenseShards._META), "w") as f:
            json.dump(
                {"n_true": int(n), "tile_rows": int(tile_rows),
                 "num_tiles": int(num_tiles),
                 "tiles_per_segment": int(tiles_per_segment)},
                f,
            )
        return DiskDenseShards(directory)

    def segment_source(self, s: int):
        """``streaming_bcd_fit_segments`` contract: materialize ONLY this
        segment's tiles (phantom tiles past the end are zero-padded and
        masked by valid_rows=0)."""
        tps = self.tiles_per_segment
        lo, hi = s * tps, min((s + 1) * tps, self.num_tiles)
        X_seg = np.asarray(self._x[lo:hi])
        Y_seg = np.asarray(self._y[lo:hi])
        pad = tps - (hi - lo)
        if pad:
            X_seg = np.concatenate(
                [X_seg, np.zeros((pad,) + X_seg.shape[1:], X_seg.dtype)]
            )
            Y_seg = np.concatenate(
                [Y_seg, np.zeros((pad,) + Y_seg.shape[1:], Y_seg.dtype)]
            )
        valid_rows = max(
            min(self.n_true - lo * self.tile_rows, tps * self.tile_rows), 0
        )
        return X_seg, Y_seg, valid_rows

    @property
    def is_memory_mapped(self) -> bool:
        return isinstance(self._x, np.memmap) and isinstance(
            self._y, np.memmap
        )
