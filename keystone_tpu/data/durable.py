"""Durable on-disk state: checksummed atomic writes + fold checkpoints.

The disk tier (shards.py) replaced Spark's lineage-backed RDDs with raw
``.npy`` files — and raw files have raw failure modes: a killed writer
leaves a directory that *parses* as a valid-but-short dataset, and a
bit flip feeds garbage straight into an hours-long fit. This module is
the shared substrate both shard formats and the fit checkpoints build
on:

  - **Atomic metadata**: :func:`atomic_write_json` writes to a temp name
    in the same directory, fsyncs, then ``os.replace``\\ s — a reader
    either sees the old meta, no meta, or the complete new meta, never a
    torn one. Writers order *meta last*, so the presence of meta implies
    the arrays it describes were fully written and flushed.
  - **Checksums**: CRC32C when a ``crc32c`` module is available in the
    environment, else zlib's CRC32 (C-speed; the container has no
    crc32c wheel and nothing may be installed). The algorithm actually
    used is recorded next to every digest, so readers verify with the
    writer's algorithm — mixed environments interoperate.
  - **Fold checkpoints**: :class:`CheckpointSpec` + save/load of a
    streamed fit's carry (Gram/correlation accumulators + segment
    cursor), bit-exact: arrays round-trip as raw bytes with dtype/shape
    manifest, so a resumed fit folds the *identical* f32 state the
    interrupted run held — the bit-identity contract
    tests/test_chaos.py proves under injected kills.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import zlib
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu import obs
from keystone_tpu.utils import faults

__all__ = [
    "CheckpointSpec",
    "ShardCorrupted",
    "atomic_write_json",
    "checksum_algo",
    "corrupted",
    "crc_of_array",
    "fingerprint_token",
    "fsync_file",
    "resolve_checkpoint",
    "source_fingerprint",
]


class ShardCorrupted(RuntimeError):
    """On-disk bytes failed checksum verification (torn write, bit flip,
    or injected corruption). Deliberately NOT an OSError: corruption is
    persistent state — the retry layer must never spin on it, and no
    caller may silently fold the data. Raise through :func:`corrupted`
    so the postmortem flight record rides the log beside it."""


def corrupted(message: str) -> ShardCorrupted:
    """Build a :class:`ShardCorrupted` to raise, dumping the obs flight
    record beside it (ISSUE 9): corruption surfaces consumer-side, far
    from the reads and checkpoint writes that preceded it, so the
    postmortem block naming the recent spans and the ones in flight
    rides the log next to the exception. A factory at the raise sites —
    not an ``__init__`` side effect — so constructing/re-wrapping/
    unpickling the exception stays pure."""
    obs.flight.dump_flight_record("ShardCorrupted: " + message)
    return ShardCorrupted(message)


try:  # pragma: no cover - container has no crc32c wheel
    import crc32c as _crc32c_mod

    def _crc(data, value: int = 0) -> int:
        return _crc32c_mod.crc32c(data, value)

    _ALGO = "crc32c"
except ImportError:
    def _crc(data, value: int = 0) -> int:
        return zlib.crc32(data, value) & 0xFFFFFFFF

    _ALGO = "crc32"


def checksum_algo() -> str:
    """The digest algorithm this process WRITES ("crc32c" when the
    optional module exists, else "crc32"). Readers always verify with
    the algorithm recorded in the metadata being read."""
    return _ALGO


def _crc_named(algo: str):
    if algo == _ALGO:
        return _crc
    if algo == "crc32":
        return lambda data, value=0: zlib.crc32(data, value) & 0xFFFFFFFF
    if algo == "crc32c":
        raise corrupted(
            "metadata was written with crc32c but no crc32c module is "
            "available to verify it"
        )
    raise corrupted(f"unknown checksum algorithm {algo!r}")


def crc_of_array(arr: np.ndarray, algo: Optional[str] = None) -> int:
    """Digest of an array's raw bytes (C-order copy if needed)."""
    fn = _crc if algo is None else _crc_named(algo)
    return fn(np.ascontiguousarray(arr).view(np.uint8).reshape(-1).data)




def verify_array(
    arr: np.ndarray, expected: int, algo: str, what: str
) -> None:
    got = crc_of_array(arr, algo)
    if got != int(expected):
        raise corrupted(
            f"{what}: checksum mismatch ({algo} {got:#010x} != recorded "
            f"{int(expected):#010x}) — torn write or bit corruption; "
            f"re-ingest the shard directory"
        )


def fsync_file(path: str) -> None:
    """Flush a file's contents to stable storage (best-effort on
    filesystems that reject fsync, e.g. some overlayfs tmp mounts)."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - fs-dependent
        pass


def atomic_write_json(path: str, obj: Any) -> None:
    """Write JSON so ``path`` is either absent, the old content, or the
    complete new content — never torn. Temp file in the same directory
    (os.replace must not cross filesystems), fsync'd before the rename,
    directory fsync'd after so the rename itself is durable."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.", dir=directory
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:  # pragma: no cover - fs-dependent
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Fit checkpoints
# ---------------------------------------------------------------------------

_CKPT_META = "checkpoint.json"
_CKPT_DATA = "carry.bin"


class CheckpointSpec:
    """Where and how often a streamed fit snapshots its fold carry.

    ``directory`` holds at most one checkpoint PER FIT: snapshots are
    namespaced by a digest of the fit's fingerprint (``fit-<digest>/``
    subdirectories), so one global ``--checkpoint-dir`` serves a
    pipeline with several segmented streamed fits — fit A's snapshots
    and clears never clobber fit B's. Within a fit only the latest
    snapshot is kept (the carry is cumulative, so older snapshots are
    strictly dominated). ``every_segments`` is the snapshot cadence K.
    Snapshot cost is one device→host sync of the carry plus an atomic
    file write, so the steady-state overhead is ~(carry_bytes /
    disk_rate) per K segments — the ``recovery_overhead`` bench row
    measures it at the default K.

    A checkpoint records a caller-built *fingerprint* (fit kind, segment
    count, featurizer identity + parameter digests, source identity);
    :meth:`load` returns None when the fingerprint does not match, so a
    stale checkpoint from a different fit — including the same geometry
    under a different feature bank or a re-ingested shard directory —
    can never leak its accumulators into this one. (Resident operands
    are fingerprinted by shape/dtype only: digesting gigabytes of live
    arrays per snapshot would dwarf the snapshot itself; disk sources
    are covered through their recorded per-tile checksums.)

    **Write-behind (ISSUE 8):** snapshot writes go through the
    data-plane runtime's ``checkpoint`` lane
    (:mod:`keystone_tpu.data.runtime`) by default, so
    :meth:`maybe_save` blocks the fold only for the device→host carry
    transfer plus queue-submit time — the fsync of a ~1.2 GB carry at
    Amazon geometry no longer stalls the fold loop. Durability is
    unchanged: :meth:`save` is atomic and versioned either way, so a
    kill DURING an in-flight async write leaves the previous complete
    snapshot resumable (tests/test_chaos.py). Ordering is structural
    (the lane is FIFO), every read-side entry point (:meth:`load` /
    :meth:`restore` / :meth:`has_snapshot` / :meth:`clear`) flushes
    pending writes first, and an async write failure surfaces LOUDLY at
    the next :meth:`maybe_save` or :meth:`flush` — a fit never
    completes thinking it was insured when it was not. ``runtime=False``
    (or ``KEYSTONE_CHECKPOINT_SYNC=1``) restores synchronous writes.
    """

    def __init__(self, directory: str, every_segments: int = 8,
                 runtime=None):
        if every_segments < 1:
            raise ValueError(
                f"every_segments must be >= 1, got {every_segments}"
            )
        self.directory = str(directory)
        self.every_segments = int(every_segments)
        # None -> the shared data-plane runtime (write-behind, the
        # default); False -> synchronous writes; or an explicit
        # DataPlaneRuntime.
        self._runtime = runtime
        self._pending: List[Any] = []  # outstanding write futures (FIFO)

    def _rt(self):
        if self._runtime is False:
            return None
        if os.environ.get("KEYSTONE_CHECKPOINT_SYNC", "").strip() in (
            "1", "true", "on"
        ):
            return None
        if self._runtime is None:
            from keystone_tpu.data.runtime import default_runtime

            return default_runtime()
        return self._runtime

    # -- write-behind plumbing --------------------------------------------

    def flush(self, timeout: float = 120.0,
              raise_errors: bool = True) -> None:
        """Wait for every pending snapshot write and re-raise the first
        failure — the loud-surface point of the write-behind contract.
        Every read-side entry point calls this first, so observers never
        race an in-flight write in the same process. ``raise_errors=
        False`` (the post-completion :meth:`clear` path, where the
        snapshot is about to be deleted anyway) demotes failures to a
        warning instead of destroying a fit that already finished."""
        futs, self._pending = self._pending, []
        first: Optional[BaseException] = None
        for i, fut in enumerate(futs):
            try:
                fut.result(timeout=timeout)
            except FutureTimeoutError as e:
                # The write is STILL RUNNING — dropping its future here
                # would let a later clear() delete the fit dir and the
                # stalled write resurrect a stale snapshot afterwards.
                # Keep it (and everything behind it on the FIFO lane)
                # pending and fail loudly regardless of raise_errors:
                # "flushed" must mean "no write in flight".
                self._pending = futs[i:] + self._pending
                if first is not None:
                    # An earlier write already FAILED and was consumed
                    # from pending above; swallowing it under the
                    # timeout would let a later flush succeed and the
                    # fit complete uninsured. The failure outranks the
                    # still-running write.
                    raise first from e
                raise
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first is None:
                    first = e
        if first is not None:
            if raise_errors:
                raise first
            import logging

            logging.getLogger("keystone_tpu.durable").warning(
                "async checkpoint write failed (fit already complete; "
                "snapshot being cleared): %s", first,
            )

    def _surface_pending_failure(self) -> None:
        """Raise a COMPLETED pending write's failure without blocking on
        ones still in flight (the per-maybe_save check: a dead
        checkpoint disk fails the fit at the next snapshot boundary,
        not at the end). Unfinished futures are retained — their
        outcome surfaces at the next boundary or at flush. A surfaced
        failure is CONSUMED (raised once, here) — re-raising the same
        dead write at every later flush would mask the recovery path."""
        still = []
        first: Optional[BaseException] = None
        for fut in self._pending:
            if not fut.done():
                still.append(fut)
                continue
            exc = fut.exception()
            if exc is not None and first is None:
                first = exc
        self._pending = still
        if first is not None:
            raise first

    def _fit_dir(self, fingerprint: Dict[str, Any]) -> str:
        """The fingerprint-digest subdirectory this fit's snapshot lives
        in — the namespacing that lets several fits share one
        ``--checkpoint-dir`` without clobbering each other."""
        canonical = json.dumps(fingerprint, sort_keys=True).encode()
        return os.path.join(self.directory, f"fit-{_crc(canonical):08x}")

    # -- save --------------------------------------------------------------

    def save(
        self,
        arrays: Sequence[np.ndarray],
        cursor: int,
        fingerprint: Dict[str, Any],
    ) -> None:
        """Atomically snapshot (arrays, cursor). The data file is
        VERSIONED per cursor (``carry-<cursor>.bin``) and the meta —
        written last, atomically — names the file it describes: a kill
        at ANY point (including between the data write and the meta
        write, where a fixed data name would pair old meta with new
        bytes) leaves either the previous complete checkpoint or the
        new one, never a meta describing the wrong data. Superseded
        data files are deleted only after the new meta is durable."""
        # The chaos hook: fires once per snapshot write attempt — on the
        # write-behind worker for async specs, inline for sync ones.
        faults.maybe_fail(faults.SITE_CHECKPOINT_WRITE)
        fit_dir = self._fit_dir(fingerprint)
        os.makedirs(fit_dir, exist_ok=True)
        arrays = [np.asarray(a) for a in arrays]
        manifest: List[Dict[str, Any]] = []
        offset = 0
        data_name = f"carry-{int(cursor)}.bin"
        data_path = os.path.join(fit_dir, data_name)
        fd, tmp = tempfile.mkstemp(prefix=data_name + ".tmp.",
                                   dir=fit_dir)
        try:
            with os.fdopen(fd, "wb") as f:
                for i, a in enumerate(arrays):
                    raw = np.ascontiguousarray(a).tobytes()
                    f.write(raw)
                    manifest.append({
                        "index": i,
                        "dtype": str(a.dtype),
                        "shape": list(a.shape),
                        "offset": offset,
                        "nbytes": len(raw),
                        "crc": _crc(raw),
                    })
                    offset += len(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, data_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        atomic_write_json(
            os.path.join(fit_dir, _CKPT_META),
            {
                "cursor": int(cursor),
                "algo": _ALGO,
                "data": data_name,
                "fingerprint": fingerprint,
                "arrays": manifest,
            },
        )
        # The new meta is durable: earlier snapshots' data files are now
        # unreachable — reclaim them.
        for name in self._data_files(fit_dir):
            if name != data_name:
                try:
                    os.unlink(os.path.join(fit_dir, name))
                except OSError:
                    pass

    @staticmethod
    def _data_files(fit_dir: str) -> List[str]:
        try:
            entries = os.listdir(fit_dir)
        except OSError:
            return []
        return [
            e for e in entries
            if (e == _CKPT_DATA
                or (e.startswith("carry-") and e.endswith(".bin")))
        ]

    # -- load --------------------------------------------------------------

    def load(
        self, fingerprint: Dict[str, Any]
    ) -> Optional[Tuple[List[np.ndarray], int]]:
        """(carry arrays, next segment cursor) from the latest snapshot,
        or None when no checkpoint exists or its fingerprint belongs to
        a different fit (the namespaced directory makes a mismatch a
        digest collision — still checked). Corrupt data raises
        :class:`ShardCorrupted` — a bad checkpoint must never silently
        seed a fresh-looking fit."""
        self.flush()
        fit_dir = self._fit_dir(fingerprint)
        meta_path = os.path.join(fit_dir, _CKPT_META)
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("fingerprint") != fingerprint:
            return None
        crc_fn = _crc_named(meta.get("algo", "crc32"))
        arrays: List[np.ndarray] = []
        data_name = meta.get("data", _CKPT_DATA)  # legacy fixed name
        with open(os.path.join(fit_dir, data_name), "rb") as f:
            blob = f.read()
        for ent in meta["arrays"]:
            raw = blob[ent["offset"]: ent["offset"] + ent["nbytes"]]
            if len(raw) != ent["nbytes"] or crc_fn(raw) != ent["crc"]:
                raise corrupted(
                    f"checkpoint array {ent['index']} in "
                    f"{fit_dir}: checksum mismatch — discard the "
                    f"checkpoint directory and restart the fit"
                )
            arrays.append(
                np.frombuffer(raw, dtype=_resolve_dtype(ent["dtype"]))
                .reshape(ent["shape"])
            )
        return arrays, int(meta["cursor"])

    def restore(
        self, fingerprint: Dict[str, Any]
    ) -> Tuple[Optional[List[np.ndarray]], int]:
        """(carry arrays, start segment) — (None, 0) when there is
        nothing (matching) to resume from. The shared entry point of
        both streamed solvers, so resume semantics cannot drift apart."""
        loaded = self.load(fingerprint)
        if loaded is None:
            return None, 0
        return loaded

    def maybe_save(
        self,
        arrays: Sequence[Any],
        segment: int,
        num_segments: int,
        fingerprint: Dict[str, Any],
        stats=None,
    ) -> bool:
        """Shared snapshot cadence of the streamed solvers: after
        ``segment``, snapshot when the every-K boundary hits and it is
        not the final segment (a completed fit clears instead of
        snapshotting). ``np.asarray`` here is the device sync — the
        snapshot captures exactly the post-segment carry a resumed run
        restores, and it MUST run on the calling (JAX-owner) thread:
        the next fold donates these buffers. The disk write itself is
        write-behind (class docstring) — the fold blocks for
        sync + queue-submit only. Returns whether a snapshot was
        written (submitted, for async specs).

        ``stats``: optional :class:`~keystone_tpu.data.prefetch.
        PrefetchStats`-like sink — the write's wall lands in
        ``site_busy_s["checkpoint"]`` (worker-side for async specs) and
        the fold-blocking share in ``site_wait_s["checkpoint"]``, so
        the <5% recovery-overhead claim is auditable per site."""
        if (
            (segment + 1) % self.every_segments != 0
            or (segment + 1) >= num_segments
        ):
            return False
        t0 = time.perf_counter()
        host = [np.asarray(a) for a in arrays]
        rt = self._rt()
        if rt is None:
            with obs.span("checkpoint.write", cursor=segment + 1,
                          sync=True):
                self.save(host, segment + 1, fingerprint)
            dt = time.perf_counter() - t0
            if stats is not None and hasattr(stats, "add_busy"):
                stats.add_busy("checkpoint", dt)
                stats.add_wait("checkpoint", dt)  # inline = fully waited
            return True
        # np.asarray of a device array can be a ZERO-COPY view of the
        # device buffer (CPU backend), and the fold programs donate the
        # carry — by the time the checkpoint worker serializes, XLA may
        # have reused the memory, producing a self-consistent (checksummed
        # at write time!) but WRONG snapshot. The async path must own its
        # bytes before the fold is allowed to continue — but only copy
        # when it doesn't already: a TPU-backend asarray is an owning
        # device-to-host transfer, and doubling a ~GB carry copy in the
        # fold-blocking window is exactly what write-behind exists to
        # avoid. (`h is a` catches raw numpy input, where asarray
        # returns the caller's own — mutable — array.)
        host = [
            h if (h is not a and h.flags.owndata)
            else np.array(h, copy=True)
            for h, a in zip(host, arrays)
        ]
        # A previously-submitted write that already failed must stop the
        # fit HERE — snapshotting onto a dead disk forever, silently,
        # is the one thing the insurance layer must never do.
        self._surface_pending_failure()
        with obs.span("checkpoint.submit", cursor=segment + 1):
            self._pending.append(rt.submit(
                "checkpoint", self._write_snapshot,
                host, segment + 1, fingerprint, stats,
            ))
        if stats is not None and hasattr(stats, "add_wait"):
            stats.add_wait("checkpoint", time.perf_counter() - t0)
        return True

    def _write_snapshot(self, host_arrays, cursor, fingerprint, stats):
        """The write-behind task body (runs on the runtime's
        ``checkpoint`` worker): pure host IO — the arrays were already
        device-synced by maybe_save on the owner thread. The span covers
        exactly the region the busy counter covers (the
        trace-correctness contract)."""
        t0 = time.perf_counter()
        with obs.span("checkpoint.write", cursor=cursor, sync=False):
            self.save(host_arrays, cursor, fingerprint)
        if stats is not None and hasattr(stats, "add_busy"):
            stats.add_busy("checkpoint", time.perf_counter() - t0)

    def has_snapshot(
        self, fingerprint: Optional[Dict[str, Any]] = None
    ) -> bool:
        """Whether a snapshot exists — for ``fingerprint``'s fit, or for
        ANY fit in the directory when None (the drill/test probe)."""
        self.flush()
        if fingerprint is not None:
            return os.path.exists(
                os.path.join(self._fit_dir(fingerprint), _CKPT_META)
            )
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return False
        return any(
            os.path.exists(os.path.join(self.directory, e, _CKPT_META))
            for e in entries if e.startswith("fit-")
        )

    def clear(self, fingerprint: Optional[Dict[str, Any]] = None) -> None:
        """Remove ``fingerprint``'s snapshot (called after a successful
        fit so a later fit with the same fingerprint starts fresh) —
        ONLY that fit's: other fits sharing the directory keep theirs.
        With no fingerprint, every fit's snapshot is removed. Pending
        write-behind snapshots are flushed first — a queued write must
        not resurrect a snapshot after the clear."""
        self.flush(raise_errors=False)
        if fingerprint is not None:
            dirs = [self._fit_dir(fingerprint)]
        else:
            try:
                dirs = [
                    os.path.join(self.directory, e)
                    for e in os.listdir(self.directory)
                    if e.startswith("fit-")
                ]
            except OSError:
                dirs = []
        for d in dirs:
            for name in [_CKPT_META] + self._data_files(d):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass
            try:
                os.rmdir(d)
            except OSError:
                pass


def fingerprint_token(x: Any) -> Any:
    """A JSON-safe, address-free identity token for fingerprint fields:
    scalars pass through, sequences tokenize elementwise, callables
    become ``module.qualname`` (``repr`` would embed a memory address
    and never match across processes), arrays become a
    shape/dtype/content-CRC triple, and anything else degrades to its
    type name."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, (list, tuple)):
        return [fingerprint_token(v) for v in x]
    if callable(x):
        mod = getattr(x, "__module__", "?")
        qn = getattr(x, "__qualname__", type(x).__name__)
        return f"{mod}.{qn}"
    try:
        arr = np.asarray(x)
        if arr.dtype == object:
            return type(x).__name__
        return {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "crc": int(crc_of_array(arr)),
        }
    except Exception:
        return type(x).__name__


def _shards_behind(obj: Any, depth: int = 0):
    """The Disk*Shards object a segment source is a view over, through
    any of the documented source forms: the shards object itself, a
    ShardSource wrapper (``.shards``), a field view (``.paired``), or a
    BOUND METHOD like ``shards.segment_source`` (``__self__`` — the
    legacy callable form the solvers also accept)."""
    if obj is None or depth > 4:
        return None
    if hasattr(obj, "_checksums") and hasattr(obj, "directory"):
        return obj
    for attr in ("shards", "paired", "__self__"):
        found = _shards_behind(getattr(obj, attr, None), depth + 1)
        if found is not None:
            return found
    return None


def source_fingerprint(source: Any) -> Optional[Dict[str, Any]]:
    """Identity of a segment source's backing data, for checkpoint
    fingerprints: the shard directory plus a digest of its recorded
    per-tile checksums — a content fingerprint that costs nothing
    (the CRCs were computed at write time), so a re-ingested directory
    with different rows of the same geometry never matches a stale
    snapshot. Resolves every documented source form, including the
    bound-method ``shards.segment_source`` callable; None only for
    sources with no disk shards behind them."""
    shards = _shards_behind(source)
    if shards is None:
        return None
    sums = getattr(shards, "_checksums", None)
    return {
        "directory": getattr(shards, "directory", None),
        "checksums_crc": (
            None if sums is None
            else int(_crc(repr(sorted(sums.items())).encode()))
        ),
    }


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency; carries bfloat16 etc.

        return np.dtype(getattr(ml_dtypes, name))


def resolve_checkpoint(checkpoint) -> Optional[CheckpointSpec]:
    """Normalize a streamed fit's ``checkpoint`` argument: a
    CheckpointSpec passes through, a string becomes a spec at the
    default cadence, and None consults ``KEYSTONE_CHECKPOINT_DIR`` (the
    ``run.py --checkpoint-dir`` wiring) — unset means no checkpointing,
    exactly the pre-reliability behavior."""
    if checkpoint is None:
        env = os.environ.get("KEYSTONE_CHECKPOINT_DIR", "").strip()
        if not env:
            return None
        every = int(os.environ.get("KEYSTONE_CHECKPOINT_EVERY", "8"))
        return CheckpointSpec(env, every_segments=every)
    if isinstance(checkpoint, str):
        return CheckpointSpec(checkpoint)
    return checkpoint
