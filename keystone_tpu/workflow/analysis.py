"""Graph traversal queries (reference: workflow/AnalysisUtils.scala:3-122)."""

from __future__ import annotations

from typing import List, Set

from .graph import Graph, GraphId, NodeId, SinkId, SourceId


def get_parents(graph: Graph, gid: GraphId) -> Set[GraphId]:
    """Direct dependencies of a graph id (empty for sources)."""
    if isinstance(gid, SourceId):
        return set()
    if isinstance(gid, NodeId):
        return set(graph.get_dependencies(gid))
    if isinstance(gid, SinkId):
        return {graph.get_sink_dependency(gid)}
    raise TypeError(f"Unknown graph id {gid!r}")


def get_children(graph: Graph, gid: GraphId) -> Set[GraphId]:
    """Direct dependents of a graph id (empty for sinks)."""
    if isinstance(gid, SinkId):
        return set()
    children: Set[GraphId] = {
        n for n, deps in graph.dependencies.items() if gid in deps
    }
    children |= {s for s, d in graph.sink_dependencies.items() if d == gid}
    return children


def get_ancestors(graph: Graph, gid: GraphId) -> Set[GraphId]:
    """All transitive dependencies of a graph id (not including itself)."""
    out: Set[GraphId] = set()
    stack = list(get_parents(graph, gid))
    while stack:
        cur = stack.pop()
        if cur not in out:
            out.add(cur)
            stack.extend(get_parents(graph, cur))
    return out


def get_descendants(graph: Graph, gid: GraphId) -> Set[GraphId]:
    """All transitive dependents of a graph id (not including itself)."""
    out: Set[GraphId] = set()
    stack = list(get_children(graph, gid))
    while stack:
        cur = stack.pop()
        if cur not in out:
            out.add(cur)
            stack.extend(get_children(graph, cur))
    return out


def linearize(graph: Graph, gid: GraphId = None) -> List[GraphId]:
    """Deterministic topological ordering.

    With a target id: the ancestors of that id in dependency order, ending at
    the id itself. Without: the whole graph (all sinks' chains, sinks sorted).

    Iterative (explicit stack) on purpose: the static verifier and the
    executor walk arbitrarily deep pipelines, and a recursive DFS dies at
    Python's recursion limit around a ~1000-node chain.
    """
    order: List[GraphId] = []
    seen: Set[GraphId] = set()

    def visit(root: GraphId) -> None:
        # Each stack frame is (id, expanded?): first visit pushes the
        # parents (reverse-sorted so the smallest pops first), the second
        # emits the id after its parents have been emitted.
        stack = [(root, False)]
        while stack:
            cur, expanded = stack.pop()
            if expanded:
                order.append(cur)
                continue
            if cur in seen:
                continue
            seen.add(cur)
            stack.append((cur, True))
            for parent in sorted(
                get_parents(graph, cur), key=_sort_key, reverse=True
            ):
                if parent not in seen:
                    stack.append((parent, False))

    if gid is not None:
        visit(gid)
    else:
        for sink in sorted(graph.sink_dependencies.keys()):
            visit(sink)
    return order


def _sort_key(gid: GraphId):
    kind = {SourceId: 0, NodeId: 1, SinkId: 2}[type(gid)]
    return (kind, gid.id)
