"""Workflow layer: untyped DAG, lazy executor, optimizer, typed ML API."""

from .analysis import get_ancestors, get_children, get_descendants, get_parents, linearize
from .env import PipelineEnv, Prefix
from .executor import GraphExecutor
from .graph import Graph, GraphError, NodeId, SinkId, SourceId
from .operators import (
    DatasetExpression,
    DatasetOperator,
    DatumExpression,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    Expression,
    ExpressionOperator,
    GatherTransformerOperator,
    Operator,
    TransformerExpression,
    TransformerOperator,
)
from .optimizable import (
    OptimizableEstimator,
    OptimizableLabelEstimator,
    OptimizableTransformer,
)
from .optimizer import (
    AutoCachingOptimizer,
    Batch,
    DefaultOptimizer,
    FixedPoint,
    Once,
    Optimizer,
    Rule,
    RuleExecutor,
)
from .pipeline import (
    Chainable,
    Estimator,
    FittedPipeline,
    Identity,
    LabelEstimator,
    LambdaTransformer,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineResult,
    Transformer,
    TransformerGraph,
    transformer,
)
from .verify import (
    UNKNOWN,
    ArraySig,
    Finding,
    HostSig,
    PlanVerificationError,
    SignatureError,
    TransformerSig,
    TupleSig,
    VerifyReport,
    expect_host,
    verify_apply_graph,
    verify_fit_graph,
    verify_graph,
)
