"""Stage fusion: compile chains of device-pure transformers into ONE XLA
program.

The reference executes one Spark stage per node; its per-node overhead is a
job wave. The TPU analog of that overhead is one XLA dispatch per node — and
one missed fusion opportunity per node boundary, because elementwise work
(rectifiers, scalers, sign flips) that XLA would fuse straight into a
neighboring matmul/FFT instead round-trips HBM between programs. This module
is the whole-pipeline optimizer's TPU-specific answer (SURVEY §3's optimizer
layer doing a transform Spark has no analog of):

  - Transformers that are *row-local pure array functions* declare it by
    implementing ``device_fn()`` (returns the array->array function).
  - :class:`StageFusionRule` rewrites maximal linear chains of such nodes
    into one :class:`FusedBatchTransformer` whose batch path is a single
    ``jax.jit`` of the composed functions: one dispatch, full XLA fusion
    across the old node boundaries.

Chains never fuse across: estimator fits, multi-input nodes (gather/
combiner), sinks, prefix-published nodes (their intermediate result must
stay materializable for the state table — e.g. everything a Cacher marks),
or nodes whose results another branch consumes.

Row-local contract for ``device_fn``: output row i depends only on input row
i (elementwise over the leading axis), so mesh zero-padding rows cannot leak
into valid rows and a single trailing ``_rezero_padding`` is equivalent to
per-stage rezeroing.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax

from .env import Prefix
from .graph import Graph, NodeId, SinkId
from .optimizer import Plan, Rule
from .pipeline import Transformer

__all__ = ["FusedBatchTransformer", "StageFusionRule", "fusable"]


def fusable(op) -> bool:
    """True when the operator participates in stage fusion."""
    fn = getattr(op, "device_fn", None)
    return callable(fn) and fn() is not None


class FusedBatchTransformer(Transformer):
    """A chain of row-local transformers compiled as one program.

    Single-datum ``apply`` keeps exact per-node semantics (composition of
    the members' ``apply``); the batch path jits the composition of the
    members' ``device_fn`` functions. Host-form datasets fall back to the
    sequential member chain.
    """

    def __init__(self, members: Sequence[Transformer]):
        if len(members) < 2:
            raise ValueError("fusion needs at least two members")
        for m in members:
            if not isinstance(m, Transformer) or m.device_fn() is None:
                raise ValueError(f"member {m!r} is not device-fusable")
        self.members = list(members)
        self._build_composed()

    def _build_composed(self) -> None:
        fns = [m.device_fn() for m in self.members]

        def composed(X):
            for f in fns:
                X = f(X)
            return X

        self._composed = jax.jit(composed)

    # The jitted closure is not picklable; FittedPipeline.save() pickles the
    # whole transformer graph (the serializable-pipeline contract,
    # Pipeline.scala:38-65 / FittedPipeline.scala:12-22), so persist only the
    # members and rebuild the composition on load.
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_composed", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._build_composed()

    @property
    def label(self) -> str:
        return "Fused[" + " > ".join(m.label for m in self.members) + "]"

    def device_fn(self):
        return self._composed

    def apply(self, x):
        for m in self.members:
            x = m.apply(x)
        return x

    def batch_apply(self, data):
        if data.is_host:
            for m in self.members:
                data = m.batch_apply(data)
            return data
        return data.map_batch(self._composed)


def _consumers(plan: Graph) -> Dict[NodeId, List]:
    out: Dict[NodeId, List] = {}
    for node, deps in plan.dependencies.items():
        for d in deps:
            out.setdefault(d, []).append(node)
    for sink in plan.sinks:
        out.setdefault(plan.get_sink_dependency(sink), []).append(sink)
    return out


class StageFusionRule(Rule):
    """Fuse maximal linear chains of device-fusable transformer nodes.

    A node chains onto its single dependency when BOTH are fusable, the
    dependency has exactly one consumer (this node), and neither is
    prefix-published (prefix results must materialize for the state table).

    Fused transformers are memoized by member identity: re-optimizing a
    graph that contains the same transformer instances (the normal case —
    pipelines are re-applied with the same node objects) reuses the same
    ``jax.jit`` callable, so XLA's compile cache hits instead of retracing
    a fresh closure every optimization pass.
    """

    _CACHE_MAX = 64

    def __init__(self) -> None:
        # key: tuple of member object ids; value keeps the members alive so
        # the ids cannot be recycled while the entry exists. Bounded FIFO —
        # sessions building many distinct pipelines (sweeps) must not pin
        # executables forever.
        self._cache: Dict[tuple, FusedBatchTransformer] = {}

    def _fused(self, ops) -> FusedBatchTransformer:
        key = tuple(id(o) for o in ops)
        hit = self._cache.get(key)
        if hit is not None and all(
            a is b for a, b in zip(hit.members, ops)
        ):
            return hit
        fused = FusedBatchTransformer(ops)
        if len(self._cache) >= self._CACHE_MAX:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = fused
        return fused

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        consumers = _consumers(plan)

        def chainable(node) -> bool:
            return (
                isinstance(node, NodeId)
                and node not in prefixes
                and fusable(plan.get_operator(node))
                and len(plan.get_dependencies(node)) == 1
            )

        # Walk heads: a chain head is chainable but its dependency link
        # upward is not extendable.
        chains: List[List[NodeId]] = []
        seen = set()
        for node in sorted(plan.nodes, key=lambda n: n.id):
            if node in seen or not chainable(node):
                continue
            # extend upward
            head = node
            while True:
                dep = plan.get_dependencies(head)[0]
                if (
                    chainable(dep)
                    and len(consumers.get(dep, [])) == 1
                ):
                    head = dep
                else:
                    break
            # collect downward from head
            chain = [head]
            cur = head
            while True:
                nexts = consumers.get(cur, [])
                if len(nexts) != 1 or isinstance(nexts[0], SinkId):
                    break
                nxt = nexts[0]
                if not chainable(nxt) or plan.get_dependencies(nxt)[0] != cur:
                    break
                chain.append(nxt)
                cur = nxt
            seen.update(chain)
            if len(chain) >= 2:
                chains.append(chain)

        for chain in chains:
            ops = [plan.get_operator(n) for n in chain]
            fused = self._fused(ops)
            head_deps = plan.get_dependencies(chain[0])
            tail = chain[-1]
            # Reuse the tail node id so downstream consumers stay wired.
            plan = plan.set_operator(tail, fused)
            plan = plan.set_dependencies(tail, head_deps)
            for n in chain[:-1]:
                plan = plan.remove_node(n)

        return plan, prefixes
