"""Stage fusion: compile chains of device-pure transformers into ONE XLA
program.

The reference executes one Spark stage per node; its per-node overhead is a
job wave. The TPU analog of that overhead is one XLA dispatch per node — and
one missed fusion opportunity per node boundary, because elementwise work
(rectifiers, scalers, sign flips) that XLA would fuse straight into a
neighboring matmul/FFT instead round-trips HBM between programs. This module
is the whole-pipeline optimizer's TPU-specific answer (SURVEY §3's optimizer
layer doing a transform Spark has no analog of):

  - Transformers that are *row-local pure array functions* declare it by
    implementing ``device_fn()`` (returns the array->array function).
  - :class:`StageFusionRule` rewrites maximal linear chains of such nodes
    into one :class:`FusedBatchTransformer` whose batch path is a single
    ``jax.jit`` of the composed functions: one dispatch, full XLA fusion
    across the old node boundaries.

Chains never fuse across: estimator fits, multi-input nodes (gather/
combiner), sinks, prefix-published nodes (their intermediate result must
stay materializable for the state table — e.g. everything a Cacher marks),
or nodes whose results another branch consumes.

Row-local contract for ``device_fn``: output row i depends only on input row
i (elementwise over the leading axis), so mesh zero-padding rows cannot leak
into valid rows and a single trailing ``_rezero_padding`` is equivalent to
per-stage rezeroing.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax

from .env import Prefix
from .graph import Graph, NodeId, SinkId
from .operators import DelegatingOperator, GatherTransformerOperator
from .optimizer import Plan, Rule
from .pipeline import LabelEstimator, Transformer

__all__ = [
    "FusedBatchTransformer",
    "FusedGatherTransformer",
    "FusedFitEstimator",
    "StageFusionRule",
    "GatherFusionRule",
    "EstimatorFusionRule",
    "StreamedFitFusionRule",
    "fusable",
    "fused_members",
    "cache_would_split_fusion",
    "fusion_splitting_nodes",
]


def fusable(op) -> bool:
    """True when the operator participates in stage fusion."""
    fn = getattr(op, "device_fn", None)
    return callable(fn) and fn() is not None


def fused_members(op) -> list:
    """Fused-stage membership query: the original operators a fused program
    absorbed, or ``[op]`` for an unfused node. Lets graph-level passes
    (cache placement, cost attribution) reason about what a post-fusion
    node *contains* without knowing each fused wrapper class."""
    if isinstance(op, FusedBatchTransformer):
        return list(op.members)
    if isinstance(op, FusedGatherTransformer):
        return [m for br in op.branches for m in br] + [op.combiner]
    if isinstance(op, FusedFitEstimator):
        return list(op.members) + [op.est]
    # StreamedFitEstimator and future fused wrappers share the duck shape:
    # a ``members`` list plus the operator the members feed.
    members = getattr(op, "members", None)
    if isinstance(members, list) and members:
        tail = getattr(op, "est", None) or getattr(op, "choice", None)
        return list(members) + ([tail] if tail is not None else [])
    return [op]


def _device_fit_capable(op) -> bool:
    """True when an estimator operator would be absorbed by
    EstimatorFusionRule / StreamedFitFusionRule (a traceable fit)."""
    if getattr(op, "streamed_fit_fusable", False):
        return True
    if getattr(op, "device_fit_fn", None) is None:
        return False
    try:
        return op.device_fit_fn() is not None
    except Exception:
        return False


def cache_would_split_fusion(plan, node, prefixes, consumers=None) -> bool:
    """Boundary query for cache placement: True when splicing a ``Cacher``
    after ``node`` would sever an edge the fusion rules would otherwise
    compile into one program (a chain link, an estimator's featurize
    input, or a gather branch feeding a device combiner).

    A node for which this returns False sits on a fused-stage *boundary*:
    a Cacher there materializes a result the fused plan had to materialize
    anyway (host stages, multi-consumer intermediates, inputs of
    non-traceable fits), so insertion never splits a fusable region.
    """
    if consumers is None:
        consumers = _consumers(plan)
    op = plan.get_operator(node)
    if not fusable(op) or node in prefixes:
        return False
    outs = consumers.get(node, [])
    if len(outs) != 1 or not isinstance(outs[0], NodeId):
        # Multi-consumer nodes and sink feeds are materialization points
        # in the fused plan already.
        return False
    consumer = outs[0]
    if consumer in prefixes:
        return False
    cop = plan.get_operator(consumer)
    cdeps = plan.get_dependencies(consumer)
    single_dep = len(plan.get_dependencies(node)) == 1
    # StageFusionRule chain edge: node -> consumer fuse into one program.
    if single_dep and fusable(cop) and len(cdeps) == 1:
        return True
    # Estimator / streamed-fit fusion: the fit absorbs its DATA input.
    if len(cdeps) == 2 and cdeps[0] == node and _device_fit_capable(cop):
        return True
    # Gather branch: node feeds a gather whose output a device combiner
    # consumes (GatherFusionRule would inline the branch).
    if single_dep and isinstance(cop, GatherTransformerOperator):
        gouts = consumers.get(consumer, [])
        if len(gouts) == 1 and isinstance(gouts[0], NodeId):
            comb = plan.get_operator(gouts[0])
            if (
                getattr(comb, "device_combine_fn", None) is not None
                and comb.device_combine_fn() is not None
            ):
                return True
    return False


def fusion_splitting_nodes(plan, prefixes) -> set:
    """All nodes where a spliced Cacher would break a fusable region —
    the exclusion set AutoCacheRule applies before selecting candidates."""
    consumers = _consumers(plan)
    return {
        n
        for n in plan.nodes
        if cache_would_split_fusion(plan, n, prefixes, consumers)
    }


class FusedBatchTransformer(Transformer):
    """A chain of row-local transformers compiled as one program.

    Single-datum ``apply`` keeps exact per-node semantics (composition of
    the members' ``apply``); the batch path jits the composition of the
    members' ``device_fn`` functions. Host-form datasets fall back to the
    sequential member chain.
    """

    def __init__(self, members: Sequence[Transformer]):
        if len(members) < 2:
            raise ValueError("fusion needs at least two members")
        for m in members:
            if not isinstance(m, Transformer) or m.device_fn() is None:
                raise ValueError(f"member {m!r} is not device-fusable")
        self.members = list(members)
        self._build_composed()

    def _build_composed(self) -> None:
        fns = [m.device_fn() for m in self.members]

        def composed(X):
            for f in fns:
                X = f(X)
            return X

        self._composed = jax.jit(composed)

    # The jitted closure is not picklable; FittedPipeline.save() pickles the
    # whole transformer graph (the serializable-pipeline contract,
    # Pipeline.scala:38-65 / FittedPipeline.scala:12-22), so persist only the
    # members and rebuild the composition on load.
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_composed", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._build_composed()

    @property
    def label(self) -> str:
        return "Fused[" + " > ".join(m.label for m in self.members) + "]"

    def device_fn(self):
        return self._composed

    def apply(self, x):
        for m in self.members:
            x = m.apply(x)
        return x

    def batch_apply(self, data):
        if data.is_host:
            for m in self.members:
                data = m.batch_apply(data)
            return data
        return data.map_batch(self._composed)


class DeviceFit:
    """The traceable-fit contract estimators opt into for fit fusion.

    ``fit(F, Y, n_true, *operands) -> params`` must be traceable
    (jittable) on the featurized array; ``build(params) -> Transformer``
    runs on host with the concrete params; ``supports(d_feat)`` gates
    geometry (e.g. block divisibility) before any tracing happens.
    ``operands``: arrays the fit needs as TRACED inputs (e.g. a random-
    feature bank, the ridge λ) — a fit that closes over concrete arrays
    embeds them as HLO constants, which recompiles per instance and
    breaks the remote-compile transport at TIMIT bank sizes.

    ``program_key``: hashable logical identity of the TRACE (estimator
    family + every static config the fit function closes over). When
    set, fused programs are shared ACROSS FusedFitEstimator instances
    with identical members and key — a λ-sweep building a fresh
    estimator per λ then compiles ONE program (λ rides in ``operands``).
    The contract: two DeviceFits with equal program_key and identical
    member objects must trace identically; anything value-affecting that
    is not in the key MUST be an operand.
    """

    def __init__(self, fit, build, supports=lambda d: True, operands=(),
                 program_key=None):
        self.fit = fit
        self.build = build
        self.supports = supports
        self.operands = tuple(operands)
        self.program_key = program_key


def masked_center(F, Y, n_true: int):
    """Mean-center (F, Y) over the first ``n_true`` rows, masking padding
    BEFORE the means: inside a fused program padding rows hold
    featurize(0), which is nonzero in general (cos(b), rectifier caps,
    intercepts), so an unmasked sum would bias every scaler. Returns
    (Fc, Yc, fmean, ymean) with padding rows re-zeroed — the solvers'
    zero-padding contract. Shared by every ``device_fit_fn``.
    """
    import jax.numpy as jnp

    valid = (jnp.arange(F.shape[0]) < n_true).astype(F.dtype)[:, None]
    F = F * valid
    fmean = jnp.sum(F, axis=0) / n_true
    Fc = (F - fmean) * valid
    yvalid = valid.astype(Y.dtype)
    ymean = jnp.sum(Y * yvalid, axis=0) / n_true
    Yc = (Y - ymean) * yvalid
    return Fc, Yc, fmean, ymean


class FusedGatherTransformer(Transformer):
    """A gather-of-branches + combiner compiled as one program.

    Each branch is a (possibly empty — identity) list of row-local
    device-fusable transformers applied to the SAME input; the combiner's
    ``device_combine_fn`` merges the branch outputs (e.g. VectorCombiner's
    concat). The batch path is one jit: branch intermediates never
    round-trip HBM between programs, and XLA schedules the branches inside
    one computation (the gather's per-branch dispatch waves disappear —
    the tree analog of :class:`FusedBatchTransformer`'s chains).
    """

    def __init__(self, branches: Sequence[Sequence[Transformer]], combiner):
        if not branches:
            raise ValueError("gather fusion needs at least one branch")
        for br in branches:
            for m in br:
                if not isinstance(m, Transformer) or m.device_fn() is None:
                    raise ValueError(f"branch member {m!r} is not fusable")
        if getattr(combiner, "device_combine_fn", None) is None or (
            combiner.device_combine_fn() is None
        ):
            raise ValueError(f"combiner {combiner!r} has no device_combine_fn")
        self.branches = [list(b) for b in branches]
        self.combiner = combiner
        self._build_composed()

    def _build_composed(self) -> None:
        # Shape-specialized lowering first: a gather of
        # [RandomSign → PaddedFFT → LinearRectifier] branches packs branch
        # pairs into complex FFTs and reads X once for all branches
        # (stats.packed_fft_gather_fn) — the generic composition below
        # reads X per branch and runs one real FFT each.
        from keystone_tpu.ops.stats import packed_fft_gather_fn

        packed = packed_fft_gather_fn(self.branches, self.combiner)
        # Observable engagement: tests pin that the MNIST-shaped gather
        # actually lowers to the packed program (whose flop/traffic model
        # the bench row states), not the generic composition.
        self.uses_packed_fft = packed is not None
        if packed is not None:
            self._composed = jax.jit(packed)
            return
        branch_fns = [[m.device_fn() for m in br] for br in self.branches]
        combine = self.combiner.device_combine_fn()

        def composed(X):
            outs = []
            for fns in branch_fns:
                b = X
                for f in fns:
                    b = f(b)
                outs.append(b)
            return combine(outs)

        self._composed = jax.jit(composed)

    # Same pickling contract as FusedBatchTransformer: jitted closures are
    # rebuilt on load.
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_composed", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._build_composed()

    @property
    def label(self) -> str:
        inner = " | ".join(
            " > ".join(m.label for m in br) or "id" for br in self.branches
        )
        return f"FusedGather[{inner} -> {self.combiner.label}]"

    def device_fn(self):
        return self._composed

    def apply(self, x):
        outs = []
        for br in self.branches:
            b = x
            for m in br:
                b = m.apply(b)
            outs.append(b)
        return self.combiner.apply(tuple(outs))

    def batch_apply(self, data):
        if data.is_host:
            branch_out = []
            for br in self.branches:
                d = data
                for m in br:
                    d = m.batch_apply(d)
                branch_out.append(d)
            gathered = GatherTransformerOperator().batch_transform(branch_out)
            return self.combiner.batch_apply(gathered)
        return data.map_batch(self._composed)


# A handful of entries covers the λ-sweep reuse case; FIFO keeps a refit
# loop over many geometries from retaining one executable per geometry.
_FIT_PROGRAM_CACHE_MAX = 8

# Programs shared ACROSS FusedFitEstimator instances by (member identity,
# DeviceFit.program_key, geometry): a λ-sweep whose driver builds a fresh
# estimator object per λ (so the rule's identity memo misses) still
# compiles the featurize+fit program ONCE — λ rides as a traced operand.
# Values hold WEAK member refs (see _shared_fit_program) and hits
# re-verify identity against the dereferenced members, so recycled id()s
# cannot alias and dead pipelines don't pin their device operands; FIFO.
_SHARED_FIT_PROGRAMS: Dict[tuple, tuple] = {}
_SHARED_FIT_MAX = 16


def _shared_fit_program(members, program_key, geom_key, build):
    # Members are held through WEAK refs: the cached program's closure
    # pins the estimator's device operands (a TIMIT-scale bank is 100s of
    # MB of HBM), so once the owning pipeline is garbage-collected the
    # entry must die with it — dead entries are purged on every insert,
    # and a hit re-verifies identity against the dereferenced members (a
    # recycled id() cannot alias a live weakref).
    import weakref

    key = (tuple(id(m) for m in members), program_key, geom_key)
    hit = _SHARED_FIT_PROGRAMS.get(key)
    if hit is not None:
        live = [r() for r in hit[0]]
        if len(live) == len(members) and all(
            a is not None and a is b for a, b in zip(live, members)
        ):
            return hit[1]
    for k in [
        k for k, (refs, _) in _SHARED_FIT_PROGRAMS.items()
        if any(r() is None for r in refs)
    ]:
        del _SHARED_FIT_PROGRAMS[k]
    program = build()
    if key not in _SHARED_FIT_PROGRAMS and (
        len(_SHARED_FIT_PROGRAMS) >= _SHARED_FIT_MAX
    ):
        _SHARED_FIT_PROGRAMS.pop(next(iter(_SHARED_FIT_PROGRAMS)))
    _SHARED_FIT_PROGRAMS[key] = (
        tuple(weakref.ref(m) for m in members), program,
    )
    return program


class FusedFitEstimator(LabelEstimator):
    """An estimator fit fused with its upstream featurize program.

    Wraps a LabelEstimator exposing ``device_fit_fn()`` (a ``DeviceFit``
    with traceable ``fit(F, Y, n_true) -> params``, host ``build(params)
    -> Transformer`` and ``supports(d_feat) -> bool``) together with the
    device-fusable transformer(s) feeding it. ``fit`` then compiles
    featurize + solve into ONE program — the feature matrix never
    materializes between them (the pipeline form of the bench's hand-fused
    featurize+BCD region). Falls back to the sequential path for host
    datasets, multi-device meshes, or unsupported geometry.
    """

    def __init__(self, members: Sequence[Transformer], est):
        self.members = list(members)
        self.est = est
        # (n_true, input shape/dtype) -> jitted featurize+fit program. The
        # rule memoizes FusedFitEstimator instances, so a λ-sweep refitting
        # the same geometry reuses ONE compiled program instead of paying
        # the multi-second featurize+solve compile per fit (the same trap
        # _gram_streamed_program documents in ops/learning/lbfgs.py).
        # FIFO-bounded like _IdentityMemo: a long-lived estimator refit
        # across many geometries must not retain one executable per key.
        self._programs: Dict[tuple, object] = {}

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_programs"] = {}  # jitted closures are not picklable
        return state

    @property
    def label(self) -> str:
        inner = " > ".join(m.label for m in self.members)
        return f"FusedFit[{inner} -> {self.est.label}]"

    @property
    def weight(self) -> int:
        return getattr(self.est, "weight", 1)

    def _fallback(self, data, labels):
        for m in self.members:
            data = m.batch_apply(data)
        return self.est.fit(data, labels)

    def fit(self, data, labels):
        dev = self.est.device_fit_fn()
        multi = data.mesh is not None and any(
            s > 1 for s in dict(data.mesh.shape).values()
        )
        if dev is None or data.is_host or labels.is_host or multi:
            return self._fallback(data, labels)
        fns = [m.device_fn() for m in self.members]
        X = data.array
        d_feat = int(
            jax.eval_shape(lambda a: _compose(fns, a), X).shape[-1]
        )
        if not dev.supports(d_feat):
            return self._fallback(data, labels)
        n_true = int(data.n)

        key = (n_true, X.shape, str(X.dtype))

        def build_program():
            @jax.jit
            def fused(X, Y, operands):
                return dev.fit(_compose(fns, X), Y, n_true, *operands)

            return fused

        if dev.program_key is not None:
            fused = _shared_fit_program(
                self.members, dev.program_key, key, build_program
            )
        else:
            fused = self._programs.get(key)
            if fused is None:
                fused = build_program()
                if len(self._programs) >= _FIT_PROGRAM_CACHE_MAX:
                    self._programs.pop(next(iter(self._programs)))
                self._programs[key] = fused

        params = fused(X, labels.array, dev.operands)
        return dev.build(params)


def _compose(fns, X):
    for f in fns:
        X = f(X)
    return X



class _IdentityMemo:
    """Bounded memo keyed by the object identities of its constituents.

    Shared by every fusion rule: re-optimizing a graph built from the same
    node objects (the normal case — pipelines are re-applied with the same
    operators) must return the SAME fused wrapper, so its jitted program
    compiles once instead of once per apply (~4.5 s per miss at the
    MnistRandomFFT geometry). id() keys alone are unsafe — an evicted
    entry's ids can be recycled by the allocator — so hits re-verify every
    constituent with `is` against the live objects the cached value holds.
    """

    def __init__(self, max_entries: int = 64):
        self._cache: Dict[tuple, object] = {}
        self._max = max_entries

    def get(self, key_objs, verify, build):
        key = tuple(id(o) for o in key_objs)
        hit = self._cache.get(key)
        if hit is not None and verify(hit):
            return hit
        value = build()
        if key not in self._cache and len(self._cache) >= self._max:
            # Only evict for genuinely NEW keys: a verify-failed overwrite
            # replaces its own slot and must not drop an unrelated entry.
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = value
        return value


def _consumers(plan: Graph) -> Dict[NodeId, List]:
    out: Dict[NodeId, List] = {}
    for node, deps in plan.dependencies.items():
        for d in deps:
            out.setdefault(d, []).append(node)
    for sink in plan.sinks:
        out.setdefault(plan.get_sink_dependency(sink), []).append(sink)
    return out


class StageFusionRule(Rule):
    """Fuse maximal linear chains of device-fusable transformer nodes.

    A node chains onto its single dependency when BOTH are fusable, the
    dependency has exactly one consumer (this node), and neither is
    prefix-published (prefix results must materialize for the state table).

    Fused transformers are memoized by member identity: re-optimizing a
    graph that contains the same transformer instances (the normal case —
    pipelines are re-applied with the same node objects) reuses the same
    ``jax.jit`` callable, so XLA's compile cache hits instead of retracing
    a fresh closure every optimization pass.
    """

    def __init__(self) -> None:
        self._memo = _IdentityMemo()

    def _fused(self, ops) -> FusedBatchTransformer:
        return self._memo.get(
            ops,
            lambda hit: len(hit.members) == len(ops)
            and all(a is b for a, b in zip(hit.members, ops)),
            lambda: FusedBatchTransformer(ops),
        )

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        consumers = _consumers(plan)

        def chainable(node) -> bool:
            return (
                isinstance(node, NodeId)
                and node not in prefixes
                and fusable(plan.get_operator(node))
                and len(plan.get_dependencies(node)) == 1
            )

        # Walk heads: a chain head is chainable but its dependency link
        # upward is not extendable.
        chains: List[List[NodeId]] = []
        seen = set()
        for node in sorted(plan.nodes, key=lambda n: n.id):
            if node in seen or not chainable(node):
                continue
            # extend upward
            head = node
            while True:
                dep = plan.get_dependencies(head)[0]
                if (
                    chainable(dep)
                    and len(consumers.get(dep, [])) == 1
                ):
                    head = dep
                else:
                    break
            # collect downward from head
            chain = [head]
            cur = head
            while True:
                nexts = consumers.get(cur, [])
                if len(nexts) != 1 or isinstance(nexts[0], SinkId):
                    break
                nxt = nexts[0]
                if not chainable(nxt) or plan.get_dependencies(nxt)[0] != cur:
                    break
                chain.append(nxt)
                cur = nxt
            seen.update(chain)
            if len(chain) >= 2:
                chains.append(chain)

        for chain in chains:
            ops = [plan.get_operator(n) for n in chain]
            fused = self._fused(ops)
            head_deps = plan.get_dependencies(chain[0])
            tail = chain[-1]
            # Reuse the tail node id so downstream consumers stay wired.
            plan = plan.set_operator(tail, fused)
            plan = plan.set_dependencies(tail, head_deps)
            for n in chain[:-1]:
                plan = plan.remove_node(n)

        return plan, prefixes


class GatherFusionRule(Rule):
    """Fuse gather(branch...) -> combiner trees into one program.

    Applies when: a :class:`GatherTransformerOperator` node's single
    consumer is a combiner exposing ``device_combine_fn``; every branch
    feeding the gather is the common input itself (identity branch) or a
    device-fusable node consumed only by the gather; and all branches hang
    off ONE common dependency. Runs after :class:`StageFusionRule`, so
    multi-node branches have already collapsed to single fused nodes.

    Fused gathers are memoized by (branch members, combiner) identity —
    same policy as the other fusion rules. Without it every pipeline
    apply() re-optimizes into a FRESH FusedGatherTransformer whose new
    jit closure recompiles the whole tree (~4.5 s per apply at the
    MnistRandomFFT bench geometry — observed as a 27x end-to-end
    regression before this cache existed).
    """

    def __init__(self) -> None:
        self._memo = _IdentityMemo()

    def _fused(self, branches, comb) -> FusedGatherTransformer:
        flat = [m for br in branches for m in br] + [comb]

        def verify(hit):
            return (
                hit.combiner is comb
                and len(hit.branches) == len(branches)
                and all(
                    len(ha) == len(ba)
                    and all(a is b for a, b in zip(ha, ba))
                    for ha, ba in zip(hit.branches, branches)
                )
            )

        return self._memo.get(
            flat, verify, lambda: FusedGatherTransformer(branches, comb)
        )

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        consumers = _consumers(plan)
        for node in sorted(plan.nodes, key=lambda n: n.id):
            if node not in plan.nodes:  # removed by an earlier rewrite
                continue
            op = plan.get_operator(node)
            if not isinstance(op, GatherTransformerOperator):
                continue
            outs = consumers.get(node, [])
            if len(outs) != 1 or isinstance(outs[0], SinkId):
                continue
            comb_node = outs[0]
            comb = plan.get_operator(comb_node)
            if (
                getattr(comb, "device_combine_fn", None) is None
                or comb.device_combine_fn() is None
                or comb_node in prefixes
                or node in prefixes
            ):
                continue
            tails = plan.get_dependencies(node)
            if not tails:
                continue
            branches, common = [], None
            ok = True
            for t in tails:
                if isinstance(t, NodeId):
                    top = plan.get_operator(t)
                    if (
                        not fusable(top)
                        or t in prefixes
                        or len(plan.get_dependencies(t)) != 1
                        or consumers.get(t, []) != [node]
                    ):
                        ok = False
                        break
                    dep = plan.get_dependencies(t)[0]
                    members = (
                        top.members
                        if isinstance(top, FusedBatchTransformer)
                        else [top]
                    )
                else:
                    dep, members = t, []  # identity branch off the source
                if common is None:
                    common = dep
                elif dep != common:
                    ok = False
                    break
                branches.append(members)
            if not ok or common is None:
                continue
            fused = self._fused(branches, comb)
            plan = plan.set_operator(comb_node, fused)
            plan = plan.set_dependencies(comb_node, [common])
            plan = plan.remove_node(node)
            for t in tails:
                if isinstance(t, NodeId):
                    plan = plan.remove_node(t)
            consumers = _consumers(plan)
        return plan, prefixes


class StreamedFitFusionRule(Rule):
    """Bind the upstream featurize program INTO a capacity-selected
    streaming estimator.

    Applies when a node's operator declares ``streamed_fit_fusable``
    (the cost model's StreamingLeastSquaresChoice) and its DATA input is
    a fusable transformer consumed only by it. The rewrite calls the
    choice's ``fuse_with_members(members)``, whose fit generates features
    per row tile inside the solver — the feature matrix never
    materializes, which is the entire point of the selection: the cost
    model picked this tier BECAUSE the featurized operand cannot fit.
    Runs after Stage/Gather fusion (upstream is one node) and after
    NodeOptimizationRule (the choice has been swapped in).
    """

    def __init__(self) -> None:
        self._memo = _IdentityMemo()

    def _fused(self, members, choice):
        return self._memo.get(
            list(members) + [choice],
            lambda hit: hit.choice is choice
            and len(hit.members) == len(members)
            and all(a is b for a, b in zip(hit.members, members)),
            lambda: choice.fuse_with_members(members),
        )

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        consumers = _consumers(plan)
        for node in sorted(plan.nodes, key=lambda n: n.id):
            if node not in plan.nodes:
                continue
            op = plan.get_operator(node)
            if not getattr(op, "streamed_fit_fusable", False):
                continue
            deps = plan.get_dependencies(node)
            if len(deps) != 2:
                continue
            dnode = deps[0]
            unbindable = None
            dop = None
            if not isinstance(dnode, NodeId) or dnode in prefixes:
                unbindable = "its data input is a source/prefix-published node"
            else:
                dop = plan.get_operator(dnode)
                if not fusable(dop) or len(plan.get_dependencies(dnode)) != 1:
                    unbindable = "its upstream transformer is not device-fusable"
            if unbindable:
                _logger().warning(
                    "capacity-selected streaming fit at %s cannot bind its "
                    "featurizer (%s): the fit will tile-stream MATERIALIZED "
                    "features — the memory-wall selection may not hold",
                    getattr(op, "label", op), unbindable,
                )
                continue
            # The featurize node may have other consumers ONLY when they
            # are this estimator's own apply sites (delegating nodes fed
            # by the same featurizer — CSE merges the train and apply
            # chains when the pipeline is applied to its training data).
            # Those get rewired to RAW input below; any other consumer
            # means the featurized result is genuinely needed elsewhere
            # and fusing would force recomputation — bail.
            def _is_own_delegate(c):
                return (
                    isinstance(c, NodeId)
                    and isinstance(plan.get_operator(c), DelegatingOperator)
                    and list(plan.get_dependencies(c)) == [node, dnode]
                )

            shared_delegates = [
                c for c in consumers.get(dnode, []) if c != node
            ]
            if not all(_is_own_delegate(c) for c in shared_delegates):
                _logger().warning(
                    "capacity-selected streaming fit at %s cannot bind its "
                    "featurizer (featurized result has other consumers): "
                    "the fit will tile-stream MATERIALIZED features — the "
                    "memory-wall selection may not hold",
                    getattr(op, "label", op),
                )
                continue
            members = (
                dop.members
                if isinstance(dop, FusedBatchTransformer)
                else [dop]
            )
            fused = self._fused(members, op)
            # Rewiring apply sites to feed RAW rows requires the fitted
            # model to disambiguate raw vs featurized input by width —
            # only provable for bank featurizers with d_in != d_feat.
            can_rewire = getattr(fused, "can_serve_raw_input", False)
            raw_in = plan.get_dependencies(dnode)[0]
            plan = plan.set_operator(node, fused)
            plan = plan.set_dependencies(node, [raw_in, deps[1]])
            if can_rewire:
                for c in shared_delegates:
                    plan = plan.set_dependencies(c, [node, raw_in])
            if can_rewire or not shared_delegates:
                plan = plan.remove_node(dnode)
            # else: dnode stays — the shared delegates keep featurizing
            # upstream and the width-adaptive model takes the identity
            # path on their featurized input.

            # Remaining apply sites (delegating nodes) may featurize via a
            # TWIN node holding the SAME operator (the fusion memos
            # guarantee object identity for train/apply twins — the
            # non-merged case, e.g. applying to held-out data). Rewire
            # them to feed RAW input too: the fitted model then carries
            # the featurizer and applies it tile-wise, so inference never
            # materializes the feature matrix either. Sites that keep
            # their featurizer still work — the fitted model is
            # width-adaptive (StreamingFeaturizedLinearModel.d_in).
            consumers = _consumers(plan)
            if can_rewire:
                delegates = [
                    c for c in consumers.get(node, [])
                    if isinstance(c, NodeId)
                    and isinstance(plan.get_operator(c), DelegatingOperator)
                ]
                for c in delegates:
                    cdeps = plan.get_dependencies(c)
                    ain = cdeps[1] if len(cdeps) == 2 else None
                    if ain == raw_in:
                        continue  # rewired above (merged case)
                    if (
                        isinstance(ain, NodeId)
                        and plan.get_operator(ain) is dop
                        and len(plan.get_dependencies(ain)) == 1
                    ):
                        plan = plan.set_dependencies(
                            c, [cdeps[0], plan.get_dependencies(ain)[0]]
                        )
                        if consumers.get(ain, []) == [c]:
                            plan = plan.remove_node(ain)
                consumers = _consumers(plan)
        return plan, prefixes


def _logger():
    import logging

    return logging.getLogger("keystone_tpu.fusion")


class EstimatorFusionRule(Rule):
    """Fuse an estimator fit with the device-fusable node feeding it.

    Applies when a LabelEstimator node exposing ``device_fit_fn()`` takes
    its DATA input from a fusable transformer whose only consumer is this
    estimator (and which is not prefix-published). The featurize + solve
    then compile as one program (:class:`FusedFitEstimator`) — the
    pipeline-level form of the manually fused featurize+BCD bench region.
    Runs after Stage/Gather fusion so the upstream is a single node.

    Fused estimators are memoized by (member, estimator) identity — the
    same policy as StageFusionRule — so a λ-sweep re-optimizing graphs
    built from the same node objects reuses ONE FusedFitEstimator, whose
    per-geometry compiled program cache then hits across fits.
    """

    def __init__(self) -> None:
        self._memo = _IdentityMemo()

    def _fused(self, members, est) -> FusedFitEstimator:
        return self._memo.get(
            list(members) + [est],
            lambda hit: hit.est is est
            and len(hit.members) == len(members)
            and all(a is b for a, b in zip(hit.members, members)),
            lambda: FusedFitEstimator(members, est),
        )

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        consumers = _consumers(plan)
        for node in sorted(plan.nodes, key=lambda n: n.id):
            if node not in plan.nodes:  # removed by an earlier rewrite
                continue
            op = plan.get_operator(node)
            if getattr(op, "device_fit_fn", None) is None:
                continue
            try:
                if op.device_fit_fn() is None:
                    continue
            except Exception:
                continue
            deps = plan.get_dependencies(node)
            if len(deps) != 2:
                continue
            dnode = deps[0]
            if not isinstance(dnode, NodeId) or dnode in prefixes:
                continue
            dop = plan.get_operator(dnode)
            if not fusable(dop) or len(plan.get_dependencies(dnode)) != 1:
                continue
            if consumers.get(dnode, []) != [node]:
                continue
            members = (
                dop.members
                if isinstance(dop, FusedBatchTransformer)
                else [dop]
            )
            fused = self._fused(members, op)
            plan = plan.set_operator(node, fused)
            plan = plan.set_dependencies(
                node, [plan.get_dependencies(dnode)[0], deps[1]]
            )
            plan = plan.remove_node(dnode)
            consumers = _consumers(plan)
        return plan, prefixes
