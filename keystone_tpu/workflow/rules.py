"""Standard whole-pipeline optimization rules.

Each rule mirrors its reference counterpart:
  - ExtractSaveablePrefixes  (reference: workflow/ExtractSaveablePrefixes.scala:9-22)
  - SavedStateLoadRule       (reference: workflow/SavedStateLoadRule.scala:7-20)
  - UnusedBranchRemovalRule  (reference: workflow/UnusedBranchRemovalRule.scala:7-24)
  - EquivalentNodeMergeRule  (reference: workflow/EquivalentNodeMergeRule.scala:13-47)
  - NodeOptimizationRule     (reference: workflow/NodeOptimizationRule.scala:143-198)
"""

from __future__ import annotations

from typing import Dict, Set

import numpy as np

from . import analysis
from .env import PipelineEnv, Prefix
from .graph import Graph, NodeId, SourceId
from .operators import EstimatorOperator, ExpressionOperator
from .optimizer import Plan, Rule


def _is_saveable(op) -> bool:
    from keystone_tpu.ops.util import Cacher

    return isinstance(op, (Cacher, EstimatorOperator))


class ExtractSaveablePrefixes(Rule):
    """Mark nodes whose results should be published to / loaded from the global
    prefix state table: Cacher nodes and estimator fits.

    Re-extraction MERGES: marks carried in from an earlier batch win, and
    only unmarked saveable nodes gain fresh prefixes. AutoCachingOptimizer
    runs this rule a second time after post-fusion cache placement, so the
    Cachers AutoCacheRule just inserted get published for cross-fit reuse
    without re-keying estimator marks the first extraction computed on the
    pre-fusion graph (whose keys earlier fits already published under).
    Marks for nodes no longer in the plan are dropped."""

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        new_prefixes: Dict[NodeId, Prefix] = {
            n: p for n, p in prefixes.items() if n in plan.operators
        }
        memo: Dict[NodeId, Prefix] = {}
        for node, op in plan.operators.items():
            if node in new_prefixes or not _is_saveable(op):
                continue
            # Prefixes are undefined for source-dependent nodes: skip them.
            ancestors = analysis.get_ancestors(plan, node)
            if any(isinstance(a, SourceId) for a in ancestors):
                continue
            new_prefixes[node] = Prefix.find(plan, node, memo)
        return plan, new_prefixes


class SavedStateLoadRule(Rule):
    """Replace marked nodes whose prefix exists in PipelineEnv.state with
    constant ExpressionOperators."""

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        state = PipelineEnv.get_or_create().state
        graph = plan
        for node, prefix in prefixes.items():
            expr = state.get(prefix)
            if expr is not None:
                graph = graph.set_operator(
                    node, ExpressionOperator(expr, label="SavedState")
                ).set_dependencies(node, [])
        return graph, prefixes


class UnusedBranchRemovalRule(Rule):
    """Dead-code elimination: drop nodes/sources that are not ancestors of any sink."""

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        ancestors_of_sinks: Set = set()
        for sink in plan.sinks:
            ancestors_of_sinks |= analysis.get_ancestors(plan, sink)

        live_nodes = {a for a in ancestors_of_sinks if isinstance(a, NodeId)}
        live_sources = {a for a in ancestors_of_sinks if isinstance(a, SourceId)}

        graph = plan
        for source in plan.sources - live_sources:
            graph = graph.remove_source(source)
        new_prefixes = dict(prefixes)
        for node in plan.nodes - live_nodes:
            graph = graph.remove_node(node)
            new_prefixes.pop(node, None)
        return graph, new_prefixes


class EquivalentNodeMergeRule(Rule):
    """Common-subexpression elimination: merge nodes with equal (operator, deps).

    Operator equality is Python ``==``/``hash``; node-library operators that are
    deterministic functions of their parameters define structural equality
    (dataclasses), everything else defaults to identity.
    """

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        groups: Dict = {}
        for node in plan.nodes:
            try:
                key = (plan.get_operator(node), plan.get_dependencies(node))
                groups.setdefault(key, []).append(node)
            except TypeError:
                # Unhashable operator: never mergeable.
                groups[(id(plan.get_operator(node)), node)] = [node]

        if all(len(g) == 1 for g in groups.values()):
            return plan, prefixes

        graph = plan
        new_prefixes = dict(prefixes)
        for group in groups.values():
            if len(group) <= 1:
                continue
            keep = min(group, key=lambda n: n.id)
            for node in group:
                if node == keep:
                    continue
                graph = graph.replace_dependency(node, keep).remove_node(node)
            merged_prefix = next(
                (new_prefixes[n] for n in group if n in new_prefixes), None
            )
            if merged_prefix is not None:
                for n in group:
                    new_prefixes.pop(n, None)
                new_prefixes[keep] = merged_prefix
        return graph, new_prefixes


class NodeOptimizationRule(Rule):
    """Node-level algorithm selection: run optimizable nodes' ``optimize`` hook
    on a sample of their input and swap in the chosen concrete operator.

    The reference executes the graph with a sampling executor
    (NodeOptimizationRule.scala:14-136) to obtain per-node input samples. Here
    the sample collector executes the graph with datasets truncated to
    ``samples_per_shard * num_shards`` rows before each optimizable node.
    """

    def __init__(self, samples_per_shard: int = 3):
        self.samples_per_shard = samples_per_shard

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        from .optimizable import (
            OptimizableEstimator,
            OptimizableLabelEstimator,
            OptimizableTransformer,
        )

        optimizable_nodes = [
            n
            for n, op in plan.operators.items()
            if isinstance(
                op, (OptimizableTransformer, OptimizableEstimator, OptimizableLabelEstimator)
            )
            # Nodes downstream of unbound sources can't be sampled.
            and not any(
                isinstance(a, SourceId) for a in analysis.get_ancestors(plan, n)
            )
        ]
        if not optimizable_nodes:
            return plan, prefixes

        samples = _collect_samples(plan, optimizable_nodes, self.samples_per_shard)

        graph = plan
        for node in optimizable_nodes:
            op = plan.get_operator(node)
            sample_inputs = samples.get(node)
            if sample_inputs is None:
                continue
            chosen = op.optimize(*sample_inputs)
            if chosen is not None:
                graph = graph.set_operator(node, chosen)
        return graph, prefixes


def _attach_sparse_width(op, value, dep_values) -> None:
    """Thread the TRUE feature width onto a derived sparse sample.

    ``optimize()`` measures d as ``indices.max()+1`` over the sampled rows,
    which undershoots whenever the handful of samples misses the top
    feature ids. The width is knowable without sampling in every real
    producer: a vectorizer declares it (``sparse_output_dim``) — whether
    chained directly or applied through a DelegatingOperator as a fitted
    transformer riding in the dep values — a Sparsify-style node's dense
    input carries it as the dense shape, and a width-preserving transform
    inherits its sparse input's. Attach it as ``total_d`` so the cost
    model prices resident_bytes at the true width.
    """
    from keystone_tpu.data import Dataset
    from keystone_tpu.ops.sparse import is_sparse_dataset

    if not is_sparse_dataset(value):
        return
    # The declaring operator is the node's own op, or (the fit-then-apply
    # route) a fitted transformer among the dep values.
    for declarer in [op] + [v for v in dep_values if not isinstance(v, Dataset)]:
        declared = getattr(declarer, "sparse_output_dim", None)
        if callable(declared):
            try:
                declared = declared()
            except Exception:
                declared = None
        if declared:
            value.total_d = int(declared)
            return
    dep_ds = [v for v in dep_values if isinstance(v, Dataset)]
    for v in dep_ds:
        if is_sparse_dataset(v):
            inherited = getattr(v, "total_d", None)
            if inherited:
                value.total_d = int(inherited)
                return
        else:
            try:
                import jax.tree_util as jtu

                leaves = jtu.tree_leaves(v.data)
                if len(leaves) == 1 and getattr(leaves[0], "ndim", 0) >= 2:
                    value.total_d = int(leaves[0].shape[-1])
                    return
            except Exception:
                pass


def _collect_samples(plan: Graph, nodes, samples_per_shard: int):
    """Execute ancestor chains of the target nodes with row-sampled datasets.

    Returns {node: tuple(sampled dep values)}.
    """
    from keystone_tpu.data import Dataset
    from .operators import DatasetOperator

    def _row_bytes(ds: Dataset):
        """Approximate bytes per row of the raw source (streaming-tier
        capacity models keep RAW rows resident, not features)."""
        try:
            if ds.is_host:
                items = ds.to_list()
                return float(np.asarray(items[0]).nbytes) if items else None
            import jax.tree_util as jtu

            return float(
                sum(
                    int(np.prod(x.shape[1:])) * x.dtype.itemsize
                    for x in jtu.tree_leaves(ds.data)
                )
            )
        except Exception:
            return None

    def sample_dataset(ds: Dataset) -> Dataset:
        from keystone_tpu.ops.sparse import is_sparse_dataset

        num_shards = 1
        if ds.mesh is not None:
            from keystone_tpu.parallel import mesh as mesh_lib

            num_shards = mesh_lib.axis_size(ds.mesh, mesh_lib.DATA_AXIS)
        k = min(ds.n, samples_per_shard * max(num_shards, 1))
        if getattr(ds, "is_shard_backed", False):
            # Out-of-core source: sample the FIRST segment only (never
            # materialize the dataset just to cost-model it) and carry
            # the disk-tier capacity facts the selector prices on.
            src = ds.shard_source
            first = src.load(0)
            arr = (
                first if isinstance(first, np.ndarray)
                else np.asarray(first[0]).reshape(
                    -1, np.asarray(first[0]).shape[-1]
                )
            )
            rows = min(k, arr.shape[0], ds.n)
            out = Dataset(np.asarray(arr[:rows]), n=rows)
            out.total_n = ds.n
            out.source_row_bytes = src.row_bytes or float(
                arr.shape[-1] * arr.dtype.itemsize
            )
            out.shard_backed = True
            out.shard_segment_bytes = src.segment_bytes
            return out
        if ds.is_host:
            out = Dataset.of(ds.to_list()[:k])
        else:
            import jax.tree_util as jtu

            data = jtu.tree_map(lambda x: x[:k], ds.data)
            out = Dataset(data, n=k)
        # Cost models need the FULL dataset size (the reference passes it via
        # numPerPartition, LeastSquaresEstimator.scala:60-64); the sample only
        # supplies d, k, and sparsity.
        out.total_n = ds.n
        out.source_row_bytes = _row_bytes(ds)
        if is_sparse_dataset(ds):
            # The TRUE feature width, measured over the FULL index array —
            # ``indices.max()+1`` over a handful of sampled rows can
            # undershoot it by orders of magnitude, mis-pricing every
            # sparse candidate's resident_bytes downstream (cost.py).
            try:
                out.total_d = int(np.asarray(ds.data["indices"]).max()) + 1
            except Exception:
                pass
        return out

    # Execute with a private memo table, sampling at every DatasetOperator.
    memo: Dict[NodeId, object] = {}

    def evaluate(gid):
        if gid in memo:
            return memo[gid]
        op = plan.get_operator(gid)
        deps = [evaluate(d) for d in plan.get_dependencies(gid)]
        if isinstance(op, DatasetOperator):
            value = sample_dataset(Dataset.of(op.dataset))
        else:
            exprs = [_wrap(d) for d in deps]
            value = op.execute(exprs).get()
            # Operators derive NEW Datasets, losing the sample metadata —
            # without re-attaching it here a chained optimizable node would
            # see n = the handful of sampled rows and cost-select for a
            # tiny problem (the reference's numPerPartition reaches its
            # estimators whole, LeastSquaresEstimator.scala:60-64).
            if isinstance(value, Dataset):
                dep_ds = [v for v in deps if isinstance(v, Dataset)]
                totals = [
                    v.total_n for v in dep_ds
                    if getattr(v, "total_n", None) is not None
                ]
                if totals:
                    value.total_n = max(totals)
                raws = [
                    v.source_row_bytes for v in dep_ds
                    if getattr(v, "source_row_bytes", None) is not None
                ]
                if raws:
                    value.source_row_bytes = max(raws)
                # Disk-tier provenance: a derived sample whose SOURCE is
                # shard-backed keeps the flag ONLY through device-fusable
                # operators — exactly the chains StreamedFitFusionRule can
                # rewire to consume the raw shard source. Through a
                # non-fusable op the fit would receive a materialized
                # intermediate, so pricing the disk tier as feasible
                # there would admit the very host-RAM blowup the budget
                # cut exists to prevent.
                from .fusion import fusable

                if fusable(op) and any(
                    getattr(v, "shard_backed", False) for v in dep_ds
                ):
                    value.shard_backed = True
                    segs = [
                        v.shard_segment_bytes for v in dep_ds
                        if getattr(v, "shard_segment_bytes", None)
                        is not None
                    ]
                    if segs:
                        value.shard_segment_bytes = max(segs)
                _attach_sparse_width(op, value, deps)
        memo[gid] = value
        return value

    def _wrap(value):
        from .operators import DatasetExpression, DatumExpression, TransformerExpression
        from .operators import TransformerOperator

        if isinstance(value, Dataset):
            return DatasetExpression(lambda v=value: v)
        if isinstance(value, TransformerOperator):
            return TransformerExpression(lambda v=value: v)
        return DatumExpression(lambda v=value: v)

    out = {}
    for node in nodes:
        try:
            dep_values = tuple(evaluate(d) for d in plan.get_dependencies(node))
            # Optimization hooks take Dataset samples; datum-fed nodes keep
            # their default implementation (the reference's sampling executor
            # likewise only samples RDD inputs).
            if not all(isinstance(v, Dataset) for v in dep_values):
                out[node] = None
            else:
                out[node] = dep_values
        except Exception:
            out[node] = None
    return out
