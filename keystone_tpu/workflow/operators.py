"""Operators and lazy expressions — the untyped execution units stored in graph nodes.

Mirrors the behavioral contract of the reference's Operator/Expression layer
(reference: src/main/scala/keystoneml/workflow/Operator.scala:10-177,
Expression.scala:9-44): an operator consumes a sequence of expressions and
produces an expression; expressions are lazy, memoized thunks so that nothing
computes until a sink's value is demanded.

Dataset payloads here are :class:`keystone_tpu.data.Dataset` values (sharded
device arrays or host object collections) instead of RDDs.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence


class Expression:
    """A lazy, memoized result of executing an operator."""

    def __init__(self, thunk: Callable[[], Any]):
        self._thunk = thunk
        self._computed = False
        self._value: Any = None

    def get(self) -> Any:
        if not self._computed:
            self._value = self._thunk()
            self._computed = True
            self._thunk = None  # free captured inputs once computed
        return self._value


class DatasetExpression(Expression):
    """Expression whose value is a Dataset (the RDD analog)."""


class DatumExpression(Expression):
    """Expression whose value is a single datum."""


class TransformerExpression(Expression):
    """Expression whose value is a fitted TransformerOperator."""


class Operator:
    """Base class for all graph operators.

    Equality/hash default to object identity; node-library operators that are
    deterministic functions of their constructor parameters override
    ``signature`` (or are dataclasses) to enable common-subexpression
    elimination and prefix-based state reuse across pipelines.
    """

    @property
    def label(self) -> str:
        return type(self).__name__

    def execute(self, deps: Sequence[Expression]) -> Expression:
        raise NotImplementedError


class DatasetOperator(Operator):
    """Zero-input operator that always emits a fixed dataset (Operator.scala:25-38)."""

    def __init__(self, dataset: Any):
        self.dataset = dataset

    @property
    def label(self) -> str:
        return f"Dataset[{type(self.dataset).__name__}]"

    def execute(self, deps: Sequence[Expression]) -> DatasetExpression:
        if deps:
            raise ValueError("DatasetOperator does not take any inputs")
        ds = self.dataset
        return DatasetExpression(lambda: ds)

    # Two wrappers of the same dataset object are the same logical operator
    # (the analog of case-class equality over an RDD reference), enabling
    # prefix-state reuse across pipelines built over the same data.
    def __eq__(self, other: object) -> bool:
        return type(other) is DatasetOperator and other.dataset is self.dataset

    def __hash__(self) -> int:
        return id(self.dataset)


class DatumOperator(Operator):
    """Zero-input operator that always emits a fixed single datum (Operator.scala:41-56)."""

    def __init__(self, datum: Any):
        self.datum = datum

    @property
    def label(self) -> str:
        return f"Datum[{type(self.datum).__name__}]"

    def execute(self, deps: Sequence[Expression]) -> DatumExpression:
        if deps:
            raise ValueError("DatumOperator does not take any inputs")
        datum = self.datum
        return DatumExpression(lambda: datum)

    def __eq__(self, other: object) -> bool:
        return type(other) is DatumOperator and other.datum is self.datum

    def __hash__(self) -> int:
        return id(self.datum)


def _split_deps(deps: Sequence[Expression]):
    """Validate that deps are homogeneous (all dataset or all datum)."""
    if not deps:
        raise ValueError("Transformer dependencies may not be empty")
    all_ds = all(isinstance(d, DatasetExpression) for d in deps)
    all_datum = all(isinstance(d, DatumExpression) for d in deps)
    if not (all_ds or all_datum):
        raise ValueError(
            "Transformer dependencies must be either all datasets or all single data items"
        )
    return all_ds


class TransformerOperator(Operator):
    """Operator that maps datums->datum and datasets->dataset (Operator.scala:66-100).

    Subclasses implement ``single_transform`` (a sequence of datum values to a
    value) and ``batch_transform`` (a sequence of Dataset values to a Dataset).
    Execution is lazy.
    """

    def single_transform(self, inputs: Sequence[Any]) -> Any:
        raise NotImplementedError

    def batch_transform(self, inputs: Sequence[Any]) -> Any:
        raise NotImplementedError

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if _split_deps(deps):
            return DatasetExpression(lambda: self.batch_transform([d.get() for d in deps]))
        return DatumExpression(lambda: self.single_transform([d.get() for d in deps]))


class EstimatorOperator(Operator):
    """Operator producing a fitted TransformerOperator from datasets (Operator.scala:112-125)."""

    def fit_datasets(self, inputs: Sequence[Any]) -> TransformerOperator:
        raise NotImplementedError

    def execute(self, deps: Sequence[Expression]) -> TransformerExpression:
        if not all(isinstance(d, DatasetExpression) for d in deps):
            raise ValueError("Estimator dependencies must all be datasets")
        return TransformerExpression(lambda: self.fit_datasets([d.get() for d in deps]))


class DelegatingOperator(Operator):
    """Applies the fitted transformer from dep 0 to the remaining deps (Operator.scala:135-164)."""

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if not deps:
            raise ValueError("DelegatingOperator dependencies may not be empty")
        transformer_expr = deps[0]
        rest = deps[1:]
        if not isinstance(transformer_expr, TransformerExpression):
            raise ValueError("DelegatingOperator's first dependency must be a transformer")
        if _split_deps(rest):
            return DatasetExpression(
                lambda: transformer_expr.get().batch_transform([d.get() for d in rest])
            )
        return DatumExpression(
            lambda: transformer_expr.get().single_transform([d.get() for d in rest])
        )


class ExpressionOperator(Operator):
    """Zero-input operator wrapping an already-computed expression (Operator.scala:172-177).

    Used by the saved-state-load rule to splice previously computed results
    (fitted transformers, cached datasets) back into a graph.
    """

    def __init__(self, expression: Expression, label: Optional[str] = None):
        self.expression = expression
        self._label = label

    @property
    def label(self) -> str:
        return self._label or "Expression"

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if deps:
            raise ValueError("ExpressionOperator does not take any inputs")
        return self.expression


class GatherTransformerOperator(TransformerOperator):
    """N-ary gather used by ``Pipeline.gather`` (GatherTransformerOperator.scala:9-18).

    For datums: emits the tuple of branch values. For datasets: emits a Dataset
    whose per-item value is the tuple of the branches' per-item values (the
    array-world analog of zip-then-concat).
    """

    def single_transform(self, inputs: Sequence[Any]) -> Any:
        return tuple(inputs)

    def batch_transform(self, inputs: Sequence[Any]) -> Any:
        from keystone_tpu.data import Dataset

        return Dataset.gather(list(inputs))

    def __eq__(self, other: object) -> bool:
        return type(other) is GatherTransformerOperator

    def __hash__(self) -> int:
        return hash(GatherTransformerOperator)
