"""Memoized pull-based graph execution (reference: workflow/GraphExecutor.scala:14-81).

On first demand the executor (optionally) runs the global whole-pipeline
optimizer, then recursively evaluates the requested id's dependency chain,
memoizing each node's Expression and publishing results for nodes whose prefix
was marked by the optimizer into the global PipelineEnv state table.

Profile collection: every source-free node's first force is timed and its
result size estimated, feeding the autocache observed-profile table. The
executor runs the OPTIMIZED graph, so what gets measured is the cost of the
post-fusion programs themselves — the full-scale ground truth AutoCacheRule
prefers over its sampled extrapolations when placing caches.
"""

from __future__ import annotations

import time
from typing import Dict, Mapping, Optional

from . import analysis
from .env import PipelineEnv, Prefix
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .operators import Expression, ExpressionOperator


class GraphExecutor:
    """Executes parts of a graph, memoizing results. Not thread-safe."""

    def __init__(
        self,
        graph: Graph,
        optimize: bool = True,
        prefixes: Optional[Mapping[NodeId, Prefix]] = None,
    ):
        self.graph = graph
        self.optimize = optimize
        self._optimized_graph: Optional[Graph] = graph if not optimize else None
        self._prefixes: Optional[Mapping[NodeId, Prefix]] = prefixes
        self._execution_state: Dict[GraphId, Expression] = {}
        self._profile_key_memo: Dict[NodeId, Prefix] = {}

    def _ensure_optimized(self) -> Graph:
        if self._optimized_graph is None:
            if self.optimize:
                from keystone_tpu import obs

                # The lazy-path analog of Pipeline.fit's fit.optimize
                # span: pipelines driven through .get()/apply() optimize
                # HERE, and the optimizer.rule.* spans need this parent
                # to read as one phase in the trace.
                with obs.span("executor.optimize",
                              nodes=len(self.graph.operators)):
                    graph, prefixes = PipelineEnv.get_or_create().optimizer.execute(self.graph, {})
            else:
                graph, prefixes = self.graph, self._prefixes or {}
            self._optimized_graph = graph
            self._prefixes = prefixes
        return self._optimized_graph

    @property
    def optimized_graph(self) -> Graph:
        return self._ensure_optimized()

    def _source_dependants(self, graph: Graph) -> set:
        out = set()
        for source in graph.sources:
            out |= analysis.get_descendants(graph, source)
            out.add(source)
        return out

    def execute(self, graph_id: GraphId) -> Expression:
        graph = self._ensure_optimized()
        if graph_id in self._source_dependants(graph):
            raise ValueError("May not execute GraphIds that depend on unconnected sources.")
        return self._execute(graph, graph_id)

    def _execute(self, graph: Graph, graph_id: GraphId) -> Expression:
        if graph_id in self._execution_state:
            return self._execution_state[graph_id]

        if isinstance(graph_id, SourceId):
            raise ValueError("SourceIds may not be executed.")
        if isinstance(graph_id, SinkId):
            expression = self._execute(graph, graph.get_sink_dependency(graph_id))
        else:
            dep_exprs = [self._execute(graph, dep) for dep in graph.get_dependencies(graph_id)]
            operator = graph.get_operator(graph_id)
            expression = operator.execute(dep_exprs)
            self._observe(graph, graph_id, operator, dep_exprs, expression)
            self._annotate_failures(graph_id, operator, dep_exprs, expression)
            self._trace_node(graph_id, operator, expression)
            # Publish results the optimizer marked for prefix-state reuse.
            if self._prefixes and graph_id in self._prefixes:
                PipelineEnv.get_or_create().state[self._prefixes[graph_id]] = expression

        self._execution_state[graph_id] = expression
        return expression

    def _trace_node(self, graph_id, operator, expression) -> None:
        """Wrap the node's thunk in an ``executor.node`` span (obs
        plane): lazy pipelines do their real work at first force, on
        whatever thread demands the value, and deps force inside the
        thunk — so spans nest into the causal tree the executor actually
        ran. Wrapped OUTSIDE _observe/_annotate_failures so the span
        covers the node's full forced wall. One no-op branch per force
        when tracing is off; ExpressionOperator splices are skipped
        (their value was computed elsewhere — a span would misattribute
        it)."""
        if isinstance(operator, ExpressionOperator):
            return
        orig = getattr(expression, "_thunk", None)
        if orig is None:  # already computed (shared expression)
            return
        from keystone_tpu import obs

        def traced():
            with obs.span("executor.node", node=graph_id.id,
                          operator=type(operator).__name__):
                return orig()

        expression._thunk = traced

    def _annotate_failures(self, graph_id, operator, dep_exprs, expression) -> None:
        """Wrap the node's thunk so a runtime failure carries the same
        coordinates a static-verifier report would: the NodeId, the
        operator class, and the inferred signatures of its inputs. The
        exception TYPE is preserved (the context is appended in place,
        once, at the deepest failing node) so callers' except clauses
        and tests keep matching — see verify.annotate_node_error."""
        orig = getattr(expression, "_thunk", None)
        if orig is None:  # already computed (shared expression)
            return
        from .verify import annotate_node_error

        def annotated():
            try:
                return orig()
            except Exception as e:
                dep_values = [
                    d._value if d._computed else None for d in dep_exprs
                ]
                annotate_node_error(e, graph_id, operator, dep_values)
                raise

        expression._thunk = annotated

    def _observe(self, graph, graph_id, operator, dep_exprs, expression) -> None:
        """Arrange for the node's first force to record an observed profile.

        The expression's thunk is wrapped so that when (and only when) the
        value is actually demanded, the node's own wall time — deps forced
        first, which every core operator's thunk does anyway — and result
        bytes land in the autocache observed-profile table under the node's
        logical Prefix. ExpressionOperator nodes are skipped (their value
        was computed elsewhere; timing the splice says nothing about the
        operator's cost), as are source-dependent nodes (no Prefix).
        """
        if isinstance(operator, ExpressionOperator):
            return
        orig = getattr(expression, "_thunk", None)
        if orig is None:  # already computed (shared expression)
            return
        from . import autocache

        key = autocache.observed_profile_key(
            graph, graph_id, self._profile_key_memo
        )
        if key is None:
            return

        def drain(value):
            """Wait out async JAX dispatch on a value's device arrays."""
            try:
                import jax

                jax.block_until_ready(
                    [x for x in jax.tree_util.tree_leaves(
                        getattr(value, "data", value)
                    ) if hasattr(x, "block_until_ready")]
                )
            except Exception:
                pass

        def timed():
            # Force AND drain deps BEFORE the clock starts: an upstream
            # fused program's in-flight device compute would otherwise
            # block inside this node's timed region and be double-counted
            # against it.
            for d in dep_exprs:
                drain(d.get())
            t0 = time.perf_counter()
            value = orig()
            # Drain the node's own dispatch INSIDE the timed region (the
            # same guard the sampled profiler applies): a jitted program
            # returns un-materialized arrays, and without the sync its
            # compute would be mis-attributed to whichever downstream
            # stage first blocks.
            drain(value)
            ns = (time.perf_counter() - t0) * 1e9
            try:
                autocache.record_observed_profile(
                    key, ns, autocache._estimate_bytes(value)
                )
            except Exception:
                pass
            return value

        expression._thunk = timed
