"""Memoized pull-based graph execution (reference: workflow/GraphExecutor.scala:14-81).

On first demand the executor (optionally) runs the global whole-pipeline
optimizer, then recursively evaluates the requested id's dependency chain,
memoizing each node's Expression and publishing results for nodes whose prefix
was marked by the optimizer into the global PipelineEnv state table.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

from . import analysis
from .env import PipelineEnv, Prefix
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .operators import Expression


class GraphExecutor:
    """Executes parts of a graph, memoizing results. Not thread-safe."""

    def __init__(
        self,
        graph: Graph,
        optimize: bool = True,
        prefixes: Optional[Mapping[NodeId, Prefix]] = None,
    ):
        self.graph = graph
        self.optimize = optimize
        self._optimized_graph: Optional[Graph] = graph if not optimize else None
        self._prefixes: Optional[Mapping[NodeId, Prefix]] = prefixes
        self._execution_state: Dict[GraphId, Expression] = {}

    def _ensure_optimized(self) -> Graph:
        if self._optimized_graph is None:
            if self.optimize:
                graph, prefixes = PipelineEnv.get_or_create().optimizer.execute(self.graph, {})
            else:
                graph, prefixes = self.graph, self._prefixes or {}
            self._optimized_graph = graph
            self._prefixes = prefixes
        return self._optimized_graph

    @property
    def optimized_graph(self) -> Graph:
        return self._ensure_optimized()

    def _source_dependants(self, graph: Graph) -> set:
        out = set()
        for source in graph.sources:
            out |= analysis.get_descendants(graph, source)
            out.add(source)
        return out

    def execute(self, graph_id: GraphId) -> Expression:
        graph = self._ensure_optimized()
        if graph_id in self._source_dependants(graph):
            raise ValueError("May not execute GraphIds that depend on unconnected sources.")
        return self._execute(graph, graph_id)

    def _execute(self, graph: Graph, graph_id: GraphId) -> Expression:
        if graph_id in self._execution_state:
            return self._execution_state[graph_id]

        if isinstance(graph_id, SourceId):
            raise ValueError("SourceIds may not be executed.")
        if isinstance(graph_id, SinkId):
            expression = self._execute(graph, graph.get_sink_dependency(graph_id))
        else:
            dep_exprs = [self._execute(graph, dep) for dep in graph.get_dependencies(graph_id)]
            operator = graph.get_operator(graph_id)
            expression = operator.execute(dep_exprs)
            # Publish results the optimizer marked for prefix-state reuse.
            if self._prefixes and graph_id in self._prefixes:
                PipelineEnv.get_or_create().state[self._prefixes[graph_id]] = expression

        self._execution_state[graph_id] = expression
        return expression
