"""Cache-placement optimization (reference: workflow/AutoCacheRule.scala:18-664).

The reference decides which RDDs to ``.cache()`` by profiling sampled
sub-pipelines (wall time + storage size) and greedily minimizing estimated
total runtime under a memory budget. The TPU analog of "caching" is keeping a
computed Dataset resident in device HBM (and publishing it into the prefix
state table) versus recomputing it on each downstream pass.

Two strategies, as in the reference:
  - AggressiveCache: cache every node whose weighted direct successor count
    exceeds 1 (AutoCacheRule.scala:503-518).
  - GreedyCache(max_mem_bytes, partition_scales, num_trials): profile
    sampled execution at MULTIPLE sample scales, fit linear time/mem models
    vs data scale (``generalizeProfiles``, AutoCacheRule.scala:104-135),
    extrapolate to the full data size, then greedily add the cache that
    most reduces estimated runtime while the cached set fits the memory
    budget (AutoCacheRule.scala:559-602).

Node weights come from the ``weight`` attribute of operators (the
WeightedOperator contract, reference: workflow/WeightedOperator.scala): the
number of passes the operator makes over its inputs.

POST-FUSION WORLD MODEL (round 6). The reference profiles the plan it will
actually run; our port used to profile the PRE-fusion execution model, so
whole-chain fusion made recompute nearly free while inserted ``Cacher``
nodes broke the fused program (round 5's autocache_on_chip row: greedy
LOST to no-cache). The rule is therefore fusion-aware on two axes:

  1. In :class:`~.optimizer.AutoCachingOptimizer` it runs AFTER the fusion
     batches, so profiles are taken per POST-fusion node: a stage absorbed
     into a fused program no longer exists as a candidate (its marginal
     recompute cost is ~0 by construction), and ``estimate_cached_runtime``
     on the fused graph prices a candidate by the delta between the fused
     plan with and without the cut.
  2. Whatever the phase order, selection excludes nodes where a spliced
     Cacher would sever an edge the fusion rules would otherwise compile
     into one program (:func:`~.fusion.cache_would_split_fusion`), so
     insertion only ever lands on fused-stage boundaries: host loaders /
     decodes, multi-consumer intermediates, gather points, and inputs of
     non-traceable fits.

Profiles come from real executions when available: the executor records
each node's first-force wall time and bytes into the observed-profile
table (:func:`record_observed_profile`), keyed by logical Prefix like the
sampling memo, and greedy consults it before paying sampled profiling
passes — the cross-fit "re-profile on the fused plan" hook.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from . import analysis
from .env import Prefix
from .graph import Graph, NodeId, SinkId
from .operators import (
    DatasetExpression,
    DatasetOperator,
    DatumExpression,
    DatumOperator,
    EstimatorOperator,
    Expression,
    ExpressionOperator,
    TransformerExpression,
    TransformerOperator,
)
from .optimizer import Plan, Rule


def node_weight(op) -> int:
    """Number of passes an operator makes over its input (default 1)."""
    return int(getattr(op, "weight", 1))


@dataclass
class Profile:
    """Measured cost of computing one node (AutoCacheRule.scala:12-16)."""

    ns: float = 0.0
    mem_bytes: int = 0

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(self.ns + other.ns, self.mem_bytes + other.mem_bytes)


@dataclass
class SampleProfile:
    """One measurement at one sample scale (AutoCacheRule.scala:16)."""

    scale: int
    profile: Profile


def generalize_profiles(
    new_scale: int, sample_profiles: Sequence[SampleProfile]
) -> Profile:
    """Fit linear models time/mem vs sample scale and evaluate at the full
    data scale (``generalizeProfiles``, AutoCacheRule.scala:104-135: solve
    ``[scale, 1] \\ y`` with coefficients clipped at zero)."""
    X = np.array(
        [[float(sp.scale), 1.0] for sp in sample_profiles], dtype=np.float64
    )

    def model(ys: List[float]) -> float:
        y = np.asarray(ys, dtype=np.float64)
        coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        coef = np.maximum(coef, 0.0)  # max(X \ y, 0.0)
        return float(coef[0] * new_scale + coef[1])

    return Profile(
        ns=model([sp.profile.ns for sp in sample_profiles]),
        mem_bytes=int(model([sp.profile.mem_bytes for sp in sample_profiles])),
    )


@dataclass(frozen=True)
class AggressiveCache:
    pass


@dataclass(frozen=True)
class GreedyCache:
    max_mem_bytes: Optional[int] = None  # default: 75% of device memory
    # Sample scales (items per shard), profiled smallest-to-largest
    # (reference default partitionScales = Seq(2, 4)).
    partition_scales: Tuple[int, ...] = (2, 4)
    num_trials: int = 1


# ---------------------------------------------------------------------------
# Graph queries (ported from AutoCacheRule.scala:18-95)
# ---------------------------------------------------------------------------


def init_cache_set(graph: Graph) -> Set[NodeId]:
    """Nodes whose results are effectively cached before the rule runs
    (initCacheSet, AutoCacheRule.scala:80-95): datum constants, Cachers,
    estimator fits, and spliced expressions."""
    from keystone_tpu.ops.util import Cacher

    cached = set()
    for node, op in graph.operators.items():
        if isinstance(
            op, (DatumOperator, EstimatorOperator, ExpressionOperator, Cacher)
        ):
            cached.add(node)
    return cached


def descendants_of_sources(graph: Graph) -> Set[NodeId]:
    out: Set[NodeId] = set()
    for source in graph.sources:
        for gid in analysis.get_descendants(graph, source):
            if isinstance(gid, NodeId):
                out.add(gid)
    return out


def compute_runs(graph: Graph, cached: Set[NodeId]) -> Dict[NodeId, int]:
    """Times each node's result gets *computed*, given a cached set
    (getRuns, AutoCacheRule.scala:57-77).

    A node's result is accessed once per (child run × child weight); caching a
    node bounds its compute count at 1.
    """
    accesses: Dict[NodeId, int] = {}

    def runs(gid) -> int:
        """Times the node at `gid` executes."""
        if isinstance(gid, SinkId):
            return 1
        if gid in accesses:
            return accesses[gid]
        total = 0
        for child in analysis.get_children(graph, gid):
            if isinstance(child, SinkId):
                total += 1
            elif isinstance(child, NodeId):
                child_runs = 1 if child in cached else runs(child)
                total += child_runs * node_weight(graph.get_operator(child))
        result = max(total, 1)
        accesses[gid] = result
        return result

    out: Dict[NodeId, int] = {}
    for node in graph.nodes:
        out[node] = 1 if node in cached else runs(node)
    return out


# ---------------------------------------------------------------------------
# Greedy selection (ported from AutoCacheRule.scala:460-602)
# ---------------------------------------------------------------------------


def estimate_cached_runtime(
    graph: Graph, cached: Set[NodeId], profiles: Dict[NodeId, Profile]
) -> float:
    """Total estimated runtime given a cached set (estimateCachedRunTime,
    AutoCacheRule.scala:468-487): Σ executions × profiled ns over all nodes
    (unprofiled nodes contribute 0)."""
    runs = compute_runs(graph, cached)
    return sum(
        runs[n] * profiles.get(n, Profile()).ns for n in graph.nodes
    )


def cached_mem(cached: Set[NodeId], profiles: Dict[NodeId, Profile]) -> int:
    return sum(profiles.get(n, Profile()).mem_bytes for n in cached)


def _still_room(
    excluded: Set[NodeId],
    runs: Dict[NodeId, int],
    profiles: Dict[NodeId, Profile],
    space_left: int,
) -> bool:
    """True iff an eligible node used >1 time would fit if cached
    (stillRoom, AutoCacheRule.scala:529-541)."""
    return any(
        runs[n] > 1
        and n not in excluded
        and profiles.get(n, Profile()).mem_bytes < space_left
        for n in runs
    )


def _select_next(
    graph: Graph,
    profiles: Dict[NodeId, Profile],
    cached: Set[NodeId],
    excluded: Set[NodeId],
    runs: Dict[NodeId, int],
    space_left: int,
) -> NodeId:
    """The fitting eligible node that minimizes estimated runtime when
    cached (selectNext, AutoCacheRule.scala:543-557). ``excluded`` bars
    nodes from being picked; the runtime estimate itself uses only the
    truly ``cached`` set. Ties break on NodeId order for determinism."""
    eligible = [
        n
        for n in sorted(graph.nodes, key=lambda n: n.id)
        if n not in excluded
        and profiles.get(n, Profile()).mem_bytes < space_left
        and runs[n] > 1
    ]
    return min(
        eligible,
        key=lambda n: estimate_cached_runtime(graph, cached | {n}, profiles),
    )


def greedy_cache_set(
    graph: Graph,
    profiles: Dict[NodeId, Profile],
    max_mem: int,
    excluded: Optional[Set[NodeId]] = None,
) -> Set[NodeId]:
    """The greedy selection loop (greedyCache, AutoCacheRule.scala:559-602).

    ``excluded`` bars extra nodes from selection (AutoCacheRule passes the
    fusion-splitting set so a Cacher never lands inside a fusable region).

    Divergence from the reference: source descendants are excluded from
    *selection*, not just subtracted from the result afterwards. The
    reference lets an unprofiled (mem-0) source descendant win selectNext
    when caching it would absorb its profiled ancestors' recompute savings,
    then strips it at the end — leaving the expensive ancestors uncached
    (a latent mis-selection its own suite never hits, since there the
    profiled candidates always dominate strictly).
    """
    cached = init_cache_set(graph)
    barred = descendants_of_sources(graph) | (excluded or set())
    runs = compute_runs(graph, cached)
    to_cache: Set[NodeId] = set()
    used = cached_mem(cached, profiles)
    while used < max_mem and _still_room(
        cached | to_cache | barred, runs, profiles, max_mem - used
    ):
        to_cache.add(
            _select_next(
                graph,
                profiles,
                cached | to_cache,
                cached | to_cache | barred,
                runs,
                max_mem - used,
            )
        )
        runs = compute_runs(graph, cached | to_cache)
        used = cached_mem(cached | to_cache, profiles)
    return to_cache


def _insert_cachers(plan: Graph, nodes: Set[NodeId]) -> Graph:
    """Splice a Cacher node after each selected node (AutoCacheRule.scala:492-501)."""
    from keystone_tpu.ops.util import Cacher

    graph = plan
    for node in sorted(nodes, key=lambda n: n.id):
        op = graph.get_operator(node)
        if isinstance(op, Cacher):
            continue
        graph, cacher_id = graph.add_node(Cacher(), [node])
        # Point all other dependents of `node` at the cacher.
        for child in list(analysis.get_children(graph, node)):
            if child == cacher_id:
                continue
            if isinstance(child, NodeId):
                deps = [cacher_id if d == node else d for d in graph.get_dependencies(child)]
                graph = graph.set_dependencies(child, deps)
            elif isinstance(child, SinkId):
                graph = graph.set_sink_dependency(child, cacher_id)
    return graph


# ---------------------------------------------------------------------------
# Multi-scale profiling (ported from profileNodes + generalizeProfiles)
# ---------------------------------------------------------------------------


def _sample_once(
    graph: Graph, nodes: Set[NodeId], sample_size: int
) -> Tuple[Dict[NodeId, Profile], Dict[NodeId, int], Dict[NodeId, int]]:
    """Execute the ancestor closure of ``nodes`` on inputs subsampled to
    ``sample_size`` items, timing each profiled node. Returns
    (raw profiles at this scale, per-node sampled item counts, per-node
    full data sizes)."""
    from keystone_tpu.data import Dataset

    memo: Dict[NodeId, object] = {}
    profiles: Dict[NodeId, Profile] = {}
    full_counts: Dict[NodeId, int] = {}
    actual: Dict[NodeId, int] = {}

    def sample_dataset(ds: Dataset) -> Dataset:
        k = min(ds.n, max(sample_size, 1))
        if ds.is_host:
            return Dataset.of(ds.to_list()[:k])
        data = jax.tree_util.tree_map(lambda x: x[:k], ds.data)
        return Dataset(data, n=k)

    def evaluate(gid):
        if gid in memo:
            return memo[gid]
        op = graph.get_operator(gid)
        dep_values = [evaluate(d) for d in graph.get_dependencies(gid)]
        t0 = time.perf_counter()
        if isinstance(op, DatasetOperator):
            full = Dataset.of(op.dataset)
            full_counts[gid] = full.n
            value = sample_dataset(full)
            actual[gid] = value.n
        else:
            exprs = [_wrap(v) for v in dep_values]
            value = op.execute(exprs).get()
            if isinstance(value, Dataset):
                value.cache()
            deps = graph.get_dependencies(gid)
            full_counts[gid] = max(
                (full_counts.get(d, 1) for d in deps), default=1
            )
            actual[gid] = max((actual.get(d, 1) for d in deps), default=1)
        elapsed_ns = (time.perf_counter() - t0) * 1e9
        profiles[gid] = Profile(ns=elapsed_ns, mem_bytes=_estimate_bytes(value))
        memo[gid] = value
        return value

    def _wrap(value) -> Expression:
        if isinstance(value, Dataset):
            return DatasetExpression(lambda v=value: v)
        if isinstance(value, TransformerOperator):
            return TransformerExpression(lambda v=value: v)
        return DatumExpression(lambda v=value: v)

    for node in nodes:
        try:
            evaluate(node)
        except Exception:
            profiles.setdefault(node, Profile())
            full_counts.setdefault(node, 1)
            actual.setdefault(node, 1)
    return profiles, actual, full_counts


def profile_nodes(
    graph: Graph,
    nodes: Set[NodeId],
    partition_scales: Sequence[int] = (2, 4),
    num_trials: int = 1,
) -> Dict[NodeId, Profile]:
    """Profile nodes at multiple sample scales and generalize to the full
    data size with the fitted linear models (profileNodes +
    generalizeProfiles, AutoCacheRule.scala:104-135, 153-465)."""
    samples: Dict[NodeId, List[SampleProfile]] = {n: [] for n in nodes}
    full: Dict[NodeId, int] = {}
    for scale in sorted(partition_scales):
        for _ in range(max(int(num_trials), 1)):
            profiles, actual, full_counts = _sample_once(graph, nodes, scale)
            for n in nodes:
                samples[n].append(
                    SampleProfile(actual.get(n, 1), profiles.get(n, Profile()))
                )
                full[n] = max(full.get(n, 1), full_counts.get(n, 1))
    out = {}
    for n in nodes:
        if len({sp.scale for sp in samples[n]}) >= 2:
            out[n] = generalize_profiles(full[n], samples[n])
        elif samples[n]:
            # Single usable scale: fall back to proportional extrapolation.
            sp = samples[n][-1]
            factor = full[n] / max(sp.scale, 1)
            out[n] = Profile(
                ns=sp.profile.ns * factor,
                mem_bytes=int(sp.profile.mem_bytes * factor),
            )
        else:
            out[n] = Profile()
    return out


def _estimate_bytes(value) -> int:
    from keystone_tpu.data import Dataset

    if isinstance(value, Dataset):
        if value.is_host:
            return sum(getattr(np.asarray(x), "nbytes", 64) for x in value.data[:16]) * max(
                len(value.data) // 16, 1
            )
        return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree_util.tree_leaves(value.data))
    return 64


# ---------------------------------------------------------------------------
# Observed profiles: real full-scale measurements collected by the executor
# ---------------------------------------------------------------------------

# Keyed like the sampling memo — (hash(Prefix), structural fingerprint) —
# holding only floats, never operators or arrays. The executor records each
# source-free node's first-force wall time + result bytes here as pipelines
# actually run; AutoCacheRule consults it before paying sampled profiling
# passes, so cache placement prices POST-FUSION nodes by what the fused
# program measurably cost, not by a toy-scale extrapolation.
_OBSERVED_PROFILES: Dict[Tuple, Profile] = {}
_OBSERVED_MAX = 512


def observed_profile_key(
    graph: Graph, node: NodeId, _memo: Optional[dict] = None
) -> Optional[Tuple]:
    """Stable cross-graph identity of a node's computation, or None for
    source-dependent nodes (whose Prefix is undefined)."""
    try:
        p = Prefix.find(graph, node, _memo)
    except (ValueError, TypeError):
        return None
    return (hash(p), _prefix_fingerprint(p))


def record_observed_profile(key: Tuple, ns: float, mem_bytes: int) -> None:
    """Record a real execution of the node behind ``key``. Keeps the MIN
    observed time (the warm recompute cost — first runs carry compiles)
    and the latest size."""
    if ns <= 0:
        return
    prev = _OBSERVED_PROFILES.pop(key, None)
    if prev is not None:
        ns = min(ns, prev.ns)
    elif len(_OBSERVED_PROFILES) >= _OBSERVED_MAX:
        _OBSERVED_PROFILES.pop(next(iter(_OBSERVED_PROFILES)))
    _OBSERVED_PROFILES[key] = Profile(ns=ns, mem_bytes=int(mem_bytes))


def get_observed_profile(key: Optional[Tuple]) -> Optional[Profile]:
    return _OBSERVED_PROFILES.get(key) if key is not None else None


def clear_observed_profiles() -> None:
    """Reset hook — called by PipelineEnv.reset(): keys hash
    DatasetOperators by dataset id(), so entries must not outlive the env
    generation (a recycled id could alias a stale profile onto different
    data)."""
    _OBSERVED_PROFILES.clear()


class AutoCacheRule(Rule):
    """Insert Cacher nodes per the configured strategy.

    Fusion-preserving placement: candidates where a spliced Cacher would
    sever an edge the fusion rules would otherwise compile into one
    program (:func:`~.fusion.cache_would_split_fusion`) are excluded from
    BOTH strategies, so a cache only ever lands on a fused-stage boundary.
    Run after the fusion batches (AutoCachingOptimizer's order), the
    surviving candidates are whole post-fusion programs and
    ``estimate_cached_runtime`` prices each cut against the plan that will
    actually execute.

    GreedyCache profiling is memoized across optimizer invocations by
    logical :class:`Prefix`: a λ-sweep refitting the same featurize chain
    pays the on-chip sampled-profiling passes ONCE, not once per fit. (The
    reference re-profiled per pipeline application; on TPU each profiling
    pass costs real compiles of the sampled shapes, so the memo is the
    difference between greedy's steady-state fits matching aggressive's
    and trailing them by a full profiling pass — measured on the
    autocache bench row.) Real executions observed by the executor
    (:func:`record_observed_profile`) take precedence over both: they are
    full-scale measurements of the fused programs themselves.
    """

    _PROFILE_MEMO_MAX = 512

    def __init__(self, strategy=None):
        self.strategy = strategy or GreedyCache()
        self._profile_memo: Dict[Tuple, Profile] = {}
        # The most recent apply()'s selected nodes — observable by benches
        # and tests even after SavedStateLoadRule replaces the inserted
        # Cachers with state splices.
        self.last_selection: Set[NodeId] = set()

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        from .fusion import fusion_splitting_nodes

        splitting = fusion_splitting_nodes(plan, prefixes)
        if isinstance(self.strategy, AggressiveCache):
            to_cache = self._aggressive(plan, splitting)
        else:
            to_cache = self._greedy(plan, self.strategy, splitting)
        self.last_selection = set(to_cache)
        return _insert_cachers(plan, to_cache), prefixes

    def _aggressive(
        self, plan: Graph, splitting: Optional[Set[NodeId]] = None
    ) -> Set[NodeId]:
        """Cache every node with >1 weighted direct successor access that is
        not already cached, not source-dependent, and not inside a fusable
        region (aggressiveCache, AutoCacheRule.scala:503-518)."""
        cached = init_cache_set(plan)
        excluded = descendants_of_sources(plan) | (splitting or set())
        out = set()
        for node in plan.nodes:
            if node in cached or node in excluded:
                continue
            accesses = 0
            for child in analysis.get_children(plan, node):
                if isinstance(child, NodeId):
                    accesses += node_weight(plan.get_operator(child))
                else:
                    accesses += 1
            if accesses > 1:
                out.add(node)
        return out

    def _greedy(
        self,
        plan: Graph,
        strategy: GreedyCache,
        splitting: Optional[Set[NodeId]] = None,
    ) -> Set[NodeId]:
        cached = init_cache_set(plan)
        runs = compute_runs(plan, cached)
        splitting = splitting or set()
        excluded = descendants_of_sources(plan) | splitting
        # Profile every uncached node accessed more than once that doesn't
        # depend on the sources (AutoCacheRule.scala:612-618) and whose
        # caching wouldn't split a fusable region (those nodes' marginal
        # recompute cost is absorbed by the fused program — profiling them
        # would price the cut against a plan that never runs).
        to_profile = {
            n
            for n in plan.nodes
            if n not in cached and runs[n] > 1 and n not in excluded
        }
        if not to_profile:
            return set()

        # Profile-memo lookup by the HASH of the logical prefix plus a
        # structural label fingerprint (all profiled nodes are source-free,
        # so Prefix.find is defined for them). The hash, not the Prefix
        # itself: a Prefix chain ends in DatasetOperator leaves that hold
        # the full training arrays, and keeping those alive for up to
        # _PROFILE_MEMO_MAX entries would be a multi-GB retention leak for
        # a cache of two floats. The fingerprint (a label string — no
        # array retention) guards the hash: a collision between chains
        # with different structure misses instead of silently reusing
        # another chain's timing profile for the optimizer's lifetime.
        scales_key = (tuple(strategy.partition_scales), strategy.num_trials)
        find_memo: Dict[NodeId, Prefix] = {}
        node_keys: Dict[NodeId, Tuple] = {}
        profiles: Dict[NodeId, Profile] = {}
        for n in to_profile:
            p = Prefix.find(plan, n, find_memo)
            base = (hash(p), _prefix_fingerprint(p))
            node_keys[n] = base + (scales_key,)
            # Full-scale measurement from a real prior execution of this
            # computation (post-fusion, warm) beats any sampled model.
            observed = get_observed_profile(base)
            if observed is not None:
                profiles[n] = observed
        for n, k in node_keys.items():
            if n not in profiles and k in self._profile_memo:
                profiles[n] = self._profile_memo[k]
        misses = to_profile - set(profiles)
        if misses:
            fresh = profile_nodes(
                plan, misses, strategy.partition_scales, strategy.num_trials
            )
            profiles.update(fresh)
            for n in misses:
                prof = fresh.get(n)
                if prof is None or prof.ns <= 0:
                    # ns == 0 is _sample_once's failure sentinel (transient
                    # OOM / compile flake): memoizing it would make the
                    # node look cost-free for the optimizer's lifetime —
                    # leave it out so the next fit re-profiles.
                    continue
                if len(self._profile_memo) >= self._PROFILE_MEMO_MAX:
                    self._profile_memo.pop(next(iter(self._profile_memo)))
                self._profile_memo[node_keys[n]] = prof

        max_mem = strategy.max_mem_bytes
        if max_mem is None:
            max_mem = _default_mem_budget()
        return greedy_cache_set(plan, profiles, max_mem, excluded=splitting)


def _prefix_fingerprint(prefix: Prefix) -> str:
    """Structural label string of a Prefix chain — cheap to build, retains
    no operators/arrays, and distinguishes chains whose hashes collide."""
    memo: Dict[int, str] = {}

    def fp(p: Prefix) -> str:
        got = memo.get(id(p))
        if got is None:
            label = getattr(p.operator, "label", type(p.operator).__name__)
            got = f"{label}({','.join(fp(d) for d in p.deps)})"
            memo[id(p)] = got
        return got

    return fp(prefix)


def _default_mem_budget() -> int:
    """75% of per-device memory (AutoCacheRule's default of 75% of free cluster mem)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return int(limit * 0.75)
    except Exception:
        pass
    return 8 << 30
