"""Cache-placement optimization (reference: workflow/AutoCacheRule.scala:18-664).

The reference decides which RDDs to ``.cache()`` by profiling sampled
sub-pipelines (wall time + storage size) and greedily minimizing estimated
total runtime under a memory budget. The TPU analog of "caching" is keeping a
computed Dataset resident in device HBM (and publishing it into the prefix
state table) versus recomputing it on each downstream pass.

Two strategies, as in the reference:
  - AggressiveCache: cache every dataset-producing node whose weighted direct
    successor count exceeds 1 (AutoCacheRule.scala:503-518).
  - GreedyCache(max_mem_bytes, scales, trials): profile sampled execution and
    greedily add the cache that most reduces estimated runtime while the
    cached set fits the memory budget (AutoCacheRule.scala:559-602).

Node weights come from the ``weight`` attribute of operators (the
WeightedOperator contract, reference: workflow/WeightedOperator.scala): the
number of passes the operator makes over its inputs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from . import analysis
from .env import Prefix
from .graph import Graph, NodeId, SinkId, SourceId
from .operators import (
    DatasetExpression,
    DatasetOperator,
    DatumExpression,
    EstimatorOperator,
    Expression,
    TransformerExpression,
    TransformerOperator,
)
from .optimizer import Plan, Rule


def node_weight(op) -> int:
    """Number of passes an operator makes over its input (default 1)."""
    return int(getattr(op, "weight", 1))


@dataclass
class Profile:
    """Measured cost of computing one node (AutoCacheRule.scala:12-16)."""

    ns: float = 0.0
    mem_bytes: int = 0

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(self.ns + other.ns, self.mem_bytes + other.mem_bytes)


@dataclass(frozen=True)
class AggressiveCache:
    pass


@dataclass(frozen=True)
class GreedyCache:
    max_mem_bytes: Optional[int] = None  # default: 75% of device memory
    samples_per_shard: int = 3


def _dataset_nodes(graph: Graph) -> Set[NodeId]:
    """Nodes that produce datasets: transformer-ish nodes not downstream of sources."""
    out = set()
    for node, op in graph.operators.items():
        if isinstance(op, EstimatorOperator):
            continue
        ancestors = analysis.get_ancestors(graph, node)
        if any(isinstance(a, SourceId) for a in ancestors):
            continue
        out.add(node)
    return out


def compute_runs(graph: Graph, cached: Set[NodeId]) -> Dict[NodeId, int]:
    """Times each node's result gets *computed*, given a cached set
    (the analog of AutoCacheRule.getRuns, AutoCacheRule.scala:57-81).

    A node's result is accessed once per (child run × child weight); caching a
    node bounds its compute count at 1.
    """
    accesses: Dict[NodeId, int] = {}

    def runs(gid) -> int:
        """Times the node at `gid` executes."""
        if isinstance(gid, SinkId):
            return 1
        if gid in accesses:
            return accesses[gid]
        total = 0
        for child in analysis.get_children(graph, gid):
            if isinstance(child, SinkId):
                total += 1
            elif isinstance(child, NodeId):
                child_runs = 1 if child in cached else runs(child)
                total += child_runs * node_weight(graph.get_operator(child))
        result = max(total, 1)
        accesses[gid] = result
        return result

    out: Dict[NodeId, int] = {}
    for node in graph.nodes:
        out[node] = 1 if node in cached else runs(node)
    return out


def _insert_cachers(plan: Graph, nodes: Set[NodeId]) -> Graph:
    """Splice a Cacher node after each selected node (AutoCacheRule.scala:492-501)."""
    from keystone_tpu.ops.util import Cacher

    graph = plan
    for node in nodes:
        op = graph.get_operator(node)
        if isinstance(op, Cacher):
            continue
        graph, cacher_id = graph.add_node(Cacher(), [node])
        # Point all other dependents of `node` at the cacher.
        for child in list(analysis.get_children(graph, node)):
            if child == cacher_id:
                continue
            if isinstance(child, NodeId):
                deps = [cacher_id if d == node else d for d in graph.get_dependencies(child)]
                graph = graph.set_dependencies(child, deps)
            elif isinstance(child, SinkId):
                graph = graph.set_sink_dependency(child, cacher_id)
    return graph


def profile_nodes(
    graph: Graph, nodes: Set[NodeId], samples_per_shard: int = 3
) -> Dict[NodeId, Profile]:
    """Execute sampled ancestor chains, measuring per-node wall time and output size
    (the analog of AutoCacheRule.profileNodes, AutoCacheRule.scala:153-465)."""
    from keystone_tpu.data import Dataset

    memo: Dict[NodeId, object] = {}
    profiles: Dict[NodeId, Profile] = {}

    def sample_dataset(ds: Dataset) -> Tuple[Dataset, float]:
        k = min(ds.n, max(samples_per_shard, 1))
        scale = ds.n / max(k, 1)
        if ds.is_host:
            return Dataset.of(ds.to_list()[:k]), scale
        data = jax.tree_util.tree_map(lambda x: x[:k], ds.data)
        return Dataset(data, n=k), scale

    scales: Dict[NodeId, float] = {}

    def evaluate(gid):
        if gid in memo:
            return memo[gid]
        op = graph.get_operator(gid)
        dep_values = [evaluate(d) for d in graph.get_dependencies(gid)]
        t0 = time.perf_counter()
        if isinstance(op, DatasetOperator):
            value, scale = sample_dataset(Dataset.of(op.dataset))
            scales[gid] = scale
        else:
            exprs = [_wrap(v) for v in dep_values]
            value = op.execute(exprs).get()
            if isinstance(value, Dataset):
                value.cache()
            dep_scales = [
                scales.get(d, 1.0) for d in graph.get_dependencies(gid)
            ]
            scales[gid] = max(dep_scales, default=1.0)
        elapsed_ns = (time.perf_counter() - t0) * 1e9
        mem = _estimate_bytes(value)
        scale = scales.get(gid, 1.0)
        profiles[gid] = Profile(ns=elapsed_ns * scale, mem_bytes=int(mem * scale))
        memo[gid] = value
        return value

    def _wrap(value) -> Expression:
        if isinstance(value, Dataset):
            return DatasetExpression(lambda v=value: v)
        if isinstance(value, TransformerOperator):
            return TransformerExpression(lambda v=value: v)
        return DatumExpression(lambda v=value: v)

    for node in nodes:
        try:
            evaluate(node)
        except Exception:
            profiles.setdefault(node, Profile())
    return {n: profiles.get(n, Profile()) for n in nodes}


def _estimate_bytes(value) -> int:
    from keystone_tpu.data import Dataset

    if isinstance(value, Dataset):
        if value.is_host:
            return sum(getattr(np.asarray(x), "nbytes", 64) for x in value.data[:16]) * max(
                len(value.data) // 16, 1
            )
        return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree_util.tree_leaves(value.data))
    return 64


class AutoCacheRule(Rule):
    """Insert Cacher nodes per the configured strategy."""

    def __init__(self, strategy=None):
        self.strategy = strategy or GreedyCache()

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        candidates = _dataset_nodes(plan)
        if not candidates:
            return plan, prefixes

        if isinstance(self.strategy, AggressiveCache):
            to_cache = self._aggressive(plan, candidates)
        else:
            to_cache = self._greedy(plan, candidates, self.strategy)

        return _insert_cachers(plan, to_cache), prefixes

    def _aggressive(self, plan: Graph, candidates: Set[NodeId]) -> Set[NodeId]:
        """Cache every dataset node with >1 weighted direct successor access."""
        out = set()
        for node in candidates:
            accesses = 0
            for child in analysis.get_children(plan, node):
                if isinstance(child, NodeId):
                    accesses += node_weight(plan.get_operator(child))
                else:
                    accesses += 1
            if accesses > 1:
                out.add(node)
        return out

    def _greedy(
        self, plan: Graph, candidates: Set[NodeId], strategy: GreedyCache
    ) -> Set[NodeId]:
        profiles = profile_nodes(plan, candidates, strategy.samples_per_shard)
        max_mem = strategy.max_mem_bytes
        if max_mem is None:
            max_mem = _default_mem_budget()

        def total_cost(cached: Set[NodeId]) -> float:
            runs = compute_runs(plan, cached)
            return sum(runs[n] * profiles[n].ns for n in candidates)

        def mem_used(cached: Set[NodeId]) -> int:
            return sum(profiles[n].mem_bytes for n in cached)

        cached: Set[NodeId] = set()
        cur_cost = total_cost(cached)
        improved = True
        while improved:
            improved = False
            best_node, best_cost = None, cur_cost
            for node in candidates - cached:
                if mem_used(cached | {node}) > max_mem:
                    continue
                cost = total_cost(cached | {node})
                if cost < best_cost:
                    best_cost, best_node = cost, node
            if best_node is not None:
                cached.add(best_node)
                cur_cost = best_cost
                improved = True
        return cached


def _default_mem_budget() -> int:
    """75% of per-device memory (AutoCacheRule's default of 75% of free cluster mem)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return int(limit * 0.75)
    except Exception:
        pass
    return 8 << 30
