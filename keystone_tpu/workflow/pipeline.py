"""Typed ML API: Transformer / Estimator / LabelEstimator / Pipeline / gather.

Behavioral contract from the reference's typed layer (reference:
workflow/Transformer.scala:18-70, Estimator.scala:10-62,
LabelEstimator.scala:13-100, Chainable.scala:13-126, Pipeline.scala:22-155,
FittedPipeline.scala:18-48, PipelineResult.scala:14-21): composition is pure
graph surgery; applying a pipeline returns lazy handles; estimator insertion
adds the estimator node plus a delegating node that applies the *fitted*
transformer to the pipeline's source; ``fit()`` executes all estimators and
yields a serializable transformer-only pipeline.
"""

from __future__ import annotations

import cloudpickle as pickle
from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, TypeVar, Union

import jax
import jax.numpy as jnp

from keystone_tpu.data import Dataset

from .executor import GraphExecutor
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    GatherTransformerOperator,
    TransformerOperator,
)

A = TypeVar("A")
B = TypeVar("B")
C = TypeVar("C")
L = TypeVar("L")


# ---------------------------------------------------------------------------
# Lazy result handles
# ---------------------------------------------------------------------------


class PipelineResult(Generic[B]):
    """Lazy wrapper around a scheduled execution; ``.get()`` memoizes."""

    def __init__(self, executor: GraphExecutor, sink: SinkId):
        self.executor = executor
        self.sink = sink
        self._result: Any = None
        self._computed = False

    def get(self) -> B:
        if not self._computed:
            self._result = self.executor.execute(self.sink).get()
            self._computed = True
        return self._result


class PipelineDataset(PipelineResult[B]):
    """Lazy handle on a dataset flowing out of a pipeline."""

    @staticmethod
    def of(dataset: Dataset) -> "PipelineDataset":
        graph, node = Graph().add_node(DatasetOperator(dataset), [])
        graph, sink = graph.add_sink(node)
        return PipelineDataset(GraphExecutor(graph), sink)


class PipelineDatum(PipelineResult[B]):
    """Lazy handle on a single datum flowing out of a pipeline."""

    @staticmethod
    def of(datum: Any) -> "PipelineDatum":
        graph, node = Graph().add_node(DatumOperator(datum), [])
        graph, sink = graph.add_sink(node)
        return PipelineDatum(GraphExecutor(graph), sink)


def _as_pipeline_dataset(data: Any) -> "PipelineDataset":
    if isinstance(data, PipelineDataset):
        return data
    if not isinstance(data, Dataset):
        data = Dataset.of(data)
    return PipelineDataset.of(data)


# ---------------------------------------------------------------------------
# Chainable mixin
# ---------------------------------------------------------------------------


class Chainable(Generic[A, B]):
    """Provides ``and_then`` composition; implementors supply ``to_pipeline``."""

    def to_pipeline(self) -> "Pipeline[A, B]":
        raise NotImplementedError

    def and_then(
        self,
        nxt: Union["Chainable[B, C]", "Estimator", "LabelEstimator"],
        data: Any = None,
        labels: Any = None,
    ) -> "Pipeline[A, C]":
        """Chain a transformer/pipeline, or fit-and-chain an estimator.

        ``and_then(est, data)`` fits ``est`` on this pipeline applied to
        ``data``; ``and_then(label_est, data, labels)`` additionally passes
        labels (Chainable.scala:26-126).
        """
        if isinstance(nxt, LabelEstimator):
            if data is None or labels is None:
                raise ValueError("LabelEstimator chaining requires data and labels")
            me = self.to_pipeline()
            return me.and_then(nxt.with_data(me.apply(data), labels))
        if isinstance(nxt, Estimator):
            if data is None:
                raise ValueError("Estimator chaining requires data")
            me = self.to_pipeline()
            return me.and_then(nxt.with_data(me.apply(data)))
        if data is not None or labels is not None:
            raise ValueError("data/labels only apply when chaining estimators")

        me = self.to_pipeline()
        next_pipe = nxt.to_pipeline()
        new_graph, _, _, sink_mapping = me.executor.graph.connect_graph(
            next_pipe.executor.graph, {next_pipe.source: me.sink}
        )
        return Pipeline(GraphExecutor(new_graph), me.source, sink_mapping[next_pipe.sink])

    # `p | next` sugar for and_then
    def __or__(self, nxt: "Chainable[B, C]") -> "Pipeline[A, C]":
        return self.and_then(nxt)


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


class Pipeline(Chainable[A, B]):
    """Typed facade over (executor, source, sink). Not thread-safe."""

    def __init__(self, executor: GraphExecutor, source: SourceId, sink: SinkId):
        self.executor = executor
        self.source = source
        self.sink = sink

    def to_pipeline(self) -> "Pipeline[A, B]":
        return self

    def apply(self, data: Any) -> PipelineResult[B]:
        """Lazily apply this pipeline to a datum, Dataset, or lazy handle."""
        if isinstance(data, Dataset):
            return self.apply(PipelineDataset.of(data))
        if isinstance(data, PipelineDataset):
            new_graph, _, _, sink_mapping = data.executor.graph.connect_graph(
                self.executor.graph, {self.source: data.sink}
            )
            return PipelineDataset(
                GraphExecutor(new_graph, self.executor.optimize), sink_mapping[self.sink]
            )
        if isinstance(data, PipelineDatum):
            new_graph, _, _, sink_mapping = data.executor.graph.connect_graph(
                self.executor.graph, {self.source: data.sink}
            )
            return PipelineDatum(
                GraphExecutor(new_graph, self.executor.optimize), sink_mapping[self.sink]
            )
        return self.apply(PipelineDatum.of(data))

    __call__ = apply

    def fit(self) -> "FittedPipeline[A, B]":
        """Fit all estimators, returning a transformer-only serializable pipeline
        (Pipeline.scala:38-65).

        Runs the static plan verifier first (workflow/verify.py): a
        malformed plan — the compile-time error KeystoneML's typed Scala
        API would have raised — fails HERE with node-level coordinates,
        not deep inside an estimator fit. ``KEYSTONE_VERIFY=off``
        disables the pre-pass."""
        from keystone_tpu import obs

        from .env import PipelineEnv
        from .rules import UnusedBranchRemovalRule
        from .verify import verify_fit_graph

        with obs.span("pipeline.fit",
                      nodes=len(self.executor.graph.operators)):
            with obs.span("fit.verify"):
                verify_fit_graph(
                    self.executor.graph, context="Pipeline.fit plan"
                )
            with obs.span("fit.optimize"):
                optimized, prefixes = (
                    PipelineEnv.get_or_create().optimizer.execute(
                        self.executor.graph, {}
                    )
                )

            # Publish fitted state into the prefix table so later
            # pipelines reuse it.
            fitting_executor = GraphExecutor(
                optimized, optimize=False, prefixes=prefixes
            )
            delegating_nodes = [
                n for n, op in optimized.operators.items()
                if isinstance(op, DelegatingOperator)
            ]

            graph = optimized
            for node in delegating_nodes:
                deps = optimized.get_dependencies(node)
                estimator_dep = deps[0]
                est_op = optimized.get_operator(estimator_dep)
                with obs.span("fit.estimator", node=estimator_dep.id,
                              operator=type(est_op).__name__):
                    transformer = (
                        fitting_executor.execute(estimator_dep).get()
                    )
                if not isinstance(transformer, TransformerOperator):
                    raise TypeError(
                        "Estimator fit did not produce a TransformerOperator"
                    )
                graph = graph.set_operator(node, transformer) \
                    .set_dependencies(node, deps[1:])

            graph, _ = UnusedBranchRemovalRule().apply(graph, {})
            return FittedPipeline(
                TransformerGraph.from_graph(graph), self.source, self.sink
            )

    @staticmethod
    def gather(branches: Sequence["Pipeline[A, B]"]) -> "Pipeline[A, List[B]]":
        """Combine the outputs of branches applied to one input (Pipeline.scala:119-154)."""
        source = SourceId(0)
        graph = Graph(sources=frozenset({source}))

        branch_sinks: List[GraphId] = []
        for branch in branches:
            graph, source_mapping, _, sink_mapping = graph.add_graph(branch.executor.graph)
            branch_source = source_mapping[branch.source]
            branch_sink = sink_mapping[branch.sink]
            branch_sink_dep = graph.get_sink_dependency(branch_sink)
            graph = (
                graph.replace_dependency(branch_source, source)
                .remove_source(branch_source)
                .remove_sink(branch_sink)
            )
            branch_sinks.append(branch_sink_dep)

        graph, gather_node = graph.add_node(GatherTransformerOperator(), branch_sinks)
        graph, sink = graph.add_sink(gather_node)
        return Pipeline(GraphExecutor(graph), source, sink)


# ---------------------------------------------------------------------------
# TransformerGraph + FittedPipeline
# ---------------------------------------------------------------------------


def compose_apply_fn(
    graph: Graph, source: SourceId, sink: SinkId
) -> Optional[Callable]:
    """Compose a transformer graph into ONE pure batched array function
    ``X -> Y``, or None when the graph is not expressible as one.

    Requirements: every node on the sink's ancestry declares a
    ``device_fn`` and takes exactly one input, and ``source`` is the only
    unbound source. After the fusion rules have run, linear pipelines —
    including gather trees, which GatherFusionRule collapses to a single
    node — satisfy this; anything host-side or multi-input does not and
    the caller keeps the per-node execution path.

    Shared by the per-datum apply fast path (one compiled executable per
    input shape instead of an eager op-by-op walk) and by
    :mod:`keystone_tpu.serving.export`'s bucketed plan compiler.
    """
    from . import analysis

    steps = []
    for gid in analysis.linearize(graph, sink):
        if gid == source or isinstance(gid, SinkId):
            continue
        if isinstance(gid, SourceId):
            return None  # a second unbound source — not a pure X -> Y map
        op = graph.get_operator(gid)
        fn_getter = getattr(op, "device_fn", None)
        fn = fn_getter() if callable(fn_getter) else None
        deps = graph.get_dependencies(gid)
        if fn is None or len(deps) != 1:
            return None
        steps.append((gid, fn, deps[0]))
    final = graph.get_sink_dependency(sink)

    def composed(X):
        values = {source: X}
        for gid, fn, dep in steps:
            values[gid] = fn(values[dep])
        return values[final]

    return composed


class TransformerGraph(Graph):
    """A Graph whose every operator is a TransformerOperator — the
    serializable transformer-only restriction backing FittedPipeline
    (reference: TransformerGraph.scala:12-29)."""

    @staticmethod
    def from_graph(graph: Graph) -> "TransformerGraph":
        for _, op in graph.operators.items():
            if not isinstance(op, TransformerOperator):
                raise TypeError(
                    f"Non-transformer operator {op.label} in TransformerGraph"
                )
        return TransformerGraph(
            sources=graph.sources,
            operators=graph.operators,
            dependencies=graph.dependencies,
            sink_dependencies=graph.sink_dependencies,
        )


class FittedPipeline(Generic[A, B]):
    """Transformer-only pipeline: eager, no optimization or fitting on apply.

    Serializable via pickle (``save``/``load``), the analog of the reference's
    Java-serializable FittedPipeline (FittedPipeline.scala:12-48).
    """

    # Per-process cap on cached per-shape datum executables: a client
    # sweeping many input shapes must not retain one program per shape.
    _DATUM_PROGRAM_CACHE_MAX = 16

    def __init__(self, graph: Graph, source: SourceId, sink: SinkId):
        self.transformer_graph = graph
        self.source = source
        self.sink = sink
        self._init_datum_cache()

    def _init_datum_cache(self) -> None:
        # (shape, dtype) -> jitted single-datum program; _batched_fn is
        # the graph's composed batch function (False = "checked, not
        # composable" so the walk only ever happens once). The lock makes
        # concurrent apply(datum) callers safe: cache insertion/eviction
        # would otherwise race (dict pop during iteration) exactly in the
        # threaded-serving setting this PR exists for.
        import threading

        self._datum_programs: Dict[tuple, Any] = {}
        self._batched_fn: Any = None
        self._datum_lock = threading.Lock()

    # Jitted closures are not picklable; FittedPipeline.save() pickles the
    # whole object, so the compile caches rebuild lazily after load (same
    # contract as the fused transformers' __getstate__).
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_datum_programs", None)
        state.pop("_batched_fn", None)
        state.pop("_datum_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._init_datum_cache()

    def _datum_program(self, x) -> Optional[Callable]:
        """One compiled executable per input (shape, dtype) for the
        single-datum serve path.

        Repeated ``apply(datum)`` calls previously walked the graph
        op-by-op, dispatching each node's eager ops every call; now the
        first call with a given shape traces ONE program (the composed
        batched function at batch 1) and later calls reuse the compiled
        executable — no re-trace, no per-node dispatch waves. Returns
        None (caller keeps the per-node path) for pipelines that don't
        compose to a pure array function.
        """
        if not hasattr(x, "shape") or not hasattr(x, "dtype"):
            return None
        with self._datum_lock:
            if self._batched_fn is None:
                self._batched_fn = (
                    compose_apply_fn(
                        self.transformer_graph, self.source, self.sink
                    )
                    or False
                )
            if self._batched_fn is False:
                return None
            key = (tuple(x.shape), str(x.dtype))
            program = self._datum_programs.get(key)
            if program is None:
                batched = self._batched_fn
                program = jax.jit(lambda v: batched(v[None])[0])
                if len(self._datum_programs) >= self._DATUM_PROGRAM_CACHE_MAX:
                    self._datum_programs.pop(next(iter(self._datum_programs)))
                self._datum_programs[key] = program
            return program

    def apply(self, data: Any) -> Any:
        from . import analysis

        is_dataset = isinstance(data, (Dataset, PipelineDataset))
        if isinstance(data, (PipelineDataset, PipelineDatum)):
            data = data.get()

        if not is_dataset and not isinstance(data, Dataset):
            program = self._datum_program(data)
            if program is not None:
                return program(data)

        values: Dict[GraphId, Any] = {self.source: data}
        for gid in analysis.linearize(self.transformer_graph, self.sink):
            if gid in values:
                continue
            if isinstance(gid, SinkId):
                values[gid] = values[self.transformer_graph.get_sink_dependency(gid)]
            elif isinstance(gid, NodeId):
                op = self.transformer_graph.get_operator(gid)
                inputs = [values[d] for d in self.transformer_graph.get_dependencies(gid)]
                try:
                    if is_dataset:
                        values[gid] = op.batch_transform(inputs)
                    else:
                        values[gid] = op.single_transform(inputs)
                except Exception as e:
                    # Runtime failures cite the same coordinates as
                    # static-verifier reports (NodeId + operator +
                    # inferred input signatures), appended in place so
                    # the exception type survives.
                    from .verify import annotate_node_error

                    annotate_node_error(e, gid, op, inputs)
                    raise
            else:
                raise ValueError(f"Unbound source {gid} in FittedPipeline")
        return values[self.sink]

    __call__ = apply

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load(path: str) -> "FittedPipeline":
        with open(path, "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------------------
# Transformer
# ---------------------------------------------------------------------------


class Transformer(TransformerOperator, Chainable[A, B]):
    """A function on single items, batchable over datasets.

    Subclasses implement ``apply`` (single item). ``batch_apply`` defaults to
    the node's ``device_fn`` via ``map_batch`` when one is declared (so a
    device-pure node implements ONE batched function, not three methods kept
    in sync), else to mapping ``apply`` over the dataset (vmap for device
    arrays, Python map for host collections); override it only for batch
    semantics neither default expresses (Transformer.scala:18-70).
    """

    def apply(self, x: A) -> B:
        raise NotImplementedError

    def batch_apply(self, data: Dataset) -> Dataset:
        fn = self.device_fn()
        if fn is not None:
            if not data.is_host:
                return data.map_batch(fn)
            # Rectangular host collections stack to one array and take the
            # batched path too (one dispatch instead of one per item — the
            # SIFT→FV pipelines' post-encoding chains live here); ragged
            # items (variable image sizes) fall through to per-item apply,
            # mirroring Dataset.map's vmap-or-loop policy.
            try:
                batch = data.array
            except (ValueError, TypeError):
                # Ragged items cannot stack (the expected case). Any other
                # exception class is a genuine stacking bug and propagates —
                # swallowing it would silently degrade the pipeline to the
                # per-item path with no visible cause.
                return data.map(self.apply)
            try:
                out = fn(jnp.asarray(batch))
                # Sync inside the try: dispatch is async, so runtime
                # failures (batch too large for one dispatch) would
                # otherwise surface downstream, past this fallback.
                jax.block_until_ready(out)
                return Dataset(out, n=data.n)
            except Exception:
                # The items DID stack, so device_fn itself failed (axis bug,
                # batch too large for one dispatch, ...). The per-item path
                # may still work, but say so — a silently-degraded pipeline
                # runs orders of magnitude slower with no visible cause.
                import logging

                logging.getLogger("keystone_tpu.pipeline").warning(
                    "%s.device_fn failed on a stacked (%d, ...) host batch; "
                    "falling back to per-item apply",
                    type(self).__name__, data.n, exc_info=True,
                )
                return data.map(self.apply)
        return data.map(self.apply)

    def device_fn(self) -> Optional[Callable]:
        """Pure batched array function equivalent to ``batch_apply`` on
        array-form datasets, or None when the node is not expressible as
        one. Implementing it opts the node into whole-pipeline stage fusion
        (workflow/fusion.py): chains of such nodes compile into ONE XLA
        program. Contract: row-local (output row i depends only on input
        row i) and side-effect free."""
        return None

    def __call__(self, x: Any) -> Any:
        """Eager application to a datum or Dataset; lazy on pipeline handles."""
        if isinstance(x, Dataset):
            return self.batch_apply(x)
        if isinstance(x, (PipelineDataset, PipelineDatum)):
            return self.to_pipeline().apply(x)
        return self.apply(x)

    def to_pipeline(self) -> Pipeline[A, B]:
        graph = Graph(
            sources=frozenset({SourceId(0)}),
            sink_dependencies={SinkId(0): NodeId(0)},
            operators={NodeId(0): self},
            dependencies={NodeId(0): (SourceId(0),)},
        )
        return Pipeline(GraphExecutor(graph), SourceId(0), SinkId(0))

    # Untyped operator plumbing
    def single_transform(self, inputs: Sequence[Any]) -> Any:
        return self.apply(inputs[0])

    def batch_transform(self, inputs: Sequence[Any]) -> Any:
        return self.batch_apply(inputs[0])


class LambdaTransformer(Transformer):
    """``Transformer(f)`` literal constructor (Transformer.scala:58-70)."""

    def __init__(self, f: Callable[[A], B], batch_f: Optional[Callable] = None, name: str = None):
        self.f = f
        self.batch_f = batch_f
        self.name = name or getattr(f, "__name__", "lambda")

    @property
    def label(self) -> str:
        return f"Lambda[{self.name}]"

    def apply(self, x: A) -> B:
        return self.f(x)

    def batch_apply(self, data: Dataset) -> Dataset:
        if self.batch_f is not None:
            return self.batch_f(data)
        return data.map(self.f)


def transformer(f: Callable[[A], B]) -> Transformer[A, B]:
    """Decorator/factory: lift a plain function to a Transformer."""
    return LambdaTransformer(f)


class Identity(Transformer[A, A]):
    """Passes input through unchanged (workflow/Identity.scala:12)."""

    def apply(self, x: A) -> A:
        return x

    def batch_apply(self, data: Dataset) -> Dataset:
        return data

    def __eq__(self, other: object) -> bool:
        return type(other) is Identity

    def __hash__(self) -> int:
        return hash(Identity)


# ---------------------------------------------------------------------------
# Estimators
# ---------------------------------------------------------------------------


def _sync_fitted(fitted) -> None:
    """Best-effort execution barrier for the measured-outcome stamp:
    jax dispatch is async, so a fit-call wall can close before the
    device work it priced has run. Host-transfer one scalar from the
    first device array in the fitted transformer's state (the
    tunnel-reliable barrier — ``block_until_ready`` returns early on
    remote backends). Results whose arrays hide in closures (chained
    transformers) are skipped: an under-stamped outcome is a smaller
    lie than a crashed fit, and the calibrator's span-window join still
    sees the fold spans."""
    state = getattr(fitted, "__dict__", None) or {}
    for v in state.values():
        for a in (v if isinstance(v, (list, tuple)) else (v,)):
            if isinstance(a, jnp.ndarray) and getattr(a, "size", 0):
                try:
                    float(jnp.asarray(a).ravel()[0])
                except Exception:
                    pass
                return


def _stamped_fit(est, thunk):
    """Run one estimator fit, back-annotating a pending cost decision.

    When the cost model selected ``est`` (``LeastSquaresEstimator.
    optimize`` left a ``CostOutcomeRef`` on it), the executor is the one
    place that observes the priced work actually run — so it stamps the
    winner's measured wall + ``estimator.fit`` span id onto the decision
    record (obs/calibrate.py joins predicted-vs-measured from that).
    The ref is consumed BEFORE the fit so a failed fit never stamps a
    bogus measurement and a re-fit never double-stamps. Estimators with
    no pending decision take the bare path — no span, no timing."""
    ref = getattr(est, "_pending_cost_outcome", None)
    if ref is None:
        return thunk()
    est._pending_cost_outcome = None
    import time as _time

    from keystone_tpu import obs

    t0 = _time.perf_counter()
    with obs.span("estimator.fit", estimator=type(est).__name__) as sp:
        fitted = thunk()
        _sync_fitted(fitted)
    # timing="single_run_cold": a pipeline fits each estimator once, so
    # this wall INCLUDES XLA compile — the calibrator surfaces the mix
    # (calibration_report "timings") and the refit discipline prefers
    # warm rows (docs/observability.md calibration section); the sweep
    # harness stamps min_of_N_warm on its dispatch-subtracted points.
    ref.stamp(
        _time.perf_counter() - t0,
        span_id=getattr(sp, "span_id", None),
        timing="single_run_cold",
    )
    return fitted


class Estimator(EstimatorOperator, Generic[A, B]):
    """Fits a Transformer from a dataset (Estimator.scala:10-62)."""

    def fit(self, data: Dataset) -> Transformer[A, B]:
        raise NotImplementedError

    def fit_datasets(self, inputs: Sequence[Any]) -> TransformerOperator:
        return _stamped_fit(self, lambda: self.fit(inputs[0]))

    def with_data(self, data: Any) -> Pipeline[A, B]:
        """Pipeline that fits this estimator on `data`, then applies the fitted
        transformer to the pipeline input (Estimator.scala:29-46)."""
        data = _as_pipeline_dataset(data)
        cur_sink_dep = data.executor.graph.get_sink_dependency(data.sink)
        graph, est_id = data.executor.graph.remove_sink(data.sink).add_node(self, [cur_sink_dep])
        graph, source_id = graph.add_source()
        graph, delegating_id = graph.add_node(DelegatingOperator(), [est_id, source_id])
        graph, sink_id = graph.add_sink(delegating_id)
        return Pipeline(GraphExecutor(graph), source_id, sink_id)


class LabelEstimator(EstimatorOperator, Generic[A, B, L]):
    """Fits a Transformer from a dataset plus labels (LabelEstimator.scala:13-100)."""

    def device_fit_fn(self):
        """Fit-fusion contract: return a ``workflow.fusion.DeviceFit``
        (traceable fit + host model builder + geometry gate) to let the
        optimizer compile upstream featurization INTO this fit as one
        program, or None (default) to keep the materialized-features
        path."""
        return None

    def fit(self, data: Dataset, labels: Dataset) -> Transformer[A, B]:
        raise NotImplementedError

    def fit_datasets(self, inputs: Sequence[Any]) -> TransformerOperator:
        return _stamped_fit(self, lambda: self.fit(inputs[0], inputs[1]))

    def with_data(self, data: Any, labels: Any) -> Pipeline[A, B]:
        data = _as_pipeline_dataset(data)
        labels = _as_pipeline_dataset(labels)

        graph, _, _, label_sink_mapping = data.executor.graph.add_graph(labels.executor.graph)
        data_sink_dep = graph.get_sink_dependency(data.sink)
        labels_sink_dep = graph.get_sink_dependency(label_sink_mapping[labels.sink])
        graph, est_id = (
            graph.remove_sink(data.sink)
            .remove_sink(label_sink_mapping[labels.sink])
            .add_node(self, [data_sink_dep, labels_sink_dep])
        )
        graph, source_id = graph.add_source()
        graph, delegating_id = graph.add_node(DelegatingOperator(), [est_id, source_id])
        graph, sink_id = graph.add_sink(delegating_id)
        return Pipeline(GraphExecutor(graph), source_id, sink_id)
