"""Untyped dataflow DAG underlying every pipeline.

Semantics mirror the reference workflow graph (reference:
src/main/scala/keystoneml/workflow/Graph.scala:32-457): a graph is an immutable
value made of *sources* (unbound inputs), *nodes* (an operator plus ordered
dependencies on nodes/sources), and *sinks* (named outputs, each depending on
exactly one node or source). All surgery operations (``add_node``, ``add_graph``,
``connect_graph``, ``replace_nodes``, ...) return new ``Graph`` values.

The implementation here is fresh and Python-idiomatic (frozen dataclasses over
plain dicts treated as immutable); only the behavioral contract is shared with
the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, Mapping, Sequence, Set, Tuple, Union

if TYPE_CHECKING:  # pragma: no cover
    from .operators import Operator


@dataclass(frozen=True, order=True)
class SourceId:
    id: int

    def __repr__(self) -> str:
        return f"Source({self.id})"


@dataclass(frozen=True, order=True)
class NodeId:
    id: int

    def __repr__(self) -> str:
        return f"Node({self.id})"


@dataclass(frozen=True, order=True)
class SinkId:
    id: int

    def __repr__(self) -> str:
        return f"Sink({self.id})"


# Union aliases matching the reference's GraphId hierarchy (GraphId.scala:7-31).
NodeOrSourceId = Union[NodeId, SourceId]
GraphId = Union[NodeId, SourceId, SinkId]


class GraphError(ValueError):
    """Raised on invalid graph surgery (the analog of Scala `require` failures)."""


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise GraphError(msg)


@dataclass(frozen=True)
class Graph:
    """Immutable dataflow DAG.

    Attributes:
      sources: set of all SourceIds.
      sink_dependencies: SinkId -> NodeOrSourceId it observes.
      operators: NodeId -> Operator stored at that node.
      dependencies: NodeId -> ordered tuple of NodeOrSourceId inputs.
    """

    sources: frozenset = field(default_factory=frozenset)
    sink_dependencies: Mapping[SinkId, NodeOrSourceId] = field(default_factory=dict)
    operators: Mapping[NodeId, "Operator"] = field(default_factory=dict)
    dependencies: Mapping[NodeId, Tuple[NodeOrSourceId, ...]] = field(default_factory=dict)

    # -- basic accessors ----------------------------------------------------

    @property
    def nodes(self) -> Set[NodeId]:
        return set(self.operators.keys())

    @property
    def sinks(self) -> Set[SinkId]:
        return set(self.sink_dependencies.keys())

    def get_dependencies(self, node: NodeId) -> Tuple[NodeOrSourceId, ...]:
        return tuple(self.dependencies[node])

    def get_sink_dependency(self, sink: SinkId) -> NodeOrSourceId:
        return self.sink_dependencies[sink]

    def get_operator(self, node: NodeId) -> "Operator":
        return self.operators[node]

    def _ids(self) -> Set[NodeOrSourceId]:
        out: Set[NodeOrSourceId] = set(self.operators.keys())
        out |= set(self.sources)
        return out

    # -- fresh id allocation ------------------------------------------------

    def _next_node_ids(self, num: int) -> Tuple[NodeId, ...]:
        max_id = max((n.id for n in self.operators), default=0)
        return tuple(NodeId(max_id + i) for i in range(1, num + 1))

    def _next_source_ids(self, num: int) -> Tuple[SourceId, ...]:
        max_id = max((s.id for s in self.sources), default=0)
        return tuple(SourceId(max_id + i) for i in range(1, num + 1))

    def _next_sink_ids(self, num: int) -> Tuple[SinkId, ...]:
        max_id = max((s.id for s in self.sink_dependencies), default=0)
        return tuple(SinkId(max_id + i) for i in range(1, num + 1))

    # -- single-vertex surgery ----------------------------------------------

    def add_node(self, op: "Operator", deps: Sequence[NodeOrSourceId]) -> Tuple["Graph", NodeId]:
        ids = self._ids()
        _check(all(d in ids for d in deps), "Node must have dependencies on existing ids")
        nid = self._next_node_ids(1)[0]
        return (
            Graph(
                self.sources,
                dict(self.sink_dependencies),
                {**self.operators, nid: op},
                {**self.dependencies, nid: tuple(deps)},
            ),
            nid,
        )

    def add_sink(self, dep: NodeOrSourceId) -> Tuple["Graph", SinkId]:
        _check(dep in self._ids(), "Sink must depend on an existing id")
        sid = self._next_sink_ids(1)[0]
        return (
            Graph(
                self.sources,
                {**self.sink_dependencies, sid: dep},
                dict(self.operators),
                dict(self.dependencies),
            ),
            sid,
        )

    def add_source(self) -> Tuple["Graph", SourceId]:
        sid = self._next_source_ids(1)[0]
        return (
            Graph(
                frozenset(self.sources) | {sid},
                dict(self.sink_dependencies),
                dict(self.operators),
                dict(self.dependencies),
            ),
            sid,
        )

    def set_dependencies(self, node: NodeId, deps: Sequence[NodeOrSourceId]) -> "Graph":
        _check(node in self.dependencies, "Node being updated must exist")
        ids = self._ids()
        _check(all(d in ids for d in deps), "Node must have dependencies on existing ids")
        return Graph(
            self.sources,
            dict(self.sink_dependencies),
            dict(self.operators),
            {**self.dependencies, node: tuple(deps)},
        )

    def set_operator(self, node: NodeId, op: "Operator") -> "Graph":
        _check(node in self.dependencies, "Node being updated must exist")
        return Graph(
            self.sources,
            dict(self.sink_dependencies),
            {**self.operators, node: op},
            dict(self.dependencies),
        )

    def set_sink_dependency(self, sink: SinkId, dep: NodeOrSourceId) -> "Graph":
        _check(sink in self.sink_dependencies, "Sink being updated must exist")
        _check(dep in self._ids(), "Sink must depend on an existing id")
        return Graph(
            self.sources,
            {**self.sink_dependencies, sink: dep},
            dict(self.operators),
            dict(self.dependencies),
        )

    def remove_sink(self, sink: SinkId) -> "Graph":
        _check(sink in self.sink_dependencies, "Sink being removed must exist")
        new_sinks = {k: v for k, v in self.sink_dependencies.items() if k != sink}
        return Graph(self.sources, new_sinks, dict(self.operators), dict(self.dependencies))

    def remove_source(self, source: SourceId) -> "Graph":
        """NOTE: may leave dangling dependencies on the removed source."""
        _check(source in self.sources, "Source being removed must exist")
        return Graph(
            frozenset(s for s in self.sources if s != source),
            dict(self.sink_dependencies),
            dict(self.operators),
            dict(self.dependencies),
        )

    def remove_node(self, node: NodeId) -> "Graph":
        """NOTE: may leave dangling dependencies on the removed node."""
        _check(node in self.operators, "Node being removed must exist")
        return Graph(
            self.sources,
            dict(self.sink_dependencies),
            {k: v for k, v in self.operators.items() if k != node},
            {k: v for k, v in self.dependencies.items() if k != node},
        )

    def replace_dependency(self, old_dep: NodeOrSourceId, new_dep: NodeOrSourceId) -> "Graph":
        _check(new_dep in self._ids(), "Replacement dependency id must exist")
        new_deps = {
            n: tuple(new_dep if d == old_dep else d for d in ds)
            for n, ds in self.dependencies.items()
        }
        new_sink_deps = {
            s: (new_dep if d == old_dep else d) for s, d in self.sink_dependencies.items()
        }
        return Graph(self.sources, new_sink_deps, dict(self.operators), new_deps)

    # -- whole-graph surgery ------------------------------------------------

    def add_graph(
        self, other: "Graph"
    ) -> Tuple["Graph", Dict[SourceId, SourceId], Dict[NodeId, NodeId], Dict[SinkId, SinkId]]:
        """Disjoint union: attach `other`, remapping its ids to avoid collisions.

        Returns (new graph, source id map, node id map, sink id map) for the ids
        of `other` (reference Graph.scala:286-327).
        """
        other_sources = sorted(other.sources)
        other_nodes = sorted(other.operators.keys())
        other_sinks = sorted(other.sink_dependencies.keys())

        src_map = dict(zip(other_sources, self._next_source_ids(len(other_sources))))
        node_map = dict(zip(other_nodes, self._next_node_ids(len(other_nodes))))
        sink_map = dict(zip(other_sinks, self._next_sink_ids(len(other_sinks))))

        def remap(d: NodeOrSourceId) -> NodeOrSourceId:
            return node_map[d] if isinstance(d, NodeId) else src_map[d]

        new_ops = {**self.operators, **{node_map[n]: other.operators[n] for n in other_nodes}}
        new_deps = {
            **self.dependencies,
            **{node_map[n]: tuple(remap(d) for d in other.dependencies[n]) for n in other_nodes},
        }
        new_sources = frozenset(self.sources) | set(src_map.values())
        new_sink_deps = {
            **self.sink_dependencies,
            **{sink_map[s]: remap(other.sink_dependencies[s]) for s in other_sinks},
        }
        return Graph(new_sources, new_sink_deps, new_ops, new_deps), src_map, node_map, sink_map

    def connect_graph(
        self, other: "Graph", splice_map: Mapping[SourceId, SinkId]
    ) -> Tuple["Graph", Dict[SourceId, SourceId], Dict[NodeId, NodeId], Dict[SinkId, SinkId]]:
        """Attach `other`, splicing some of its sources onto this graph's sinks.

        splice_map: {source in `other` -> sink in `self`}. Spliced sources and
        sinks are removed from the result (reference Graph.scala:340-364).
        """
        _check(
            all(s in other.sources for s in splice_map),
            "Must connect to sources that exist in the other graph",
        )
        _check(
            all(k in self.sink_dependencies for k in splice_map.values()),
            "Must connect to sinks that exist in this graph",
        )

        graph, src_map, node_map, sink_map = self.add_graph(other)
        for old_src, sink in splice_map.items():
            src = src_map[old_src]
            sink_dep = self.get_sink_dependency(sink)
            graph = graph.replace_dependency(src, sink_dep).remove_source(src)
        for sink in set(splice_map.values()):
            graph = graph.remove_sink(sink)

        out_src_map = {k: v for k, v in src_map.items() if k not in splice_map}
        return graph, out_src_map, node_map, sink_map

    def replace_nodes(
        self,
        nodes_to_remove: Set[NodeId],
        replacement: "Graph",
        replacement_source_splice: Mapping[SourceId, NodeOrSourceId],
        replacement_sink_splice: Mapping[NodeId, SinkId],
    ) -> "Graph":
        """Swap a set of nodes for an entire replacement graph.

        replacement_source_splice: replacement source -> existing id to feed it.
        replacement_sink_splice: removed node -> replacement sink that now
        supplies its former dependents (reference Graph.scala:379-434).
        """
        _check(
            set(replacement_sink_splice.values()) == replacement.sinks,
            "Must attach all of the replacement's sinks",
        )
        _check(
            all(n in nodes_to_remove for n in replacement_sink_splice),
            "May only replace dependencies on removed nodes",
        )
        _check(
            set(replacement_source_splice.keys()) == replacement.sources,
            "Must attach all of the replacement's sources",
        )
        _check(
            all(
                not (isinstance(v, NodeId) and v in nodes_to_remove)
                for v in replacement_source_splice.values()
            ),
            "May not connect replacement sources to nodes being removed",
        )
        ids = self._ids()
        _check(
            all(v in ids for v in replacement_source_splice.values()),
            "May only connect replacement sources to existing nodes",
        )

        graph = self
        for node in nodes_to_remove:
            graph = graph.remove_node(node)

        graph, src_map, _, sink_map = graph.add_graph(replacement)

        for old_src, target in replacement_source_splice.items():
            src = src_map[old_src]
            graph = graph.replace_dependency(src, target).remove_source(src)

        for removed_node, old_sink in replacement_sink_splice.items():
            sink = sink_map[old_sink]
            replacement_dep = graph.get_sink_dependency(sink)
            graph = graph.replace_dependency(removed_node, replacement_dep)

        final_deps = {d for ds in graph.dependencies.values() for d in ds}
        _check(
            all(n not in final_deps for n in nodes_to_remove),
            "May not have any remaining dangling edges on the removed nodes",
        )

        for sink in set(sink_map.values()):
            graph = graph.remove_sink(sink)
        return graph

    # -- visualization ------------------------------------------------------

    def to_dot(self) -> str:
        """Graphviz DOT rendering, used by the rule executor's trace logging."""

        def name(gid: GraphId) -> str:
            kind = type(gid).__name__.replace("Id", "")
            return f"{kind}_{gid.id}"

        lines = []
        for s in sorted(self.sources):
            lines.append(f'{name(s)} [label="{s}" shape="Msquare"]')
        for n in sorted(self.operators):
            lines.append(f'{name(n)} [label="{self.operators[n].label}"]')
        for s in sorted(self.sink_dependencies):
            lines.append(f'{name(s)} [label="{s}" shape="Msquare"]')
        for n in sorted(self.dependencies):
            for d in self.dependencies[n]:
                lines.append(f"{name(d)} -> {name(n)}")
        for s in sorted(self.sink_dependencies):
            lines.append(f"{name(self.sink_dependencies[s])} -> {name(s)}")
        body = "\n  ".join(lines)
        return "digraph pipeline {\n  rankdir=LR;\n  " + body + "\n}"
