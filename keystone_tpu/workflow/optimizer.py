"""Catalyst-style rule engine for whole-pipeline optimization.

Mirrors reference workflow/Rule.scala:12-20 and RuleExecutor.scala:5-87: an
optimizer is a sequence of named batches of rules; each batch runs serially
with a strategy (Once or FixedPoint) until convergence or iteration cap; rule
applications that change the plan are trace-logged as DOT diffs.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from .env import Prefix
from .graph import Graph, NodeId

logger = logging.getLogger("keystone_tpu.optimizer")

Plan = Tuple[Graph, Dict[NodeId, Prefix]]


class Rule:
    """A plan transformation producing a logically equivalent plan."""

    @property
    def rule_name(self) -> str:
        return type(self).__name__

    def apply(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        raise NotImplementedError


@dataclass(frozen=True)
class Once:
    max_iterations: int = 1


@dataclass(frozen=True)
class FixedPoint:
    max_iterations: int = 2**31 - 1


@dataclass
class Batch:
    name: str
    strategy: object
    rules: Sequence[Rule]


def _plans_equal(a: Plan, b: Plan) -> bool:
    return a[0] == b[0] and a[1] == b[1]


class RuleExecutor:
    """Executes rule batches serially; subclasses define ``batches``."""

    batches: List[Batch] = []

    def execute(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        cur: Plan = (plan, dict(prefixes))

        from keystone_tpu import obs

        for batch in self.batches:
            batch_start = cur
            iteration = 1
            last = cur
            while True:
                for rule in batch.rules:
                    # One span per rule application (obs plane, ISSUE
                    # 9): the trace shows where optimization wall went
                    # and which rules changed the plan. The f-string
                    # name and kwargs are built only when tracing is on
                    # — the disabled fixpoint loop pays one branch.
                    if obs.enabled():
                        with obs.span(
                            f"optimizer.rule.{rule.rule_name}",
                            batch=batch.name, iteration=iteration,
                        ) as sp:
                            result = rule.apply(cur[0], cur[1])
                            changed = not _plans_equal(result, cur)
                            sp.set(changed=changed)
                    else:
                        result = rule.apply(cur[0], cur[1])
                        changed = not _plans_equal(result, cur)
                    if changed:
                        logger.debug(
                            "=== Applying Rule %s ===\n%s\n%s",
                            rule.rule_name,
                            cur[0].to_dot(),
                            result[0].to_dot(),
                        )
                    cur = result
                iteration += 1
                if iteration > batch.strategy.max_iterations:
                    if iteration != 2:
                        logger.info(
                            "Max iterations (%d) reached for batch %s",
                            iteration - 1,
                            batch.name,
                        )
                    break
                if _plans_equal(cur, last):
                    logger.debug(
                        "Fixed point reached for batch %s after %d iterations.",
                        batch.name,
                        iteration - 1,
                    )
                    break
                last = cur

            if _plans_equal(batch_start, cur):
                logger.debug("Batch %s has no effect.", batch.name)

        return cur


class Optimizer(RuleExecutor):
    """Base class for whole-pipeline optimizers (DefaultOptimizer.scala).

    Every optimizer run starts with the static plan verifier
    (workflow/verify.py): an invalid candidate plan — shape mismatch,
    estimator state consumed as data, conflicting shardings — is
    rejected with a structured :class:`~keystone_tpu.workflow.verify.
    PlanVerificationError` BEFORE any rule, cost model, or compile
    touches it. ``KEYSTONE_VERIFY=off`` disables the pre-pass.
    """

    def execute(self, plan: Graph, prefixes: Dict[NodeId, Prefix]) -> Plan:
        from .verify import verify_fit_graph

        verify_fit_graph(plan, context="optimizer input plan")
        return super().execute(plan, prefixes)


def _make_stage_fusion():
    from .fusion import StageFusionRule

    return StageFusionRule()


def _make_tree_fit_fusion():
    from .fusion import (
        EstimatorFusionRule,
        GatherFusionRule,
        StreamedFitFusionRule,
    )

    return [GatherFusionRule(), EstimatorFusionRule(), StreamedFitFusionRule()]


class DefaultOptimizer(Optimizer):
    """Standard batches: saved-state load, CSE to fixpoint, node-level optimization
    (reference: workflow/DefaultOptimizer.scala:8-14)."""

    def __init__(self) -> None:
        from .rules import (
            EquivalentNodeMergeRule,
            ExtractSaveablePrefixes,
            NodeOptimizationRule,
            SavedStateLoadRule,
            UnusedBranchRemovalRule,
        )

        self.batches = [
            Batch(
                "Load Saved State",
                Once(),
                [ExtractSaveablePrefixes(), SavedStateLoadRule(), UnusedBranchRemovalRule()],
            ),
            Batch(
                "Common Sub-expression Elimination",
                FixedPoint(),
                [EquivalentNodeMergeRule()],
            ),
            Batch("Node Level Optimization", Once(), [NodeOptimizationRule()]),
            # TPU-specific: compile chains of row-local device transformers
            # into one XLA program (workflow/fusion.py). Runs last so CSE /
            # prefix extraction see the original node granularity. Gather
            # trees and trailing estimator fits fuse after the chains have
            # collapsed.
            Batch("Stage Fusion", Once(), [_make_stage_fusion()]),
            Batch("Tree & Fit Fusion", Once(), _make_tree_fit_fusion()),
        ]


class AutoCachingOptimizer(Optimizer):
    """DefaultOptimizer plus cache-placement (reference: DefaultOptimizer.scala:19-26).

    Cache placement runs on the POST-fusion plan — the plan that will
    actually execute (the reference's defining property, which round 5
    measured this port violating: profiling the pre-fusion model made
    greedy insert Cachers that broke the fused program and LOSE to
    no-cache). Stage/Tree/Fit fusion collapse device-pure regions first;
    AutoCacheRule then profiles the surviving nodes — host stages,
    multi-consumer intermediates, fused-program outputs — and every
    insertion lands on a fused-stage boundary by construction. The batch
    closes with a prefix re-extraction + saved-state load so the Cachers
    it just placed participate in cross-fit reuse through the
    PipelineEnv state table (a λ-sweep's later fits load the cached
    boundary result instead of recomputing the stage).

    ``cache_before_fusion=True`` restores the round-5 order (cache first,
    fuse around the materialization points) — kept for A/B measurement on
    the autocache bench row, not for production use.
    """

    def __init__(self, strategy=None, cache_before_fusion: bool = False) -> None:
        from .autocache import AutoCacheRule, GreedyCache
        from .rules import (
            EquivalentNodeMergeRule,
            ExtractSaveablePrefixes,
            NodeOptimizationRule,
            SavedStateLoadRule,
            UnusedBranchRemovalRule,
        )

        load_batch = Batch(
            "Load Saved State",
            Once(),
            [ExtractSaveablePrefixes(), SavedStateLoadRule(), UnusedBranchRemovalRule()],
        )
        cse_batch = Batch(
            "Common Sub-expression Elimination",
            FixedPoint(),
            [EquivalentNodeMergeRule()],
        )
        node_opt_batch = Batch(
            "Node Level Optimization", Once(), [NodeOptimizationRule()]
        )
        cache_rule = AutoCacheRule(strategy or GreedyCache())
        if cache_before_fusion:
            self.batches = [
                load_batch,
                cse_batch,
                node_opt_batch,
                Batch("Auto Cache", Once(), [cache_rule]),
                # After cache placement: cached/prefix nodes are excluded
                # from chains, so fusion never hides a materialization point.
                Batch("Stage Fusion", Once(), [_make_stage_fusion()]),
                Batch("Tree & Fit Fusion", Once(), _make_tree_fit_fusion()),
            ]
        else:
            self.batches = [
                load_batch,
                cse_batch,
                node_opt_batch,
                Batch("Stage Fusion", Once(), [_make_stage_fusion()]),
                Batch("Tree & Fit Fusion", Once(), _make_tree_fit_fusion()),
                Batch(
                    "Auto Cache (post-fusion)",
                    Once(),
                    [
                        cache_rule,
                        # The Cachers just placed are saveable materialization
                        # points: mark them (merge — earlier marks win), load
                        # any boundary result a previous fit already
                        # published, and drop branches the loads made dead.
                        ExtractSaveablePrefixes(),
                        SavedStateLoadRule(),
                        UnusedBranchRemovalRule(),
                    ],
                ),
            ]
