"""Static plan verification: catch shape/dtype/structure bugs before
anything compiles.

KeystoneML's typed Scala API made a malformed pipeline a *compile-time*
error (``Pipeline[A,B]`` composition simply didn't typecheck); the Python
port traded that away, so a shape mismatch, a silent f32→bf16 drift, or
an estimator leaking into an apply graph previously surfaced only deep
inside a fit, an AOT export, or an hours-long streamed run. This module
restores the static guarantee by abstract interpretation over the
untyped :class:`~keystone_tpu.workflow.graph.Graph`:

  - Every node gets a *signature* (:class:`ArraySig` — the
    ``ShapeDtypeStruct`` analog, :class:`HostSig` for host-object
    stages, :class:`TupleSig` for gathers, :class:`TransformerSig` for
    estimator outputs), propagated source→sink in topological order.
  - Device-traceable operators (anything exposing ``device_fn`` /
    ``device_combine_fn``) are interpreted with ``jax.eval_shape`` —
    shape errors XLA would raise at trace time are raised HERE, named
    by ``NodeId`` and operator, with nothing compiled.
  - Host-side operators (NLP tokenizers, featurizers, image decode)
    declare ``output_signature(sig)`` (see :func:`expect_host` — the
    declaration API ops/ modules use); undeclared host ops stop
    propagation (or are reported in ``strict`` mode).
  - Structural invariants are checked alongside: estimator state must
    never be reachable as *data* in an apply path, gather branches must
    agree on example counts, multi-input device nodes must not mix
    shardings, and a hand-placed :class:`~keystone_tpu.ops.util.Cacher`
    must not sever an edge the fusion rules would otherwise compile
    into one program.

The verifier runs as a default pre-pass in ``Pipeline.fit``, in
``Optimizer.execute`` (so invalid candidate plans are rejected before
they are ever cost-modeled or compiled), and in
``serving/export.py::export_plan``. ``KEYSTONE_VERIFY=off`` disables it;
``KEYSTONE_VERIFY=strict`` additionally reports undeclared host-op
signatures. Error-severity findings raise
:class:`PlanVerificationError` with a structured multi-error report;
warning-severity findings (dtype drift, fusion-splitting caches) are
logged. See docs/verification.md for the full taxonomy.
"""

from __future__ import annotations

import logging
import os
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from . import analysis
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    ExpressionOperator,
    GatherTransformerOperator,
    Operator,
)

logger = logging.getLogger("keystone_tpu.verify")

__all__ = [
    "ArraySig",
    "HostSig",
    "TupleSig",
    "TransformerSig",
    "UNKNOWN",
    "Finding",
    "VerifyReport",
    "PlanVerificationError",
    "SignatureError",
    "expect_host",
    "signature_of_value",
    "verify_graph",
    "verify_fit_graph",
    "verify_apply_graph",
    "verification_mode",
    "annotate_node_error",
    "describe_value",
]


# ---------------------------------------------------------------------------
# Signatures
# ---------------------------------------------------------------------------


class Sig:
    """Base class of all node signatures."""

    def describe(self) -> str:
        return type(self).__name__


class _Unknown(Sig):
    """Signature of a value the verifier cannot reason about (unbound
    sources, spliced expressions, undeclared host ops). Unknown inputs
    silence downstream checks — the verifier under-approximates rather
    than guess."""

    _instance: Optional["_Unknown"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def describe(self) -> str:
        return "?"


UNKNOWN = _Unknown()


@dataclass(frozen=True)
class ArraySig(Sig):
    """Batch-form array signature: ``shape`` is the full (padded) batch
    shape with ``None`` for an unknown leading example axis; ``n`` is the
    true example count when known; ``datum=True`` marks a single example
    (shape then has NO leading example axis). ``mesh`` carries the
    sharding mesh when the backing dataset declared one — multi-input
    nodes check meshes for conflicts."""

    shape: Tuple[Optional[int], ...]
    dtype: str
    n: Optional[int] = None
    mesh: Any = field(default=None, compare=False)
    datum: bool = False

    def describe(self) -> str:
        dims = ",".join("?" if d is None else str(d) for d in self.shape)
        kind = "datum" if self.datum else "batch"
        return f"{kind} f[{dims}]:{self.dtype}"


@dataclass(frozen=True)
class HostSig(Sig):
    """Host-object (or non-dense-array) signature: ``kind`` is a small
    vocabulary shared by the declared NLP/image ops — ``"str"``,
    ``"tokens"`` (list of str), ``"ngrams"`` (list of tuples),
    ``"tf_dict"`` (feature→weight dict), ``"int_tokens"``,
    ``"ngram_counts"``, ``"sparse"`` (the padded-COO device batch),
    ``"any"``."""

    kind: str = "any"
    n: Optional[int] = None
    datum: bool = False

    def describe(self) -> str:
        return f"host[{self.kind}]"


@dataclass(frozen=True)
class TupleSig(Sig):
    """Signature of a gather output: one element signature per branch."""

    elements: Tuple[Sig, ...]
    n: Optional[int] = None
    datum: bool = False

    def describe(self) -> str:
        return "(" + ", ".join(e.describe() for e in self.elements) + ")"


@dataclass(frozen=True)
class TransformerSig(Sig):
    """Signature of an estimator node's output: a fitted transformer
    (state, not data). Carries the estimator so delegating nodes can ask
    it for a ``fitted_signature``."""

    label: str
    estimator: Any = field(default=None, compare=False)

    def describe(self) -> str:
        return f"transformer[{self.label}]"


class SignatureError(ValueError):
    """Raised by an operator's ``output_signature`` when the incoming
    signature violates its declared input contract. The verifier turns
    it into a finding naming the node."""


def expect_host(sig: Sig, kinds: Sequence[str], op: Operator) -> HostSig:
    """Declaration helper for host ops: assert ``sig`` is a
    :class:`HostSig` of one of ``kinds`` (``"any"`` in either position
    matches everything) and return it. Raises :class:`SignatureError`
    with an operator-named message otherwise."""
    if not isinstance(sig, HostSig):
        raise SignatureError(
            f"{op.label} expects host input of kind {tuple(kinds)}, "
            f"got {sig.describe()}"
        )
    if sig.kind != "any" and "any" not in kinds and sig.kind not in kinds:
        raise SignatureError(
            f"{op.label} expects host input of kind {tuple(kinds)}, "
            f"got host[{sig.kind}]"
        )
    return sig


# ---------------------------------------------------------------------------
# Signature inference for concrete payloads
# ---------------------------------------------------------------------------


_HOST_KIND_ORDER = ("str", "tokens", "ngrams", "tf_dict", "int_tokens")


def _infer_host_kind(item: Any) -> str:
    if isinstance(item, str):
        return "str"
    if isinstance(item, bytes):
        return "bytes"
    if isinstance(item, dict):
        return "tf_dict"
    if isinstance(item, (list, tuple)) and item:
        first = item[0]
        if isinstance(first, str):
            return "tokens"
        if isinstance(first, tuple):
            return "ngrams"
        if isinstance(first, (int, np.integer)):
            return "int_tokens"
    return "any"


def _is_arraylike(x: Any) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def signature_of_value(value: Any) -> Sig:
    """Best-effort signature of a concrete value (a Dataset payload, a
    datum, or an intermediate result — the executor's error annotation
    uses this too). Datasets describe their batch form; any bare value
    is by construction a single datum."""
    from keystone_tpu.data import Dataset

    if isinstance(value, Dataset):
        if value.is_host:
            items = value.data
            kind = _infer_host_kind(items[0]) if items else "any"
            return HostSig(kind, n=value.n)
        if value.is_shard_backed:
            return UNKNOWN
        data = value.data
        if isinstance(data, dict) and set(data.keys()) == {
            "indices", "values",
        }:
            # The padded-COO sparse batch form (ops/sparse.py).
            return HostSig("sparse", n=value.n)
        if isinstance(data, tuple):
            elems = tuple(
                ArraySig(tuple(a.shape), str(np.dtype(a.dtype)), n=value.n,
                         mesh=value.mesh)
                if _is_arraylike(a) else UNKNOWN
                for a in data
            )
            return TupleSig(elems, n=value.n)
        if _is_arraylike(data):
            return ArraySig(
                tuple(int(d) for d in data.shape),
                str(np.dtype(data.dtype)),
                n=value.n,
                mesh=value.mesh,
            )
        return UNKNOWN
    if isinstance(value, (str, bytes, dict, list)):
        return HostSig(_infer_host_kind(value), datum=True)
    if isinstance(value, tuple):
        return TupleSig(
            tuple(signature_of_value(v) for v in value),
            datum=True,
        )
    if _is_arraylike(value):
        return ArraySig(
            tuple(int(d) for d in value.shape),
            str(np.dtype(value.dtype)),
            datum=True,
        )
    if isinstance(value, (int, float, np.number, bool)):
        return ArraySig((), str(np.asarray(value).dtype), datum=True)
    return UNKNOWN


# ---------------------------------------------------------------------------
# Findings / report
# ---------------------------------------------------------------------------


# Error taxonomy (docs/verification.md):
SHAPE_MISMATCH = "shape-mismatch"
HOST_SIGNATURE_MISMATCH = "host-signature-mismatch"
DTYPE_DRIFT = "dtype-drift"
ESTIMATOR_IN_APPLY = "estimator-in-apply"
CACHE_SPLITS_FUSION = "cache-splits-fusion"
GATHER_MISMATCH = "gather-mismatch"
SHARDING_CONFLICT = "sharding-conflict"
UNDECLARED_SIGNATURE = "undeclared-signature"

_ERROR_CODES = frozenset({
    SHAPE_MISMATCH,
    HOST_SIGNATURE_MISMATCH,
    ESTIMATOR_IN_APPLY,
    GATHER_MISMATCH,
    SHARDING_CONFLICT,
})


@dataclass(frozen=True)
class Finding:
    """One verification finding, anchored to the offending node."""

    code: str
    node: GraphId
    operator: str
    message: str
    severity: str = "error"  # "error" | "warn"

    def __str__(self) -> str:
        return f"[{self.code}] {self.node!r} {self.operator}: {self.message}"


class VerifyReport:
    """Structured multi-error report: every finding names its NodeId and
    operator, so a failure cites the same coordinates as the executor's
    runtime error annotations."""

    def __init__(self, findings: Sequence[Finding] = ()):  # noqa: D401
        self.findings: List[Finding] = list(findings)
        self.sigs: Dict[GraphId, Sig] = {}

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warn"]

    def by_code(self, code: str) -> List[Finding]:
        return [f for f in self.findings if f.code == code]

    def add(self, code, node, op, message, severity=None) -> None:
        if severity is None:
            severity = "error" if code in _ERROR_CODES else "warn"
        label = getattr(op, "label", None) or type(op).__name__
        self.findings.append(Finding(code, node, label, message, severity))

    def __bool__(self) -> bool:
        return bool(self.findings)

    def __str__(self) -> str:
        if not self.findings:
            return "plan verified: no findings"
        lines = [
            f"plan verification: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s)"
        ]
        lines += [f"  {f}" for f in self.findings]
        return "\n".join(lines)

    def raise_if_errors(self, context: str = "plan") -> None:
        if self.errors:
            raise PlanVerificationError(self, context)
        for w in self.warnings:
            logger.warning("%s: %s", context, w)


class PlanVerificationError(ValueError):
    """An invalid plan was rejected by the static verifier (before
    anything was cost-modeled or compiled). ``.report`` holds the full
    multi-error :class:`VerifyReport`."""

    def __init__(self, report: VerifyReport, context: str = "plan"):
        self.report = report
        self.context = context
        errs = "\n".join(f"  {f}" for f in report.errors)
        super().__init__(
            f"{context} failed static verification "
            f"({len(report.errors)} error(s)):\n{errs}"
        )


# ---------------------------------------------------------------------------
# Abstract interpretation
# ---------------------------------------------------------------------------


_EVAL_BATCH = 2  # placeholder batch size when the leading axis is unknown


def _evaluable(sig: Sig) -> bool:
    """Concrete enough for jax.eval_shape: an ArraySig whose only
    unknown dimension (if any) is the leading example axis."""
    if not isinstance(sig, ArraySig):
        return False
    dims = sig.shape if sig.datum else sig.shape[1:]
    return all(d is not None for d in dims)


def _spec_for(sig: ArraySig):
    import jax

    shape = sig.shape
    if sig.datum:
        shape = (1,) + shape
    else:
        shape = tuple(_EVAL_BATCH if d is None else d for d in shape)
    return jax.ShapeDtypeStruct(shape, np.dtype(sig.dtype))


def _sig_from_result(res, in_sig: ArraySig) -> Sig:
    shape = tuple(int(d) for d in res.shape)
    if in_sig.datum:
        if not shape or shape[0] != 1:
            return UNKNOWN  # not row-local; don't guess the datum form
        return ArraySig(shape[1:], str(np.dtype(res.dtype)), datum=True)
    lead: Tuple[Optional[int], ...] = shape
    if in_sig.shape and in_sig.shape[0] is None:
        lead = (None,) + shape[1:]
    return ArraySig(lead, str(np.dtype(res.dtype)), n=in_sig.n,
                    mesh=in_sig.mesh)


def _eval_device_fn(fn, sig: ArraySig):
    """jax.eval_shape the operator's batched function on the incoming
    signature. Returns (result_struct, None) or (None, error_message)."""
    import jax

    try:
        res = jax.eval_shape(fn, _spec_for(sig))
    except Exception as e:  # noqa: BLE001 — any trace failure is the finding
        msg = str(e).strip().split("\n")[0]
        return None, (msg[:300] or type(e).__name__)
    return res, None


def _short(sig: Sig) -> str:
    return sig.describe()


def _first_float(*dtypes: str) -> bool:
    # jax's dtype lattice, not numpy's: bfloat16 (ml_dtypes) is floating
    # here but NOT an np.floating subdtype — and bf16 drift is the single
    # most important case this check exists for.
    import jax.numpy as jnp

    return all(jnp.issubdtype(np.dtype(d), jnp.floating) for d in dtypes)


def _known(sig: Sig) -> bool:
    """Fully-known signature (recursively for tuples): the strict
    undeclared-signature check only fires when the operator was actually
    handed something it could have declared against."""
    if isinstance(sig, _Unknown):
        return False
    if isinstance(sig, TupleSig):
        return all(_known(e) for e in sig.elements)
    return True


def _dtype_drift(in_dtype: str, out_dtype: str) -> bool:
    """True when a float→float dtype change is an operator-level drift
    worth flagging. float64 inputs under a disabled-x64 jax config are
    exempt: jax demotes EVERY f64 operand globally there, so the change
    is runtime policy, not this operator's doing."""
    if not _first_float(in_dtype, out_dtype) or in_dtype == out_dtype:
        return False
    if in_dtype == "float64":
        import jax

        if not jax.config.jax_enable_x64:
            return False
    return True


def _mesh_of(sig: Sig):
    return sig.mesh if isinstance(sig, ArraySig) else None


def _n_of(sig: Sig) -> Optional[int]:
    return getattr(sig, "n", None)


def _full_topo(graph: Graph) -> List[GraphId]:
    """Every node/sink in dependency order — sink-reachable ids first
    (``analysis.linearize``), then any remaining islands (nodes no sink
    observes yet: mid-surgery graphs) in their own topo order."""
    order = analysis.linearize(graph)
    seen = set(order)
    for node in sorted(graph.nodes, key=lambda n: n.id):
        if node not in seen:
            tail = analysis.linearize(graph, node)
            order.extend(g for g in tail if g not in seen)
            seen.update(tail)
    return order


def _infer_and_check(
    graph: Graph,
    node: NodeId,
    op: Operator,
    in_sigs: List[Sig],
    report: VerifyReport,
    strict: bool,
) -> Sig:
    """One node of the abstract interpretation: run the node-level
    checks, return the node's output signature."""
    # -- estimator state must never flow as data --------------------------
    for i, s in enumerate(in_sigs):
        if isinstance(s, TransformerSig) and not (
            isinstance(op, DelegatingOperator) and i == 0
        ):
            report.add(
                ESTIMATOR_IN_APPLY, node, op,
                f"input {i} is fitted-estimator state "
                f"({s.describe()}) consumed as data — estimator output "
                "may only feed a DelegatingOperator's first slot",
            )
            return UNKNOWN

    if isinstance(op, DelegatingOperator):
        if not in_sigs:
            return UNKNOWN
        head = in_sigs[0]
        if not isinstance(head, (TransformerSig, _Unknown)):
            report.add(
                ESTIMATOR_IN_APPLY, node, op,
                f"first input must be an estimator's fitted transformer, "
                f"got {head.describe()}",
            )
            return UNKNOWN
        est = head.estimator if isinstance(head, TransformerSig) else None
        fitted_sig = getattr(est, "fitted_signature", None)
        if fitted_sig is not None:
            try:
                return fitted_sig(in_sigs[1:]) or UNKNOWN
            except SignatureError as e:
                report.add(HOST_SIGNATURE_MISMATCH, node, op, str(e))
                return UNKNOWN
            except Exception:  # noqa: BLE001 — declarations must not kill verify
                return UNKNOWN
        return UNKNOWN

    # -- cross-input consistency (estimators, gathers, combiners) --------
    known_ns = {(_n_of(s)) for s in in_sigs if _n_of(s) is not None}
    meshes = [_mesh_of(s) for s in in_sigs if _mesh_of(s) is not None]
    if len(in_sigs) > 1:
        if len(known_ns) > 1:
            report.add(
                GATHER_MISMATCH, node, op,
                f"inputs disagree on example count: {sorted(known_ns)} "
                f"({', '.join(_short(s) for s in in_sigs)})",
            )
        if len({id(m) for m in meshes}) > 1:
            report.add(
                SHARDING_CONFLICT, node, op,
                "inputs are sharded over different meshes: "
                + ", ".join(str(m) for m in meshes),
            )

    if isinstance(op, EstimatorOperator):
        # Estimators may declare a fit-input contract (the analog of the
        # typed API's Estimator[A, B] input bound).
        check = getattr(op, "check_fit_signature", None)
        if check is not None and all(_known(s) for s in in_sigs):
            try:
                check(in_sigs)
            except SignatureError as e:
                report.add(HOST_SIGNATURE_MISMATCH, node, op, str(e))
            except Exception:  # noqa: BLE001 — declarations must not kill verify
                pass
        return TransformerSig(
            getattr(op, "label", type(op).__name__), estimator=op
        )

    if isinstance(op, GatherTransformerOperator):
        n = next(iter(known_ns)) if len(known_ns) == 1 else None
        datum = any(getattr(s, "datum", False) for s in in_sigs)
        return TupleSig(tuple(in_sigs), n=n, datum=datum)

    if isinstance(op, (DatasetOperator, DatumOperator, ExpressionOperator)):
        # handled by the caller (payload signatures); defensive default.
        return UNKNOWN

    # -- cache-cut placement ----------------------------------------------
    if getattr(op, "is_cache", False):
        _check_cache_cut(graph, node, op, report)
        return in_sigs[0] if in_sigs else UNKNOWN

    # -- declared host/array signature ------------------------------------
    declared = getattr(op, "output_signature", None)
    if declared is not None and in_sigs and all(_known(s) for s in in_sigs):
        try:
            out = declared(in_sigs[0] if len(in_sigs) == 1 else in_sigs)
            return out if isinstance(out, Sig) else UNKNOWN
        except SignatureError as e:
            report.add(HOST_SIGNATURE_MISMATCH, node, op, str(e))
            return UNKNOWN
        except Exception:  # noqa: BLE001 — declarations must not kill verify
            logger.debug("output_signature of %s failed", op, exc_info=True)
            return UNKNOWN

    # -- device combiner over a gather tuple -------------------------------
    combine_get = getattr(op, "device_combine_fn", None)
    if (
        callable(combine_get)
        and len(in_sigs) == 1
        and isinstance(in_sigs[0], TupleSig)
    ):
        if not all(_evaluable(e) for e in in_sigs[0].elements):
            return UNKNOWN  # branches not fully known: nothing to check
        fn = combine_get()
        if fn is not None:
            import jax

            tup = in_sigs[0]
            branch_dtypes = {e.dtype for e in tup.elements}
            if (
                len(branch_dtypes) > 1
                and _first_float(*branch_dtypes)
                and any(
                    _dtype_drift(a, b)
                    for a in branch_dtypes for b in branch_dtypes
                )
            ):
                report.add(
                    DTYPE_DRIFT, node, op,
                    f"gathered branches mix float dtypes "
                    f"{sorted(branch_dtypes)} — the combiner will "
                    "silently promote",
                )
            specs = [_spec_for(e) for e in tup.elements]
            try:
                res = jax.eval_shape(fn, specs)
            except Exception as e:  # noqa: BLE001
                report.add(
                    SHAPE_MISMATCH, node, op,
                    f"combiner rejects branch signatures "
                    f"{_short(tup)}: {str(e).strip().splitlines()[0][:300]}",
                )
                return UNKNOWN
            ref = tup.elements[0]
            out = _sig_from_result(res, ref)
            if isinstance(out, ArraySig):
                out = ArraySig(out.shape, out.dtype, n=tup.n, mesh=ref.mesh,
                               datum=ref.datum)
            return out

    # -- device-traceable transformer --------------------------------------
    fn_get = getattr(op, "device_fn", None)
    if callable(fn_get) and len(in_sigs) == 1 and _evaluable(in_sigs[0]):
        fn = fn_get()
        if fn is not None:
            sig = in_sigs[0]
            res, err = _eval_device_fn(fn, sig)
            if err is not None:
                report.add(
                    SHAPE_MISMATCH, node, op,
                    f"rejects input {_short(sig)}: {err}",
                )
                return UNKNOWN
            out = _sig_from_result(res, sig)
            if (
                isinstance(out, ArraySig)
                and _dtype_drift(sig.dtype, out.dtype)
                and not getattr(op, "declares_dtype_change", False)
            ):
                report.add(
                    DTYPE_DRIFT, node, op,
                    f"silently changes float dtype {sig.dtype} -> "
                    f"{out.dtype} across a stage boundary (declare with "
                    "`declares_dtype_change = True` if intended)",
                )
            return out

    # -- undeclared -------------------------------------------------------
    try:
        has_device_decl = (
            callable(fn_get) and fn_get() is not None
        ) or declared is not None
    except Exception:  # noqa: BLE001
        has_device_decl = declared is not None
    if (
        strict
        and in_sigs
        and not has_device_decl
        and all(_known(s) for s in in_sigs)
    ):
        report.add(
            UNDECLARED_SIGNATURE, node, op,
            f"host-side operator has no declared output_signature (and no "
            f"device_fn) for input {', '.join(_short(s) for s in in_sigs)}",
            severity="error",
        )
    return UNKNOWN


def _check_cache_cut(graph: Graph, node: NodeId, op, report: VerifyReport):
    """A Cacher must sit on a fused-stage *boundary*: if its dependency
    and its consumer would have compiled into one program, the cut
    splits the fusable region — the exact placement mistake
    AutoCacheRule refuses mechanically. Delegates to the authoritative
    predicate (``fusion.cache_would_split_fusion``) on the
    cacher-stripped graph, so this check and the optimizer's can never
    disagree about what fuses."""
    from . import fusion

    deps = graph.get_dependencies(node)
    if len(deps) != 1 or not isinstance(deps[0], NodeId):
        return
    d = deps[0]
    try:
        # Remove the cacher: its consumers re-attach directly to d —
        # the graph the fusion rules would have seen without the cut.
        stripped = graph.replace_dependency(node, d).remove_node(node)
    except Exception:  # noqa: BLE001 — malformed surgery: other checks own it
        return
    if fusion.cache_would_split_fusion(stripped, d, {}):
        dop = graph.get_operator(d)
        consumer_labels = sorted(
            stripped.get_operator(c).label
            for c, cdeps in stripped.dependencies.items()
            if d in cdeps
        )
        report.add(
            CACHE_SPLITS_FUSION, node, op,
            f"cache cut after {dop.label} ({d!r}, feeding "
            f"{', '.join(consumer_labels)}) splits a fusable region — "
            "the stages would otherwise compile into one program",
        )


def verify_graph(
    graph: Graph,
    source_sigs: Optional[Mapping[SourceId, Sig]] = None,
    strict: bool = False,
) -> VerifyReport:
    """Run the abstract interpretation over ``graph`` and return the
    report. ``source_sigs`` binds signatures to unbound sources (the
    export path passes the example-input signature); unbound sources
    default to :data:`UNKNOWN`."""
    report = VerifyReport()
    sigs: Dict[GraphId, Sig] = {}
    for src in graph.sources:
        sigs[src] = (source_sigs or {}).get(src, UNKNOWN)

    for gid in _full_topo(graph):
        if gid in sigs:
            continue
        if isinstance(gid, SinkId):
            sigs[gid] = sigs.get(graph.get_sink_dependency(gid), UNKNOWN)
            continue
        if isinstance(gid, SourceId):
            sigs[gid] = UNKNOWN
            continue
        op = graph.get_operator(gid)
        deps = graph.get_dependencies(gid)
        in_sigs = [sigs.get(d, UNKNOWN) for d in deps]
        if isinstance(op, DatasetOperator):
            sigs[gid] = signature_of_value(op.dataset)
        elif isinstance(op, DatumOperator):
            sigs[gid] = signature_of_value(op.datum)
        elif isinstance(op, ExpressionOperator):
            sigs[gid] = UNKNOWN
        else:
            sigs[gid] = _infer_and_check(
                graph, gid, op, in_sigs, report, strict
            )
    report.sigs = sigs
    return report


# ---------------------------------------------------------------------------
# Pre-pass entry points (fit / optimizer / export)
# ---------------------------------------------------------------------------


def verification_mode() -> str:
    """The ``KEYSTONE_VERIFY`` knob: ``"on"`` (default), ``"off"``
    (skip the pre-pass entirely), or ``"strict"`` (undeclared host-op
    signatures become errors too)."""
    raw = os.environ.get("KEYSTONE_VERIFY", "on").strip().lower()
    if raw in ("off", "0", "false", "no", "disable", "disabled"):
        return "off"
    if raw == "strict":
        return "strict"
    return "on"


# One-slot memo: Pipeline.fit verifies a graph and then immediately hands
# the same object to Optimizer.execute — don't interpret it twice.
_LAST_VERIFIED: Optional["weakref.ref[Graph]"] = None


def _recently_verified(graph: Graph) -> bool:
    return _LAST_VERIFIED is not None and _LAST_VERIFIED() is graph


def _mark_verified(graph: Graph) -> None:
    global _LAST_VERIFIED
    try:
        _LAST_VERIFIED = weakref.ref(graph)
    except TypeError:  # pragma: no cover — Graph is weakref-able
        _LAST_VERIFIED = None


def verify_fit_graph(graph: Graph, context: str = "pipeline plan") -> None:
    """The default pre-pass ``Pipeline.fit`` and ``Optimizer.execute``
    run: verify, raise :class:`PlanVerificationError` on error-severity
    findings, log warnings. Honors ``KEYSTONE_VERIFY``."""
    from keystone_tpu import obs

    mode = verification_mode()
    if mode == "off":
        return
    if _recently_verified(graph):
        return
    with obs.span("verify.pre_pass", context=context, mode=mode,
                  nodes=len(graph.operators)) as sp:
        report = verify_graph(graph, strict=(mode == "strict"))
        sp.set(warnings=len(report.warnings), errors=len(report.errors))
        report.raise_if_errors(context)
    # Memoize only CLEAN graphs (fit hands the same object straight to
    # the optimizer pre-pass): a failed verification must re-run if the
    # caller retries.
    _mark_verified(graph)


def verify_apply_graph(
    graph: Graph,
    source: SourceId,
    sink: SinkId,
    example: Any = None,
    context: str = "apply plan",
) -> Optional[VerifyReport]:
    """The export pre-pass: the graph must be an apply-only (transformer
    and state-free) plan, and — when an ``example`` datum is given — the
    whole chain must typecheck from its concrete signature. Returns the
    report (None when verification is off)."""
    mode = verification_mode()
    if mode == "off":
        return None
    report = VerifyReport()
    for node in graph.nodes:
        op = graph.get_operator(node)
        if isinstance(op, (EstimatorOperator, DelegatingOperator)):
            report.add(
                ESTIMATOR_IN_APPLY, node, op,
                "estimator state reachable from the apply graph — serving "
                "never runs fits; call .fit() first",
            )
    if report.errors:
        report.raise_if_errors(context)

    source_sigs: Dict[SourceId, Sig] = {}
    if example is not None:
        ex = np.asarray(example)
        source_sigs[source] = ArraySig(
            (None,) + tuple(int(d) for d in ex.shape),
            str(np.dtype(ex.dtype)),
        )
    inner = verify_graph(
        graph, source_sigs=source_sigs, strict=(mode == "strict")
    )
    inner.findings.extend(report.findings)
    inner.raise_if_errors(context)
    return inner


# ---------------------------------------------------------------------------
# Runtime error coordinates (executor satellite)
# ---------------------------------------------------------------------------


def describe_value(value: Any) -> str:
    """One-line signature description of a concrete runtime value."""
    try:
        return signature_of_value(value).describe()
    except Exception:  # noqa: BLE001 — annotation must never mask the error
        return type(value).__name__


def annotate_node_error(
    exc: BaseException,
    node: GraphId,
    op: Operator,
    dep_values: Sequence[Any],
) -> None:
    """Attach graph coordinates (NodeId, operator class, inferred input
    signatures) to a runtime node failure, IN PLACE — the exception type
    is preserved so callers' except clauses keep matching, and the
    annotation only applies once (the deepest failing node wins), so
    re-raises through enclosing nodes stay clean."""
    if getattr(exc, "_keystone_node_context", None) is not None:
        return
    inputs = ", ".join(describe_value(v) for v in dep_values) or "-"
    label = getattr(op, "label", None) or type(op).__name__
    context = (
        f"[keystone node {node!r} op={label} "
        f"({type(op).__name__}) inputs=({inputs})]"
    )
    try:
        exc._keystone_node_context = context  # type: ignore[attr-defined]
    except Exception:  # noqa: BLE001 — some exceptions forbid attributes
        return
    try:
        if exc.args and isinstance(exc.args[0], str):
            exc.args = (f"{exc.args[0]}\n  {context}",) + exc.args[1:]
        else:
            exc.args = exc.args + (context,)
    except Exception:  # noqa: BLE001 — never mask the original failure
        pass
