"""Process-global pipeline environment and logical prefixes.

Mirrors the reference's PipelineEnv + Prefix (reference:
workflow/PipelineEnv.scala:7-46, Prefix.scala:4-30): a process-global table
mapping the *logical prefix* of a node (its operator plus the prefixes of its
dependencies, recursively) to an already-computed Expression, so fitted
estimators and cached datasets are reused across pipeline applications; plus
the currently installed whole-pipeline optimizer.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional, Tuple

from .graph import Graph, NodeId, SourceId
from .operators import Expression, Operator

if TYPE_CHECKING:  # pragma: no cover
    from .optimizer import Optimizer


class Prefix:
    """Logical hash of a node: its operator + prefixes of its ordered deps.

    Immutable; the hash is computed once at construction so that shared
    sub-prefixes in diamond-shaped DAGs don't make hashing quadratic.
    """

    __slots__ = ("operator", "deps", "_hash")

    def __init__(self, operator: Operator, deps: Tuple["Prefix", ...]):
        self.operator = operator
        self.deps = tuple(deps)
        self._hash = hash((operator, self.deps))

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Prefix)
            and self._hash == other._hash
            and self.operator == other.operator
            and self.deps == other.deps
        )

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:
        return f"Prefix({self.operator.label}, deps={len(self.deps)})"

    @staticmethod
    def find(graph: Graph, node: NodeId, _memo: Optional[dict] = None) -> "Prefix":
        """Compute the prefix of `node`. Errors if any ancestor is a source.

        Memoized per-call so shared (diamond) subgraphs are traversed once.
        """
        if _memo is None:
            _memo = {}
        if node in _memo:
            return _memo[node]
        deps = []
        for dep in graph.get_dependencies(node):
            if isinstance(dep, SourceId):
                raise ValueError(
                    "May not get the prefix of a node with Sources in the dependencies."
                )
            deps.append(Prefix.find(graph, dep, _memo))
        out = Prefix(graph.get_operator(node), tuple(deps))
        _memo[node] = out
        return out


class PipelineEnv:
    """Global state shared by all pipelines in the process. Not thread-safe."""

    _instance: Optional["PipelineEnv"] = None

    def __init__(self) -> None:
        self.state: Dict[Prefix, Expression] = {}
        self._optimizer: Optional["Optimizer"] = None

    @classmethod
    def get_or_create(cls) -> "PipelineEnv":
        if cls._instance is None:
            cls._instance = PipelineEnv()
        return cls._instance

    @property
    def optimizer(self) -> "Optimizer":
        if self._optimizer is None:
            from .optimizer import DefaultOptimizer

            self._optimizer = DefaultOptimizer()
        return self._optimizer

    def set_optimizer(self, optimizer: "Optimizer") -> None:
        self._optimizer = optimizer

    def reset(self) -> None:
        """Clear prefix state and optimizer (test fixture hook, PipelineContext.scala:9-42).

        Also clears the autocache observed-profile table: its keys hash
        DatasetOperators by dataset id(), and letting entries outlive the
        env generation would widen the window for a recycled id to alias a
        stale profile onto different data (the hazard _SHARED_FIT_PROGRAMS
        guards with weakref re-verification)."""
        self.state.clear()
        self._optimizer = None
        from . import autocache

        autocache.clear_observed_profiles()
