"""Optimizable nodes: operators with data-dependent algorithm selection.

Mirror of reference workflow/OptimizableNodes.scala:7-50: each optimizable node
has a ``default`` concrete implementation plus an ``optimize(sample, ...)``
hook invoked by NodeOptimizationRule with a small sample of the node's actual
input, returning the concrete operator to swap in (or None to keep default).
"""

from __future__ import annotations

from typing import Optional

from keystone_tpu.data import Dataset

from .operators import TransformerOperator
from .pipeline import Estimator, LabelEstimator, Transformer


class OptimizableTransformer(Transformer):
    """Transformer with a sample-driven implementation choice."""

    @property
    def default(self) -> Transformer:
        raise NotImplementedError

    def optimize(self, sample: Dataset) -> Optional[TransformerOperator]:
        raise NotImplementedError

    def apply(self, x):
        return self.default.apply(x)

    def batch_apply(self, data: Dataset) -> Dataset:
        return self.default.batch_apply(data)


class OptimizableEstimator(Estimator):
    """Estimator with a sample-driven implementation choice."""

    @property
    def default(self) -> Estimator:
        raise NotImplementedError

    def optimize(self, sample: Dataset) -> Optional[object]:
        raise NotImplementedError

    def fit(self, data: Dataset):
        return self.default.fit(data)


class OptimizableLabelEstimator(LabelEstimator):
    """LabelEstimator with a sample-driven implementation choice."""

    @property
    def default(self) -> LabelEstimator:
        raise NotImplementedError

    def optimize(self, sample: Dataset, labels_sample: Dataset) -> Optional[object]:
        raise NotImplementedError

    def fit(self, data: Dataset, labels: Dataset):
        return self.default.fit(data, labels)
