"""CLI launcher: ``python -m keystone_tpu.run <PipelineName> --flags``
(reference: bin/run-pipeline.sh:1-55 — spark-submit wrapper resolving a
pipeline class name and forwarding flags).

Pipeline names accept the reference's fully-qualified class names
(``keystoneml.pipelines.images.mnist.MnistRandomFFT``) or the bare name.

``python -m keystone_tpu.run serve [--model fitted.pkl | --pipeline
MnistRandomFFT] --rate 200 --duration-s 5`` starts the online serving
path instead: export the fitted pipeline, run the deadline-aware
micro-batch server under open-loop Poisson load, and print the p50/p99
latency + throughput summary line (docs/serving.md). ``--replicas N``
serves through the replicated plane instead (least-loaded routing,
per-replica breakers, watchdog restarts, hot-swap — docs/serving.md's
replicated section). ``--fleet N`` serves through N crash-contained
plane PROCESSES behind the FleetRouter's admission door (each plane a
full replicated stack fed the plan over the fingerprint-verified ship;
process kills survived with exact books — docs/serving.md fleet
section). ``--from-plan artifact.json`` consumes a ``bin/plan --apply``
serving-defaults artifact: flags left at their defaults are filled
from the planner's measured baseline, and the summary line stamps the
artifact's provenance (docs/placement.md).

Global reliability flags (any pipeline, and serve — docs/reliability.md):
``--checkpoint-dir=DIR`` makes segmented streamed fits snapshot their
fold carry there (and resume from it on re-run, bit-identically);
``--fault-plan=JSON|@file.json`` installs a deterministic fault-injection
plan (``utils/faults.py``) for manual chaos drills.

Observability (any pipeline, and serve — docs/observability.md):
``--trace=DIR`` runs the invocation under the obs plane's tracer and
writes ``DIR/trace.json`` (Perfetto-loadable), ``DIR/events.jsonl``
(the compact log ``bin/trace`` summarizes), and ``DIR/meta.json`` —
one correlated record of optimizer decisions, fold chunks, IO lane
tasks, checkpoint writes, and serving requests under one ``run_id``.
Serve additionally has the LIVE plane: ``--slo-p99-ms`` declares a p99
latency SLO (the summary line prints the OK/WARN/BREACH verdict and
budget spent), ``--metrics-port``/``--metrics-dir`` publish Prometheus
text + atomic JSON snapshots while the server runs (``bin/slo`` renders
them), and ``KEYSTONE_TRACE_SAMPLE``/``KEYSTONE_TRACE_SLOW_MS``
tail-sample traced serving spans. ``--autoscale`` (with
``--min-replicas``/``--max-replicas``/``--scale-cooldown-s``) closes
the loop: an autoscaler thread consumes the SLO burn-rate state machine
and drives zero-drop replica add/remove — and past the ceiling, the
brownout admission ladder; the summary line reports
``replicas_low/high``, ``scale_ups``, ``scale_downs``, and
``brownout_steps_entered``, and ``bin/slo`` renders the autoscale
decision log beside the verdict table (docs/serving.md).

``python -m keystone_tpu.run learn --publish-every-k 4 --rate 300
--duration-s 8`` runs the continuous-learning closed loop
(docs/reliability.md model-publication contract): a ContinuousTrainer
incrementally re-fits over arriving synthetic segments (checkpoint/
resume-capable via ``--checkpoint-dir``) while the replicated plane
takes live Poisson traffic, publishing every K segments through the
LifecycleController's validation gate → canary → promote/rollback
path. The summary line carries
``published/rejected/rollbacks/canary_promotions`` and the measured
model ``staleness_s`` beside the serving percentiles; ``bin/slo``
renders the lifecycle decision log and staleness next to the SLO
verdict tables. Exits with the serve contract's one-line diagnostic on
failure.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Dict


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache for pipeline runs.

    Example pipelines compile dozens of programs (per image scale, per
    solver block); caching them across runs matters most on backends where
    compilation is remote/slow. Default dir ~/.cache/keystone_tpu_xla;
    disable with KEYSTONE_COMPILE_CACHE=0 or point it elsewhere.
    """
    setting = os.environ.get("KEYSTONE_COMPILE_CACHE", "")
    if setting == "0":
        return
    cache_dir = setting or os.path.join(
        os.path.expanduser("~"), ".cache", "keystone_tpu_xla"
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization; never block the run on it


def _mnist(argv):
    from keystone_tpu.pipelines import mnist_random_fft

    mnist_random_fft.main(argv)


def _timit(argv):
    from keystone_tpu.pipelines import timit

    timit.main(argv)


def _cifar(variant):
    def runner(argv):
        from keystone_tpu.pipelines import cifar

        cifar.main(argv, variant=variant)

    return runner


def _voc(argv):
    from keystone_tpu.pipelines import voc_sift_fisher

    voc_sift_fisher.main(argv)


def _imagenet(argv):
    from keystone_tpu.pipelines import imagenet_sift_lcs_fv

    imagenet_sift_lcs_fv.main(argv)


def _amazon(argv):
    from keystone_tpu.pipelines import amazon_reviews

    amazon_reviews.main(argv)


def _newsgroups(argv):
    from keystone_tpu.pipelines import newsgroups

    newsgroups.main(argv)


def _stupid_backoff(argv):
    from keystone_tpu.pipelines import stupid_backoff

    stupid_backoff.main(argv)


def _serve(argv):
    """``--serve`` mode: load (or quick-fit) a pipeline, export the
    serving plan, start the micro-batch server, drive it with open-loop
    Poisson load, and print the percentile summary line (docs/serving.md).

    ``python -m keystone_tpu.run serve --model fitted.pkl --input-dim 784``
    serves a saved FittedPipeline; without ``--model`` it fits the named
    ``--pipeline`` (MnistRandomFFT) on synthetic data first.
    """
    import argparse
    import json

    parser = argparse.ArgumentParser("keystone-serve")
    parser.add_argument("--model", default="", help="FittedPipeline pickle")
    parser.add_argument("--pipeline", default="MnistRandomFFT",
                        help="pipeline to quick-fit when no --model is given")
    parser.add_argument("--input-dim", type=int, default=784)
    parser.add_argument("--numFFTs", type=int, default=4)
    parser.add_argument("--blockSize", type=int, default=2048)
    parser.add_argument("--fit-n", type=int, default=4096)
    parser.add_argument("--max-batch", type=int, default=256)
    parser.add_argument("--max-wait-ms", type=float, default=5.0)
    parser.add_argument("--queue-depth", type=int, default=1024)
    parser.add_argument("--replicas", type=int, default=1,
                        help="serve through a ReplicatedServer with this "
                        "many replicas (1 = single MicroBatchServer)")
    parser.add_argument("--restart-budget", type=int, default=3,
                        help="replica respawn attempts before permanent "
                        "eviction (with --replicas > 1)")
    parser.add_argument("--autoscale", action="store_true",
                        help="close the SLO loop: an Autoscaler thread "
                        "drives replica add/remove (and the brownout "
                        "ladder past --max-replicas) from the declared "
                        "SLO's burn-rate state machine; requires "
                        "--slo-p99-ms > 0 (docs/serving.md autoscaler "
                        "section)")
    parser.add_argument("--min-replicas", type=int, default=1,
                        help="autoscaler floor (with --autoscale)")
    parser.add_argument("--max-replicas", type=int, default=8,
                        help="autoscaler ceiling; past it admission "
                        "degrades down the brownout ladder "
                        "(with --autoscale)")
    parser.add_argument("--scale-cooldown-s", type=float, default=2.0,
                        help="minimum spacing between any two autoscale "
                        "actions — the no-flapping window "
                        "(with --autoscale)")
    parser.add_argument("--tenants", type=int, default=1,
                        help="serve N tenants through the multi-tenant "
                        "model zoo (each tenant gets its own exported "
                        "plan, SLO tracker, and fair admission share; "
                        "--rate is split uniformly across tenants) — "
                        "docs/serving.md model-zoo section")
    parser.add_argument("--tenant-spec", default="",
                        help="JSON tenant spec file: {\"tenants\": "
                        "[{\"id\": \"a\", \"weight\": 1.0, \"rate_hz\": "
                        "100}, ...]} — overrides --tenants/--rate with "
                        "a skewed per-tenant mix")
    parser.add_argument("--zoo-budget-mb", type=float, default=0.0,
                        help="device-memory budget for the zoo's "
                        "resident weights (0 = size to fit every "
                        "tenant; a binding budget exercises paging)")
    parser.add_argument("--rate", type=float, default=200.0,
                        help="offered Poisson rate (requests/s)")
    parser.add_argument("--duration-s", type=float, default=5.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slo-p99-ms", type=float, default=0.0,
                        help="declare a p99 latency SLO objective at this "
                        "bound (plus an availability objective); the "
                        "summary line then carries the live verdict and "
                        "budget spent (0 = no SLO)")
    parser.add_argument("--slo-target", type=float, default=0.99,
                        help="good-fraction target of the latency "
                        "objective (error budget = 1 - target)")
    parser.add_argument("--metrics-port", type=int, default=-1,
                        help="serve Prometheus text-format + JSON "
                        "snapshots over HTTP on this port (0 = ephemeral, "
                        "-1 = off) — docs/observability.md live plane")
    parser.add_argument("--metrics-dir", default="",
                        help="write atomic live_metrics.json snapshots "
                        "here every --metrics-interval-s (scrape-less "
                        "environments; bin/slo reads them)")
    parser.add_argument("--metrics-interval-s", type=float, default=1.0)
    parser.add_argument("--from-plan", default="", metavar="PATH",
                        help="consume a bin/plan --apply defaults "
                        "artifact: its measured-baseline knobs "
                        "(replicas, queue depth, SLO bound) fill in "
                        "any flag left at its default, and the summary "
                        "line stamps the artifact's provenance "
                        "(docs/placement.md planner cookbook)")
    parser.add_argument("--fleet", type=int, default=1,
                        help="serve through a FleetRouter fronting N "
                        "crash-contained plane PROCESSES (each plane = "
                        "a full ReplicatedServer with --replicas "
                        "replicas); process kills are survived with "
                        "exact books (docs/serving.md fleet section)")
    args = parser.parse_args(argv)

    import numpy as np

    from keystone_tpu import obs
    from keystone_tpu.serving import (
        Autoscaler,
        MicroBatchServer,
        ReplicatedServer,
        export_plan,
        run_open_loop,
    )

    plan_stamp = None
    if args.from_plan:
        try:
            plan_stamp = _serve_apply_plan_defaults(args, parser)
        except (OSError, ValueError, KeyError) as e:
            print(
                f"serve: --from-plan failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 2

    if args.autoscale and args.slo_p99_ms <= 0:
        print(
            "serve: --autoscale needs a declared SLO objective "
            "(--slo-p99-ms > 0) — the control loop consumes the "
            "burn-rate state machine",
            file=sys.stderr,
        )
        return 2
    if args.autoscale and not 1 <= args.min_replicas <= args.max_replicas:
        # Validate BEFORE any server threads start: a ValueError out of
        # Autoscaler.__init__ after ReplicatedServer construction would
        # leak running workers and violate the one-line-diagnostic
        # contract above.
        print(
            f"serve: need 1 <= --min-replicas ({args.min_replicas}) <= "
            f"--max-replicas ({args.max_replicas})",
            file=sys.stderr,
        )
        return 2

    if args.fleet < 1:
        print(f"serve: need --fleet >= 1 (got {args.fleet})",
              file=sys.stderr)
        return 2
    if args.fleet > 1 and args.autoscale:
        print(
            "serve: --fleet and --autoscale are mutually exclusive "
            "(the fleet's planes do their own admission; router-level "
            "elasticity is ROADMAP work)",
            file=sys.stderr,
        )
        return 2

    tenant_specs = _serve_tenant_specs(args)
    if tenant_specs is not None and args.fleet > 1:
        print(
            "serve: --fleet and --tenants/--tenant-spec are mutually "
            "exclusive (the zoo's multi-tenant plane is in-process)",
            file=sys.stderr,
        )
        return 2
    if tenant_specs is not None and args.autoscale:
        print(
            "serve: --tenants/--tenant-spec and --autoscale are "
            "mutually exclusive (the zoo's admission plane does its own "
            "per-tenant degradation)",
            file=sys.stderr,
        )
        return 2

    # Load/fit and export fail as a ONE-LINE diagnostic + non-zero exit,
    # not a bare traceback: serve is the operator-facing entry point, and
    # a supervisor restarting it needs the exit code, not a stack.
    phase = "load" if args.model else "quick-fit"
    if tenant_specs is not None:
        try:
            fitted, d_in = _serve_build_fitted(args)
        except SystemExit:
            raise
        except Exception as e:
            print(
                f"serve: {phase} failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 1
        return _serve_zoo(args, fitted, d_in, tenant_specs,
                          plan_stamp=plan_stamp)
    try:
        fitted, d_in = _serve_build_fitted(args)
        phase = "export"
        plan = export_plan(
            fitted, np.zeros(d_in, np.float32), max_batch=args.max_batch
        )
    except SystemExit:
        raise
    except Exception as e:
        print(
            f"serve: {phase} failed: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 1
    single_s = plan.measure_single_request_s()
    rng = np.random.default_rng(args.seed + 1)
    pool = rng.normal(size=(256, d_in)).astype(np.float32)

    if args.fleet > 1:
        return _serve_fleet(args, fitted, plan, single_s, pool,
                            plan_stamp)

    # Live SLO objectives (docs/observability.md): a p99 latency bound
    # plus availability, publishing slo.state/burn gauges into their
    # own registry so the exporter renders them beside the serving
    # counters (the verdict block additionally carries the numeric
    # state_level the Prometheus renderer keeps).
    slo_tracker = None
    slo_registry = None
    if args.slo_p99_ms > 0:
        slo_registry = obs.MetricsRegistry()
        slo_tracker = obs.SLOTracker([
            obs.SLOObjective(
                "latency", kind="latency",
                threshold_s=args.slo_p99_ms / 1e3, target=args.slo_target,
            ),
            obs.SLOObjective(
                "availability", kind="availability", target=0.999,
            ),
        ], metrics=slo_registry)
    if args.replicas > 1 or args.autoscale:
        # Autoscale always rides the replicated plane (the elasticity
        # primitives live there), starting inside the configured bounds.
        n0 = args.replicas
        if args.autoscale:
            n0 = min(max(n0, args.min_replicas), args.max_replicas)
        server = ReplicatedServer(
            plan, num_replicas=n0, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms, max_queue_depth=args.queue_depth,
            restart_budget=args.restart_budget, slo=slo_tracker,
        )
    else:
        server = MicroBatchServer(
            plan, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue_depth=args.queue_depth, slo=slo_tracker,
        )
    autoscaler = None
    exporter = None
    try:
        # Inside the try: from here on, any construction failure must
        # still close() the already-running server threads.
        if args.autoscale:
            autoscaler = Autoscaler(
                server, slo_tracker,
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                cooldown_s=args.scale_cooldown_s,
                metrics=server.metrics,
            ).start()
        if args.metrics_port >= 0 or args.metrics_dir:
            from keystone_tpu.data.runtime import default_runtime

            sources = {
                "metrics": server.metrics,
                "serving": server.stats,
                "runtime": default_runtime().stats,
            }
            if slo_registry is not None:
                sources["slo_metrics"] = slo_registry
            if autoscaler is not None:
                # bin/slo renders this block (decision log + scale
                # counters) beside the SLO verdict table.
                sources["autoscale"] = autoscaler.stats
            exporter = obs.LiveExporter(
                sources=sources,
                slo=slo_tracker,
                snapshot_dir=args.metrics_dir or None,
                port=args.metrics_port if args.metrics_port >= 0 else None,
                interval_s=args.metrics_interval_s,
            )
        report = run_open_loop(
            server.submit, lambda i: pool[i % len(pool)],
            rate_hz=args.rate, duration_s=args.duration_s, seed=args.seed,
            slo=slo_tracker,
        )
        stats = server.stats()
    finally:
        if autoscaler is not None:
            autoscaler.close()
        if exporter is not None:
            exporter.close()
        server.close()
    summary = report.to_row_dict()
    summary.update({
        "single_request_s": round(single_s, 6),
        "buckets": plan.buckets,
        "plan_compiled": plan.compiled,
        "max_wait_ms": args.max_wait_ms,
        "plan_fingerprint": plan.fingerprint,
    })
    if plan_stamp is not None:
        summary["plan_artifact"] = plan_stamp
    if slo_tracker is not None:
        # The verdict and the budget, on the one line an operator reads.
        verdict = report.slo or slo_tracker.verdict()
        summary.update({
            "slo_state": verdict["state"],
            "slo_budget_spent_fraction": max(
                o["budget_spent_fraction"]
                for o in verdict["objectives"].values()
            ),
        })
    if exporter is not None and exporter.port is not None:
        summary["metrics_port"] = exporter.port
    if autoscaler is not None:
        a_stats = autoscaler.stats()
        summary.update({
            "replicas_low": a_stats["replicas_low"],
            "replicas_high": a_stats["replicas_high"],
            "scale_ups": a_stats["scale_ups"],
            "scale_downs": a_stats["scale_downs"],
            "brownout_steps_entered": a_stats["brownout_steps_entered"],
            # The audit companions the bench row rule requires beside
            # any scale_ups/scale_downs claim.
            "num_decisions": a_stats["num_decisions"],
            "min_replicas": a_stats["min_replicas"],
            "max_replicas": a_stats["max_replicas"],
        })
    if args.replicas > 1 or args.autoscale:
        summary.update({
            "replicas": stats.get("num_replicas"),
            "healthy_replicas": stats.get("healthy_replicas"),
            "restarts_total": stats.get("restarts_total"),
            "evicted_replicas": stats.get("evicted_replicas"),
            "degraded": stats.get("degraded"),
        })
    else:
        summary.update({
            "mean_pad_fraction": stats.get("mean_pad_fraction"),
            "breaker_state": stats.get("breaker_state"),
        })
    print(json.dumps(summary))
    return 0


def _serve_apply_plan_defaults(args, parser):
    """Consume a ``bin/plan --apply`` artifact: every serve flag the
    operator left at its parser default is filled from the artifact's
    measured-baseline ``serve_defaults`` block (an explicit flag always
    wins — the operator outranks the planner). Returns the provenance
    stamp the serve summary line carries, so the plane's configuration
    is auditable back to the trace it was sized from."""
    import json

    from keystone_tpu.tools.plan import PLAN_ARTIFACT_KIND

    with open(args.from_plan) as f:
        doc = json.load(f)
    if doc.get("artifact") != PLAN_ARTIFACT_KIND:
        raise ValueError(
            f"{args.from_plan!r} is not a bin/plan --apply artifact "
            f"(artifact={doc.get('artifact')!r})"
        )
    applied = {}
    for key, value in sorted(doc["serve_defaults"].items()):
        if not hasattr(args, key):
            continue
        if getattr(args, key) == parser.get_default(key):
            setattr(args, key, value)
            applied[key] = value
    return {
        "path": args.from_plan,
        "applied": applied,
        "source_traces": doc.get("source_traces", []),
        "fidelity_max_abs_log_error": doc.get("fidelity", {}).get(
            "max_abs_log_error"
        ),
        "written_at_unix_s": doc.get("written_at_unix_s"),
    }


def _serve_fleet(args, fitted, plan, single_s, pool, plan_stamp):
    """``serve --fleet N``: the exported plan shipped (split-plane
    encoded, fingerprint-verified on arrival) to N crash-contained
    plane PROCESSES behind the FleetRouter's admission door, driven
    with the same open-loop Poisson storm, summarized with the fleet's
    exact books (docs/serving.md fleet section)."""
    import json

    from keystone_tpu.serving import run_open_loop
    from keystone_tpu.serving.fleet import FleetRouter
    from keystone_tpu.serving.fleet_plane import encode_plan_ship

    try:
        ship = encode_plan_ship(fitted, plan)
    except Exception as e:  # noqa: BLE001 — one-line serve contract
        print(
            f"serve: plan ship encode failed: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 1
    fleet = FleetRouter(
        ship,
        num_planes=args.fleet,
        replicas_per_plane=max(1, args.replicas),
        max_outstanding=args.queue_depth,
        restart_budget=args.restart_budget,
        plane_cfg={
            "max_wait_ms": args.max_wait_ms,
            "max_queue_depth": args.queue_depth,
        },
    )
    try:
        report = run_open_loop(
            fleet.submit, lambda i: pool[i % len(pool)],
            rate_hz=args.rate, duration_s=args.duration_s,
            seed=args.seed,
        )
        stats = fleet.stats()
        books_ok = fleet.accounting_ok()
    finally:
        fleet.close()
    summary = report.to_row_dict()
    summary.update({
        "single_request_s": round(single_s, 6),
        "buckets": plan.buckets,
        "plan_fingerprint": plan.fingerprint,
        "max_wait_ms": args.max_wait_ms,
        "num_planes": stats["num_planes"],
        "replicas_per_plane": max(1, args.replicas),
        "healthy_planes": stats["healthy_planes"],
        "evicted_planes": stats["evicted_planes"],
        "quarantined_planes": stats["quarantined_planes"],
        "restarts_total": stats["restarts_total"],
        "aggregate_offered": stats["aggregate_offered"],
        "fleet_completed": stats["completed"],
        "fleet_rejected": stats["rejected"],
        "fleet_failed": stats["failed"],
        "fleet_p99_latency_s": stats["fleet_p99_latency_s"],
        "planes": stats["planes"],
        "fleet_accounting_ok": books_ok,
    })
    if plan_stamp is not None:
        summary["plan_artifact"] = plan_stamp
    print(json.dumps(summary))
    if not books_ok:
        # The fleet invariant is the contract this mode exists for —
        # a summary with unbalanced books must not exit 0.
        print(
            "serve: fleet books do NOT balance (offered "
            f"{stats['aggregate_offered']} != completed "
            f"{stats['completed']} + rejected {stats['rejected']} + "
            f"failed {stats['failed']})",
            file=sys.stderr,
        )
        return 1
    return 0


def _learn(argv):
    """``learn`` mode: the continuous-learning closed loop — a
    ContinuousTrainer re-fitting over arriving synthetic segments while
    the replicated plane serves live Poisson traffic, every candidate
    publishing through the lifecycle gate → canary → promote/rollback
    path (docs/reliability.md). Prints one summary line with the
    publication counters and measured model staleness; exits non-zero
    with a one-line diagnostic on failure (the serve contract)."""
    import argparse
    import json

    parser = argparse.ArgumentParser("keystone-learn")
    parser.add_argument("--input-dim", type=int, default=16)
    parser.add_argument("--out-dim", type=int, default=4)
    parser.add_argument("--segments", type=int, default=24,
                        help="how many shard segments arrive over the run")
    parser.add_argument("--segment-rows", type=int, default=256)
    parser.add_argument("--arrival-spread-s", type=float, default=-1.0,
                        help="segments arrive uniformly over this window "
                        "(default: 60%% of --duration-s)")
    parser.add_argument("--publish-every-k", type=int, default=4,
                        help="trainer publishes a candidate every K "
                        "segments (the final segment always publishes)")
    parser.add_argument("--quality-bound", type=float, default=0.05,
                        help="max held-out score regression a candidate "
                        "may show vs the incumbent before the gate "
                        "rejects it")
    parser.add_argument("--canary-sustain-s", type=float, default=1.0,
                        help="canary window before full promotion "
                        "(0 disables the canary)")
    parser.add_argument("--canary-latency-factor", type=float, default=3.0)
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--max-wait-ms", type=float, default=2.0)
    parser.add_argument("--queue-depth", type=int, default=1024)
    parser.add_argument("--replicas", type=int, default=2,
                        help="replicated-plane size (>= 2 so the canary "
                        "has incumbents to compare against)")
    parser.add_argument("--restart-budget", type=int, default=3)
    parser.add_argument("--rate", type=float, default=200.0)
    parser.add_argument("--duration-s", type=float, default=8.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slo-p99-ms", type=float, default=0.0)
    parser.add_argument("--slo-target", type=float, default=0.99)
    parser.add_argument("--metrics-port", type=int, default=-1)
    parser.add_argument("--metrics-dir", default="")
    parser.add_argument("--metrics-interval-s", type=float, default=1.0)
    args = parser.parse_args(argv)

    import numpy as np

    from keystone_tpu import obs
    from keystone_tpu.learning import ContinuousTrainer, TimedSegmentFeed
    from keystone_tpu.serving import (
        LifecycleController,
        ReplicatedServer,
        export_plan,
        run_open_loop,
    )

    if args.replicas < 1:
        print("learn: --replicas must be >= 1", file=sys.stderr)
        return 2

    # Synthesize / fit / export fail as a ONE-LINE diagnostic + non-zero
    # exit (the serve contract — learn is operator-facing too).
    phase = "synthesize"
    try:
        d, k = args.input_dim, args.out_dim
        rng = np.random.default_rng(args.seed)
        W_true = rng.normal(size=(d, k)).astype(np.float32)
        def segment(n):
            X = rng.normal(size=(n, d)).astype(np.float32)
            y = (X @ W_true
                 + 0.01 * rng.normal(size=(n, k))).astype(np.float32)
            return X, y
        segments = [segment(args.segment_rows)
                    for _ in range(args.segments)]
        holdout = segment(4 * args.segment_rows)
        phase = "quick-fit"
        from keystone_tpu.ops.learning.linear import LinearMapper
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            TransformerGraph,
        )

        X0, y0 = segments[0]
        X64 = X0.astype(np.float64)
        W0 = np.linalg.solve(
            X64.T @ X64 + 1e-3 * np.eye(d), X64.T @ y0.astype(np.float64)
        ).astype(np.float32)
        pipe0 = LinearMapper(W0).to_pipeline()
        fitted0 = FittedPipeline(
            TransformerGraph.from_graph(pipe0.executor.graph),
            pipe0.source, pipe0.sink,
        )
        phase = "export"
        plan0 = export_plan(
            fitted0, np.zeros(d, np.float32), max_batch=args.max_batch
        )
    except SystemExit:
        raise
    except Exception as e:
        print(f"learn: {phase} failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 1

    slo_tracker = None
    slo_registry = None
    if args.slo_p99_ms > 0:
        slo_registry = obs.MetricsRegistry()
        slo_tracker = obs.SLOTracker([
            obs.SLOObjective(
                "latency", kind="latency",
                threshold_s=args.slo_p99_ms / 1e3, target=args.slo_target,
            ),
            obs.SLOObjective(
                "availability", kind="availability", target=0.999,
            ),
        ], metrics=slo_registry)

    spread = (args.arrival_spread_s if args.arrival_spread_s >= 0
              else 0.6 * args.duration_s)
    offsets = [spread * i / max(args.segments - 1, 1)
               for i in range(args.segments)]
    server = ReplicatedServer(
        plan0, num_replicas=args.replicas, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue_depth=args.queue_depth,
        restart_budget=args.restart_budget, slo=slo_tracker,
    )
    controller = None
    trainer = None
    exporter = None
    try:
        controller = LifecycleController(
            server, plan0, holdout=holdout,
            quality_bound=args.quality_bound,
            canary_sustain_s=args.canary_sustain_s,
            canary_latency_factor=args.canary_latency_factor,
            slo=slo_tracker,
        ).start()
        feed = TimedSegmentFeed(segments, arrival_offsets=offsets)
        # --checkpoint-dir (KEYSTONE_CHECKPOINT_DIR) flows through
        # checkpoint=None exactly like the streamed solvers.
        trainer = ContinuousTrainer(
            feed, controller, publish_every_k=args.publish_every_k,
        )
        if args.metrics_port >= 0 or args.metrics_dir:
            from keystone_tpu.data.runtime import default_runtime

            sources = {
                "metrics": server.metrics,
                "serving": server.stats,
                "lifecycle": controller.stats,
                "trainer": trainer.stats,
                "runtime": default_runtime().stats,
            }
            if slo_registry is not None:
                sources["slo_metrics"] = slo_registry
            exporter = obs.LiveExporter(
                sources=sources,
                slo=slo_tracker,
                snapshot_dir=args.metrics_dir or None,
                port=args.metrics_port if args.metrics_port >= 0 else None,
                interval_s=args.metrics_interval_s,
            )
        trainer.start()
        rng_req = np.random.default_rng(args.seed + 1)
        pool = rng_req.normal(size=(256, d)).astype(np.float32)
        report = run_open_loop(
            server.submit, lambda i: pool[i % len(pool)],
            rate_hz=args.rate, duration_s=args.duration_s,
            seed=args.seed, slo=slo_tracker,
        )
        trainer.join(timeout=60.0)
        controller.poll()  # settle the last staleness clock
        lc_stats = controller.stats()
        tr_stats = trainer.stats()
        stats = server.stats()
    finally:
        if trainer is not None:
            trainer.stop()
        if controller is not None:
            controller.close()
        if exporter is not None:
            exporter.close()
        server.close()
    if trainer.error is not None:
        print(
            f"learn: trainer died mid-fit: "
            f"{type(trainer.error).__name__}: {trainer.error} — "
            "re-run with the same --checkpoint-dir to resume",
            file=sys.stderr,
        )
        return 1
    summary = report.to_row_dict()
    # The lifecycle claims (staleness*/rollbacks) ride in the SAME dict
    # as num_published and the offered rate — the make_row audit shape.
    summary.update({
        "published": lc_stats["published"],
        "num_published": lc_stats["num_published"],
        # NOT "rejected": that key is the LOAD accounting (sheds) from
        # the report above; gate rejections are a different book.
        "gate_rejected": lc_stats["rejected"],
        "rollbacks": lc_stats["rollbacks"],
        "canary_promotions": lc_stats["canary_promotions"],
        "staleness_s": lc_stats["staleness_s"],
        "staleness_median_s": lc_stats["staleness_median_s"],
        "trainer_segments_fit": tr_stats["segments_fit"],
        "trainer_resumes": tr_stats["resumes"],
        "incumbent_fingerprint": lc_stats["incumbent_fingerprint"],
        "replicas": stats.get("num_replicas"),
        "healthy_replicas": stats.get("healthy_replicas"),
        "accounting_ok": (
            report.num_offered
            == report.completed + report.rejected + report.failed
        ),
    })
    if slo_tracker is not None:
        verdict = report.slo or slo_tracker.verdict()
        summary.update({
            "slo_state": verdict["state"],
            "slo_budget_spent_fraction": max(
                o["budget_spent_fraction"]
                for o in verdict["objectives"].values()
            ),
        })
    if exporter is not None and exporter.port is not None:
        summary["metrics_port"] = exporter.port
    print(json.dumps(summary))
    return 0


def _serve_build_fitted(args):
    """(fitted, d_in) for serve mode: load a saved FittedPipeline or
    quick-fit the named pipeline on synthetic data."""
    import numpy as np

    from keystone_tpu.workflow.pipeline import FittedPipeline

    if args.model:
        return FittedPipeline.load(args.model), args.input_dim
    if args.pipeline.rsplit(".", 1)[-1] == "MnistRandomFFT":
        import jax.numpy as jnp

        from keystone_tpu.data import Dataset
        from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
        from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
        from keystone_tpu.pipelines.mnist_random_fft import (
            MnistRandomFFTConfig,
            build_featurizer,
        )

        d_in = args.input_dim
        rng = np.random.default_rng(args.seed)
        X = jnp.asarray(rng.normal(size=(args.fit_n, d_in)).astype(np.float32))
        y = rng.integers(0, 10, size=args.fit_n)
        labels = ClassLabelIndicatorsFromIntLabels(10)(
            Dataset.of(jnp.asarray(y))
        )
        cfg = MnistRandomFFTConfig(
            num_ffts=args.numFFTs, block_size=args.blockSize, image_size=d_in
        )
        fitted = build_featurizer(cfg).and_then(
            BlockLeastSquaresEstimator(args.blockSize, 1, 1e-3),
            Dataset.of(X), labels,
        ).fit()
        return fitted, d_in
    raise SystemExit(
        f"--serve quick-fit supports MnistRandomFFT (got "
        f"{args.pipeline!r}); pass --model for anything else"
    )


def _serve_tenant_specs(args):
    """``[{"id", "weight", "rate_hz"}, ...]`` from --tenant-spec (the
    skewed-mix form) or --tenants N (uniform — --rate split evenly);
    None when serve should run the single-tenant path."""
    import json

    if args.tenant_spec:
        with open(args.tenant_spec) as f:
            doc = json.load(f)
        specs = doc.get("tenants") if isinstance(doc, dict) else doc
        if not isinstance(specs, list) or not specs:
            raise SystemExit(
                f"--tenant-spec {args.tenant_spec!r}: expected "
                '{"tenants": [{"id": ..., "weight": ..., "rate_hz": '
                "...}, ...]}"
            )
        return [
            {
                "id": str(s["id"]),
                "weight": float(s.get("weight", 1.0)),
                "rate_hz": float(s.get("rate_hz", args.rate / len(specs))),
            }
            for s in specs
        ]
    if args.tenants > 1:
        return [
            {
                "id": f"t{i}",
                "weight": 1.0,
                "rate_hz": args.rate / args.tenants,
            }
            for i in range(args.tenants)
        ]
    return None


def _serve_zoo(args, fitted, d_in, tenant_specs, plan_stamp=None):
    """Multi-tenant serve: one zoo, one exported plan per tenant (the
    fitted pipeline is cloned per tenant — paging mutates operator
    state in place, so tenants must never share operator objects), a
    per-tenant SLO tracker when an SLO is declared, skewed open-loop
    Poisson load, and a summary line with the per-tenant verdicts plus
    the zoo's paging/quarantine/cold-start counters."""
    import json
    import pickle

    import numpy as np

    from keystone_tpu import obs
    from keystone_tpu.serving import (
        ModelZoo,
        export_plan,
        run_multi_tenant_open_loop,
    )

    names = [s["id"] for s in tenant_specs]
    if len(set(names)) != len(names):
        print(f"serve: duplicate tenant ids: {names}", file=sys.stderr)
        return 2

    slos = {}
    if args.slo_p99_ms > 0:
        # NO shared registry across trackers: every tracker would
        # register the SAME (slo.*, objective=) gauge keys and stomp
        # each other last-writer-wins. The per-tenant verdicts ride the
        # zoo's stats block (the "zoo" exporter source below), which is
        # what bin/slo's tenant table renders.
        for name in names:
            slos[name] = obs.SLOTracker([
                obs.SLOObjective(
                    "latency", kind="latency",
                    threshold_s=args.slo_p99_ms / 1e3,
                    target=args.slo_target,
                ),
                obs.SLOObjective(
                    "availability", kind="availability", target=0.999,
                ),
            ])

    plans = {}
    try:
        for spec in tenant_specs:
            # Clone per tenant: pickle round trip (the documented
            # FittedPipeline copy path — compile caches rebuild lazily).
            clone = pickle.loads(pickle.dumps(fitted))
            plans[spec["id"]] = export_plan(
                clone, np.zeros(d_in, np.float32), max_batch=args.max_batch
            )
    except Exception as e:
        print(
            f"serve: tenant export failed: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 1

    per_tenant_bytes = {
        name: max(p.pinned_bytes, 1) for name, p in plans.items()
    }
    budget = (
        int(args.zoo_budget_mb * (1 << 20)) if args.zoo_budget_mb > 0
        else sum(per_tenant_bytes.values()) + len(plans)
    )
    zoo = ModelZoo(
        budget_bytes=budget,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        max_queue_depth=args.queue_depth,
    )
    exporter = None
    try:
        for spec in tenant_specs:
            zoo.add_tenant(
                spec["id"], plans[spec["id"]], weight=spec["weight"],
                slo=slos.get(spec["id"]),
            )
        if args.metrics_port >= 0 or args.metrics_dir:
            from keystone_tpu.data.runtime import default_runtime

            sources = {
                "metrics": zoo.metrics,
                "zoo": zoo.stats,
                "runtime": default_runtime().stats,
            }
            exporter = obs.LiveExporter(
                sources=sources,
                snapshot_dir=args.metrics_dir or None,
                port=args.metrics_port if args.metrics_port >= 0 else None,
                interval_s=args.metrics_interval_s,
            )
        rng = np.random.default_rng(args.seed + 1)
        pool = rng.normal(size=(256, d_in)).astype(np.float32)
        report = run_multi_tenant_open_loop(
            zoo.submit,
            lambda tenant, i: pool[i % len(pool)],
            rates_hz={s["id"]: s["rate_hz"] for s in tenant_specs},
            duration_s=args.duration_s, seed=args.seed,
            slos=slos or None,
        )
        stats = zoo.stats()
    finally:
        if exporter is not None:
            exporter.close()
        zoo.close()
    summary = report.to_row_dict()
    # The summary line keeps the per-tenant report blocks under
    # ``per_tenant``; ``tenants`` is the headline COUNT (the satellite
    # counters an operator greps for).
    summary["per_tenant"] = summary.pop("tenants")
    summary.update({
        "tenants": stats["num_tenants"],
        "residents": stats["residents"],
        "quarantined": stats["quarantined"],
        "coldstart_failfast": stats["coldstart_failfast"],
        "page_ins": stats["page_ins"],
        "page_outs": stats["page_outs"],
        "zoo_budget_bytes": stats["budget_bytes"],
        "accounting_ok": stats["accounting_ok"]
        and report.accounting_ok(),
    })
    if slos:
        summary["tenant_slo_states"] = report.tenant_states()
    if exporter is not None and exporter.port is not None:
        summary["metrics_port"] = exporter.port
    if plan_stamp is not None:
        summary["plan_artifact"] = plan_stamp
    print(json.dumps(summary))
    return 0


PIPELINES: Dict[str, Callable] = {
    "MnistRandomFFT": _mnist,
    "TimitPipeline": _timit,
    "Timit": _timit,
    "LinearPixels": _cifar("LinearPixels"),
    "RandomCifar": _cifar("RandomCifar"),
    "RandomPatchCifar": _cifar("RandomPatchCifar"),
    "RandomPatchCifarKernel": _cifar("RandomPatchCifarKernel"),
    "RandomPatchCifarAugmented": _cifar("RandomPatchCifarAugmented"),
    "VOCSIFTFisher": _voc,
    "ImageNetSiftLcsFV": _imagenet,
    "AmazonReviewsPipeline": _amazon,
    "NewsgroupsPipeline": _newsgroups,
    "StupidBackoffPipeline": _stupid_backoff,
}


def resolve(name: str) -> Callable:
    """Accept bare or fully-qualified (dotted) pipeline names."""
    bare = name.rsplit(".", 1)[-1]
    if bare not in PIPELINES:
        known = ", ".join(sorted(PIPELINES))
        raise SystemExit(f"Unknown pipeline {name!r}. Known pipelines: {known}")
    return PIPELINES[bare]


# Global flags popped before any per-pipeline parser sees them; each
# becomes the env knob the library layer reads:
#   --host-budget-bytes=N  -> KEYSTONE_HOST_BUDGET_BYTES (cost.py: caps
#       host RAM a dataset claims before routing through disk shards)
#   --checkpoint-dir=DIR   -> KEYSTONE_CHECKPOINT_DIR (durable.py:
#       segmented streamed fits snapshot + resume their fold carry)
#   --fault-plan=JSON|@f   -> KEYSTONE_FAULT_PLAN (faults.py: install a
#       deterministic fault-injection plan for manual chaos drills)
#   --trace=DIR            -> KEYSTONE_TRACE (obs: run under the tracer,
#       write the Perfetto trace + event log to DIR)
_GLOBAL_FLAGS = {
    "--host-budget-bytes=": "KEYSTONE_HOST_BUDGET_BYTES",
    "--checkpoint-dir=": "KEYSTONE_CHECKPOINT_DIR",
    "--fault-plan=": "KEYSTONE_FAULT_PLAN",
    "--trace=": "KEYSTONE_TRACE",
}


def _extract_global_flags(argv):
    """Pop the global reliability/capacity flags (any pipeline, and
    serve) into their env knobs — per-pipeline flag parsers never see
    them, and the library layer picks them up with no plumbing."""
    out = []
    for a in argv:
        for prefix, env in _GLOBAL_FLAGS.items():
            if a.startswith(prefix):
                os.environ[env] = a.split("=", 1)[1]
                break
        else:
            out.append(a)
    return out


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("Pipelines:", ", ".join(sorted(PIPELINES)))
        return 0
    argv = _extract_global_flags(argv)
    if not argv:  # invocation was ONLY global flags — show help, no crash
        print(__doc__)
        print("Pipelines:", ", ".join(sorted(PIPELINES)))
        return 0
    _enable_compile_cache()
    # The whole invocation runs under the obs tracer when KEYSTONE_TRACE
    # (or --trace=DIR above) names a directory — one flag turns any
    # pipeline or serve run into a Perfetto-loadable causal record
    # (docs/observability.md); a no-op context otherwise.
    from keystone_tpu import obs

    with obs.tracing_from_env():
        if argv[0] in ("serve", "--serve"):
            return _serve(argv[1:])
        if argv[0] in ("learn", "--learn"):
            return _learn(argv[1:])
        runner = resolve(argv[0])
        runner(argv[1:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
