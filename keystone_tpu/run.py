"""CLI launcher: ``python -m keystone_tpu.run <PipelineName> --flags``
(reference: bin/run-pipeline.sh:1-55 — spark-submit wrapper resolving a
pipeline class name and forwarding flags).

Pipeline names accept the reference's fully-qualified class names
(``keystoneml.pipelines.images.mnist.MnistRandomFFT``) or the bare name.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Dict


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache for pipeline runs.

    Example pipelines compile dozens of programs (per image scale, per
    solver block); caching them across runs matters most on backends where
    compilation is remote/slow. Default dir ~/.cache/keystone_tpu_xla;
    disable with KEYSTONE_COMPILE_CACHE=0 or point it elsewhere.
    """
    setting = os.environ.get("KEYSTONE_COMPILE_CACHE", "")
    if setting == "0":
        return
    cache_dir = setting or os.path.join(
        os.path.expanduser("~"), ".cache", "keystone_tpu_xla"
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization; never block the run on it


def _mnist(argv):
    from keystone_tpu.pipelines import mnist_random_fft

    mnist_random_fft.main(argv)


def _timit(argv):
    from keystone_tpu.pipelines import timit

    timit.main(argv)


def _cifar(variant):
    def runner(argv):
        from keystone_tpu.pipelines import cifar

        cifar.main(argv, variant=variant)

    return runner


def _voc(argv):
    from keystone_tpu.pipelines import voc_sift_fisher

    voc_sift_fisher.main(argv)


def _imagenet(argv):
    from keystone_tpu.pipelines import imagenet_sift_lcs_fv

    imagenet_sift_lcs_fv.main(argv)


def _amazon(argv):
    from keystone_tpu.pipelines import amazon_reviews

    amazon_reviews.main(argv)


def _newsgroups(argv):
    from keystone_tpu.pipelines import newsgroups

    newsgroups.main(argv)


def _stupid_backoff(argv):
    from keystone_tpu.pipelines import stupid_backoff

    stupid_backoff.main(argv)


PIPELINES: Dict[str, Callable] = {
    "MnistRandomFFT": _mnist,
    "TimitPipeline": _timit,
    "Timit": _timit,
    "LinearPixels": _cifar("LinearPixels"),
    "RandomCifar": _cifar("RandomCifar"),
    "RandomPatchCifar": _cifar("RandomPatchCifar"),
    "RandomPatchCifarKernel": _cifar("RandomPatchCifarKernel"),
    "RandomPatchCifarAugmented": _cifar("RandomPatchCifarAugmented"),
    "VOCSIFTFisher": _voc,
    "ImageNetSiftLcsFV": _imagenet,
    "AmazonReviewsPipeline": _amazon,
    "NewsgroupsPipeline": _newsgroups,
    "StupidBackoffPipeline": _stupid_backoff,
}


def resolve(name: str) -> Callable:
    """Accept bare or fully-qualified (dotted) pipeline names."""
    bare = name.rsplit(".", 1)[-1]
    if bare not in PIPELINES:
        known = ", ".join(sorted(PIPELINES))
        raise SystemExit(f"Unknown pipeline {name!r}. Known pipelines: {known}")
    return PIPELINES[bare]


def _extract_host_budget(argv):
    """Pop the global ``--host-budget-bytes=N`` flag (any pipeline): caps
    the host RAM the capacity selector lets a dataset claim, past which
    fits route through disk shards (docs/data.md). Exported as the
    ``KEYSTONE_HOST_BUDGET_BYTES`` env knob ``cost.host_memory_bytes``
    reads, so per-pipeline flag parsers never see it."""
    out = []
    for a in argv:
        if a.startswith("--host-budget-bytes="):
            os.environ["KEYSTONE_HOST_BUDGET_BYTES"] = a.split("=", 1)[1]
        else:
            out.append(a)
    return out


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("Pipelines:", ", ".join(sorted(PIPELINES)))
        return 0
    argv = _extract_host_budget(argv)
    _enable_compile_cache()
    runner = resolve(argv[0])
    runner(argv[1:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
