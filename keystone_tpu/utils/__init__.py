from .images import (
    ImageMetadata,
    conv2d_valid,
    crop,
    flip_horizontal,
    flip_image,
    load_image,
    to_grayscale,
)

__all__ = [
    "ImageMetadata",
    "conv2d_valid",
    "crop",
    "flip_horizontal",
    "flip_image",
    "load_image",
    "to_grayscale",
]
