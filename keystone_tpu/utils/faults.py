"""Deterministic, seedable fault injection + retry policy (the chaos
substrate of the reliability layer, docs/reliability.md).

KeystoneML inherited fault tolerance from Spark's RDD lineage; the
TPU-native data plane here (disk shards, prefetch threads, a serving
worker) inherits nothing, so every recovery path must be *built* — and a
recovery path that was never executed is a recovery path that does not
work. This module makes executing them cheap and, critically,
REPLAYABLE: a :class:`FaultPlan` names the exact call sites and call
indices at which an ``IOError``, payload corruption, or latency spike
happens, so a chaos test that failed once fails identically forever.

Instrumented sites (each site counts its own calls, 0-based):

  - ``shard.load``    — one segment/field read inside the disk shard
                        classes (``data/shards.py``).
  - ``prefetch.read`` — one ``source.load`` on the Prefetcher's reader
                        thread (``data/prefetch.py``).
  - ``serving.execute`` — one batch execution inside the micro-batch
                        server's worker (``serving/batcher.py``).
  - ``serving.replica.execute`` — one batch execution on a replica
                        worker OUTSIDE the per-batch error guard
                        (``serving/replicas.py``): an injected error
                        here kills the whole replica worker (watchdog
                        + restart territory), not just one batch.
  - ``serving.replica.spawn`` — one replica (re)spawn attempt in the
                        replicated server's restart path; injected
                        errors burn the restart budget toward
                        permanent eviction.
  - ``serving.autoscale.spawn`` — one scale-up spawn attempt in
                        ``ReplicatedServer.add_replica``
                        (``serving/replicas.py``): injected errors are
                        absorbed by bounded retries within the restart
                        budget, so chaos tests can kill an autoscaler's
                        scale-up mid-flight and prove elasticity stays
                        zero-drop.
  - ``checkpoint.write`` — one snapshot write inside
                        ``CheckpointSpec.save`` (``data/durable.py``)
                        — fires on the write-behind runtime worker
                        (or inline for a synchronous spec), so chaos
                        tests can kill/fail/delay a snapshot while the
                        fold keeps running.
  - ``serving.zoo.page_in`` — one paged-weight decode task on the model
                        zoo's page lane (``serving/zoo.py``): error
                        rules are absorbed by the zoo's bounded
                        RetryPolicy (exhaustion quarantines the
                        tenant), corrupt rules flip a byte of a stored
                        weight plane — the per-tensor CRCs must catch
                        it and quarantine, never serve.
  - ``serving.zoo.page_out`` — one weight encode task on the zoo's
                        page lane: an injected kill mid-encode must
                        leave the previous RESIDENT copy authoritative
                        (nothing is published until the encode
                        completes).
  - ``image.decode``  — one segment decode inside the image-tier shard
                        source (``data/images.py``): decompressing the
                        encoded bytes for every image of one segment on
                        the prefetcher's read lane. Injected errors
                        exercise the same bounded-retry path as
                        ``prefetch.read``; decode wall time is reported
                        to the active :func:`observing_retries` stats as
                        per-site busy time under ``"decode"``.
  - ``image.augment`` — one segment augmentation pass (deterministic
                        seeded crop/flip) in the image-tier shard
                        source, also on the read lane and also reported
                        as per-site busy time (``"augment"``).
  - ``trainer.fit``    — one segment fold inside the continuous
                        trainer's incremental re-fit loop
                        (``learning/continuous.py``): an injected error
                        kills the trainer mid-fit — the chaos suite
                        proves a restarted trainer resumes from its
                        checkpoint BIT-IDENTICALLY and still publishes.
  - ``lifecycle.validate`` — one candidate validation pass in the
                        publication gate (``serving/lifecycle.py``): an
                        injected error is a gate-infrastructure failure
                        — the candidate is rejected loudly (audited,
                        ``ok=False``) and the serving plane is never
                        touched.
  - ``lifecycle.publish`` — one canary/promotion swap attempt in the
                        lifecycle controller: an injected error fails
                        the publication loudly while the incumbent plan
                        keeps serving (zero-drop — the swap machinery
                        re-enters the old plan on failure).
  - ``fleet.plane.spawn`` — one plane-process (re)spawn attempt in the
                        fleet router's watchdog (``serving/fleet.py``):
                        injected errors are absorbed by paced bounded
                        retries inside the per-plane restart budget;
                        exhaustion evicts the plane LOUDLY while the
                        surviving fleet keeps serving.
  - ``fleet.rpc.send`` — one router→plane RPC send
                        (``serving/fleet_rpc.py``), fired BEFORE any
                        bytes hit the wire so error rules are safely
                        retried (at-most-once preserved); corrupt rules
                        model wire corruption of a shipped weight plane
                        — the split-plane per-tensor CRCs must catch it
                        and quarantine the plane, never serve.

Activation is either lexical (``with plan.active():``) or ambient via
the ``KEYSTONE_FAULT_PLAN`` env var (a JSON plan, or ``@/path/to.json``)
— the env form is what ``run.py --fault-plan`` wires through for manual
chaos drills. With no active plan every hook is a counter-free no-op.

:class:`RetryPolicy` is the bounded-exponential-backoff companion:
transient-only (``OSError`` by default — a checksum failure is
*persistent* and must fail loud, never be retried into silence), with
deterministic jitter derived from (seed, site, call, attempt) so two
runs of the same plan back off identically.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "FaultError",
    "FaultPlan",
    "FaultRule",
    "RetryPolicy",
    "SITE_AUTOSCALE_SPAWN",
    "SITE_CHECKPOINT_WRITE",
    "SITE_FLEET_PLANE_SPAWN",
    "SITE_FLEET_RPC_SEND",
    "SITE_IMAGE_AUGMENT",
    "SITE_IMAGE_DECODE",
    "SITE_LIFECYCLE_PUBLISH",
    "SITE_LIFECYCLE_VALIDATE",
    "SITE_PREFETCH_READ",
    "SITE_REPLICA_EXECUTE",
    "SITE_REPLICA_SPAWN",
    "SITE_SERVING_EXECUTE",
    "SITE_SHARD_LOAD",
    "SITE_TRAINER_FIT",
    "SITE_ZOO_PAGE_IN",
    "SITE_ZOO_PAGE_OUT",
    "active_plan",
    "corrupt_array",
    "install",
    "maybe_fail",
    "observe_busy",
    "observe_retry",
    "observing_retries",
    "uninstall",
]

SITE_SHARD_LOAD = "shard.load"
SITE_PREFETCH_READ = "prefetch.read"
SITE_SERVING_EXECUTE = "serving.execute"
SITE_REPLICA_EXECUTE = "serving.replica.execute"
SITE_REPLICA_SPAWN = "serving.replica.spawn"
SITE_AUTOSCALE_SPAWN = "serving.autoscale.spawn"
SITE_CHECKPOINT_WRITE = "checkpoint.write"
SITE_IMAGE_DECODE = "image.decode"
SITE_IMAGE_AUGMENT = "image.augment"
SITE_ZOO_PAGE_IN = "serving.zoo.page_in"
SITE_ZOO_PAGE_OUT = "serving.zoo.page_out"
SITE_TRAINER_FIT = "trainer.fit"
SITE_LIFECYCLE_VALIDATE = "lifecycle.validate"
SITE_LIFECYCLE_PUBLISH = "lifecycle.publish"
SITE_FLEET_PLANE_SPAWN = "fleet.plane.spawn"
SITE_FLEET_RPC_SEND = "fleet.rpc.send"

_KINDS = ("error", "corrupt", "latency")
_EXC_TYPES: Dict[str, type] = {
    "OSError": OSError,
    "IOError": OSError,  # alias in py3
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
}


class FaultError(OSError):
    """The default injected transient error: an OSError subclass so the
    retry layer treats it exactly like a real flaky read, while tests can
    still assert the failure was the *injected* one."""


class FaultRule:
    """One injection: at ``site``, on the call indices in ``calls``
    (0-based per-site counter) or with seeded probability ``p``, perform
    ``kind``:

      - ``error``:   raise ``exc`` (default :class:`FaultError`).
      - ``corrupt``: flip one byte of the payload handed to
                     :func:`corrupt_array` (checksum layers must catch it).
      - ``latency``: sleep ``latency_s`` before returning.

    ``count`` bounds how many times the rule fires (probability rules
    default to unbounded; call-list rules fire once per listed call).
    """

    def __init__(
        self,
        site: str,
        kind: str = "error",
        calls: Optional[Sequence[int]] = None,
        p: float = 0.0,
        count: Optional[int] = None,
        exc: str = "FaultError",
        message: str = "injected fault",
        latency_s: float = 0.0,
    ):
        if kind not in _KINDS:
            raise ValueError(f"fault kind {kind!r} not in {_KINDS}")
        if calls is None and p <= 0.0:
            raise ValueError("a FaultRule needs calls=[...] or p > 0")
        self.site = str(site)
        self.kind = kind
        self.calls = None if calls is None else frozenset(int(c) for c in calls)
        self.p = float(p)
        self.count = None if count is None else int(count)
        self.exc = str(exc)
        self.message = str(message)
        self.latency_s = float(latency_s)
        self.fired = 0

    def make_exception(self) -> BaseException:
        cls = _EXC_TYPES.get(self.exc, FaultError)
        if self.exc == "FaultError":
            cls = FaultError
        return cls(f"{self.message} [site={self.site} kind={self.kind}]")

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"site": self.site, "kind": self.kind}
        if self.calls is not None:
            d["calls"] = sorted(self.calls)
        if self.p:
            d["p"] = self.p
        if self.count is not None:
            d["count"] = self.count
        if self.kind == "error":
            d["exc"] = self.exc
        if self.latency_s:
            d["latency_s"] = self.latency_s
        return d


class FaultPlan:
    """A deterministic set of :class:`FaultRule` injections.

    Determinism contract: per-site call counters start at zero at
    install time, call-indexed rules fire at exactly the listed calls,
    and probabilistic rules draw from ``default_rng(seed ^ hash(site))``
    in per-site call order — so the same plan over the same workload
    injects the same faults, every run (the replayability every chaos
    test in tests/test_chaos.py leans on).

    Thread-safe: sites fire from reader/worker threads while the plan is
    installed from the driver thread.
    """

    def __init__(self, rules: Sequence[FaultRule] = (), seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._rngs: Dict[str, np.random.Generator] = {}
        self.log: List[Tuple[str, int, str]] = []  # (site, call, kind)

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_dict(spec: Dict[str, Any]) -> "FaultPlan":
        rules = [FaultRule(**r) for r in spec.get("rules", ())]
        return FaultPlan(rules, seed=int(spec.get("seed", 0)))

    @staticmethod
    def from_json(text: str) -> "FaultPlan":
        return FaultPlan.from_dict(json.loads(text))

    @staticmethod
    def from_env(env: str = "KEYSTONE_FAULT_PLAN") -> Optional["FaultPlan"]:
        """Parse the ambient plan: a JSON object, or ``@/path/to.json``.
        Returns None when the variable is unset/empty."""
        raw = os.environ.get(env, "").strip()
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        return FaultPlan.from_json(raw)

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "rules": [r.to_dict() for r in self.rules]}

    # -- firing ------------------------------------------------------------

    def _site_rng(self, site: str) -> np.random.Generator:
        rng = self._rngs.get(site)
        if rng is None:
            rng = np.random.default_rng(
                (self.seed ^ (zlib.crc32(site.encode()) & 0x7FFFFFFF))
            )
            self._rngs[site] = rng
        return rng

    def fire(
        self,
        site: str,
        counter: Optional[str] = None,
        kinds: Optional[Tuple[str, ...]] = None,
    ) -> Tuple[int, List[FaultRule]]:
        """Advance a call counter and return (call_index, rules matching
        ``site`` and ``kinds``). ``counter`` names the counter keyed
        (default: the site itself) — corruption hooks count under
        ``<site>.corrupt`` so error rules at the same site never shift
        corruption call indices, and ``kinds`` keeps each hook from
        consuming (or double-firing) the other hook's rules.
        Probability draws happen for every call of a p-rule's site,
        matched or not, so the draw sequence is a pure function of
        (seed, site, call order)."""
        counter = site if counter is None else counter
        with self._lock:
            call = self._counters.get(counter, 0)
            self._counters[counter] = call + 1
            matched = []
            for r in self.rules:
                if r.site != site:
                    continue
                if kinds is not None and r.kind not in kinds:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                hit = False
                if r.calls is not None:
                    hit = call in r.calls
                elif r.p > 0.0:
                    hit = bool(self._site_rng(site).random() < r.p)
                if hit:
                    r.fired += 1
                    matched.append(r)
                    self.log.append((site, call, r.kind))
            return call, matched

    def calls_seen(self, site: str) -> int:
        with self._lock:
            return self._counters.get(site, 0)

    # -- activation --------------------------------------------------------

    def active(self) -> "_Activation":
        """Context manager installing this plan for the dynamic extent
        (across ALL threads — reader/worker threads must see it)."""
        return _Activation(self)

    def __enter__(self) -> "FaultPlan":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall(self)


class _Activation:
    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        install(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        uninstall(self.plan)


_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[FaultPlan] = None
_ENV_CHECKED = False


def install(plan: FaultPlan) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None and _ACTIVE is not plan:
            raise RuntimeError(
                "a FaultPlan is already installed; nesting plans would make "
                "call counters ambiguous (uninstall the active plan first)"
            )
        _ACTIVE = plan


def uninstall(plan: Optional[FaultPlan] = None) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if plan is None or _ACTIVE is plan:
            _ACTIVE = None


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, resolving ``KEYSTONE_FAULT_PLAN`` once on
    first use (the ``run.py --fault-plan`` path installs ambiently)."""
    global _ENV_CHECKED, _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    if not _ENV_CHECKED:
        with _ACTIVE_LOCK:
            if not _ENV_CHECKED:
                _ENV_CHECKED = True
                plan = FaultPlan.from_env()
                if plan is not None:
                    _ACTIVE = plan
    return _ACTIVE


def _reset_env_cache() -> None:
    """Test hook: forget the memoized KEYSTONE_FAULT_PLAN lookup."""
    global _ENV_CHECKED
    with _ACTIVE_LOCK:
        _ENV_CHECKED = False


def maybe_fail(site: str) -> None:
    """Site hook for error/latency faults: raises or sleeps per the
    active plan; no-op (and counter-free) when no plan is installed."""
    plan = active_plan()
    if plan is None:
        return
    _, matched = plan.fire(site, kinds=("error", "latency"))
    for r in matched:
        if r.kind == "latency":
            time.sleep(r.latency_s)
        elif r.kind == "error":
            raise r.make_exception()


def corrupt_array(site: str, arr: np.ndarray) -> np.ndarray:
    """Site hook for corruption faults: when a ``corrupt`` rule fires,
    return a COPY of ``arr`` with one byte flipped (first byte XOR 0xFF
    — deterministic); otherwise return ``arr`` untouched. Shares the
    site counter with :func:`maybe_fail` callers only if they use
    distinct sites — corruption sites count independently via the
    ``<site>.corrupt`` counter so error rules at the same site never
    shift corruption call indices."""
    plan = active_plan()
    if plan is None:
        return arr
    _, matched = plan.fire(site, counter=site + ".corrupt",
                           kinds=("corrupt",))
    if not matched:
        return arr
    out = np.array(arr, copy=True)
    flat = out.view(np.uint8).reshape(-1)
    if flat.size:
        flat[0] ^= 0xFF
    return out


# -- retry observability ----------------------------------------------------
#
# Retries happen layers below the code that owns the fit's stats (the
# shard classes have no PrefetchStats handle, and one shards object can
# serve many fits). The observer is a THREAD-local slot the consuming
# layer (Prefetcher reader thread, or the serial segment loop) points at
# its stats for the duration of a load — every RetryPolicy in the stack
# then reports recovered transients into the right fit's counters, so
# "the fit survived flaky IO" is never structurally invisible.

_RETRY_TLS = threading.local()


class _RetryObservation:
    """Restore-on-exit guard for the thread's retry-stats slot."""

    def __init__(self, stats):
        self.stats = stats
        self.prev = None

    def __enter__(self):
        self.prev = getattr(_RETRY_TLS, "stats", None)
        _RETRY_TLS.stats = self.stats
        return self.stats

    def __exit__(self, *exc):
        _RETRY_TLS.stats = self.prev


def observing_retries(stats) -> _RetryObservation:
    """Route this thread's :func:`observe_retry` calls into ``stats``
    (an object with ``retries`` / ``backoff_s`` counters, e.g.
    PrefetchStats) for the context's duration; ``None`` silences."""
    return _RetryObservation(stats)


def observe_retry(delay_s: float) -> None:
    """Count one recovered transient (called from retry ``on_retry``
    hooks at any layer). No-op when the thread has no observer."""
    stats = getattr(_RETRY_TLS, "stats", None)
    if stats is not None:
        stats.retries += 1
        stats.backoff_s += float(delay_s)


def observe_busy(site: str, seconds: float) -> None:
    """Report per-site busy seconds into the thread's observer (the
    same thread-local channel as :func:`observe_retry`) — how the shard
    layer's checksum pass attributes its ``verify`` time to the
    consuming fit's :class:`~keystone_tpu.data.prefetch.PrefetchStats`
    without holding a stats handle. No-op without an observer, or for
    observers without per-site accounting (``add_busy``)."""
    stats = getattr(_RETRY_TLS, "stats", None)
    if stats is not None and hasattr(stats, "add_busy"):
        stats.add_busy(site, float(seconds))


class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``attempts`` counts TOTAL tries (so 3 means 2 retries). Retries only
    ``transient`` exception types (``OSError`` — which injected
    :class:`FaultError`\\ s subclass — by default); anything else,
    including :class:`~keystone_tpu.data.durable.ShardCorrupted`,
    re-raises immediately: a checksum mismatch is persistent state, and
    retrying it would just re-read the same bad bytes while hiding the
    failure from the operator.

    Jitter is a pure function of (seed, key, attempt): two runs of the
    same plan back off by identical amounts, keeping chaos-test timing
    replayable. Exhaustion re-raises the LAST error unchanged, so
    callers observe exactly the pre-retry-layer failure mode.
    """

    def __init__(
        self,
        attempts: int = 3,
        base_delay_s: float = 0.02,
        max_delay_s: float = 2.0,
        jitter: float = 0.25,
        seed: int = 0,
        transient: Tuple[type, ...] = (OSError,),
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.attempts = int(attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.transient = tuple(transient)

    def delay_s(self, attempt: int, key: str = "") -> float:
        """Backoff before retry number ``attempt`` (1-based): capped
        exponential plus deterministic jitter in [0, jitter] fractions
        of the base step."""
        base = min(
            self.base_delay_s * (2.0 ** (attempt - 1)), self.max_delay_s
        )
        h = zlib.crc32(f"{self.seed}:{key}:{attempt}".encode()) & 0xFFFFFFFF
        frac = (h / 0xFFFFFFFF) * self.jitter
        return min(base * (1.0 + frac), self.max_delay_s)

    def call(
        self,
        fn: Callable[[], Any],
        key: str = "",
        on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    ) -> Any:
        """Run ``fn`` with retries. ``on_retry(attempt, delay_s, exc)``
        fires before each backoff sleep (the stats-counter hook)."""
        last: Optional[BaseException] = None
        for attempt in range(1, self.attempts + 1):
            try:
                return fn()
            except self.transient as e:  # noqa: PERF203 — retry loop
                last = e
                if attempt == self.attempts:
                    raise
                d = self.delay_s(attempt, key)
                if on_retry is not None:
                    on_retry(attempt, d, e)
                time.sleep(d)
        raise last  # pragma: no cover — loop always returns or raises


def _env_number(name: str, default: str, cast, minimum):
    """Parse a numeric env knob, failing at PARSE time with one clear
    error naming the variable — a bad value must not surface as an
    unrelated TypeError deep inside a shard read's retry loop."""
    raw = os.environ.get(name, default)
    try:
        value = cast(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"{name}={raw!r} is not a valid {cast.__name__} "
            f"(unset it or set a number >= {minimum})"
        ) from None
    if value < minimum:
        raise ValueError(
            f"{name}={raw!r} must be >= {minimum}"
        )
    return value


def default_retry_policy() -> RetryPolicy:
    """The data plane's shared default policy; knobs ride env vars so
    drills can tighten/loosen without code changes:
    ``KEYSTONE_RETRY_ATTEMPTS`` (default 3, an int >= 1) and
    ``KEYSTONE_RETRY_BASE_S`` (default 0.02, a float >= 0). Invalid
    values raise one :class:`ValueError` naming the variable, here at
    policy construction — never mid-read."""
    return RetryPolicy(
        attempts=_env_number("KEYSTONE_RETRY_ATTEMPTS", "3", int, 1),
        base_delay_s=_env_number("KEYSTONE_RETRY_BASE_S", "0.02", float, 0.0),
    )
