"""Tracing / profiling utilities (SURVEY.md §5).

The reference has two profiling mechanisms: the AutoCacheRule sampling
profiler (wall-clock + memory per node, AutoCacheRule.scala:153-465) and
ad-hoc per-phase nanosecond logs inside solvers (KernelRidgeRegression.scala:
213-221). The TPU equivalents here:

  - ``PhaseTimer`` — named phase accumulation with a log summary, used by the
    iterative solvers for per-phase breakdowns.
  - ``trace`` — context manager around ``jax.profiler`` emitting a TensorBoard
    trace directory (XLA device timelines), the deep-dive tool.
  - ``compiled_cost`` — static cost extraction from a jitted function's
    compiled XLA executable (FLOPs / bytes accessed), the analog of the
    reference's analytic ``CostModel`` inputs but read from the compiler
    instead of hand-derived.
  - ``prefetch_overlap_fraction`` — the achieved ingestion-overlap share
    of a prefetched streamed fit, from its
    :class:`~keystone_tpu.data.prefetch.PrefetchStats`.
  - ``RequestSpan`` / ``SpanLog`` — per-request serving spans (queue wait /
    pad fraction / execution time) recorded by the online micro-batcher
    (:mod:`keystone_tpu.serving.batcher`), bounded so a long-lived server
    never grows its profiling state without limit.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax

logger = logging.getLogger("keystone_tpu.profiling")


class PhaseTimer:
    """Accumulate wall-clock per named phase.

    >>> t = PhaseTimer("krr")
    >>> with t.phase("kernel_gen"):
    ...     do_work()
    >>> t.log_summary()
    """

    def __init__(self, name: str = ""):
        self.name = name
        self.totals: "OrderedDict[str, float]" = OrderedDict()
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, phase_name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[phase_name] = self.totals.get(phase_name, 0.0) + dt
            self.counts[phase_name] = self.counts.get(phase_name, 0) + 1

    def total(self, phase_name: str) -> float:
        return self.totals.get(phase_name, 0.0)

    def summary(self) -> str:
        parts = [
            f"{k}={v:.3f}s/{self.counts[k]}x" for k, v in self.totals.items()
        ]
        prefix = f"{self.name}: " if self.name else ""
        return prefix + ", ".join(parts) if parts else prefix + "(no phases)"

    def log_summary(self, level: int = logging.INFO) -> None:
        logger.log(level, "%s", self.summary())


def prefetch_overlap_fraction(stats) -> Optional[float]:
    """Achieved ingestion-overlap fraction of one prefetched streamed fit.

    ``stats`` is the :class:`~keystone_tpu.data.prefetch.PrefetchStats` the
    fit's Prefetcher filled: ``load_s`` is total time inside
    ``source.load`` (reader thread — disk + staging copies), ``wait_s`` is
    total time the CONSUMER blocked on the queue (latency the prefetch
    failed to hide). The hidden share is

        (load_s − wait_s) / load_s        clamped to [0, 1]

    — 1.0 means every second of disk→host ingestion ran behind device
    compute; 0.0 means fully serial (every load was waited on). Unlike the
    bench's two-leg A/B (``(wall_off − wall_on) / load_s``), this needs
    ONE run, so any streamed fit can report it (pass ``prefetch_stats`` to
    ``streaming_bcd_fit_segments`` / ``run_lbfgs_gram_streamed``). Returns
    None when no load time was recorded; a serial ``prefetch_depth=0``
    pass (``stats.prefetched`` False — loads ran inline on the consumer,
    nothing overlapped) reports 0.0.
    """
    load_s = float(getattr(stats, "load_s", 0.0) or 0.0)
    if load_s <= 0.0:
        return None
    if not getattr(stats, "prefetched", False):
        return 0.0
    wait_s = float(getattr(stats, "wait_s", 0.0) or 0.0)
    return min(max((load_s - wait_s) / load_s, 0.0), 1.0)


def overlap_report(stats) -> Dict[str, Dict[str, Optional[float]]]:
    """Per-SITE overlap report of one streamed fit (ISSUE 8 satellite):
    the per-phase form of :func:`prefetch_overlap_fraction`, built from
    the ``site_busy_s`` / ``site_wait_s`` accounting the data-plane
    runtime's consumers fill in one
    :class:`~keystone_tpu.data.prefetch.PrefetchStats`:

      - ``read`` — segment loads on the runtime's ``read`` worker
        (busy) vs consumer queue waits (wait);
      - ``verify`` — the shard layer's CRC pass (rides inside read's
        wall, attributed via ``faults.observe_busy``);
      - ``checkpoint`` — write-behind snapshot writes (busy, worker
        side) vs the fold-blocking sync+submit share (wait);
      - ``decode`` / ``augment`` — the image tier's per-segment decode
        and seeded augmentation (ride inside the read lane's wall,
        attributed via ``faults.observe_busy`` from
        ``EncodedImageSource.load`` — ISSUE 18);
      - ``compute`` — the consumer's transfer + fold dispatch + device
        throttle, the denominator phase everything else hides behind.

    Per site: ``busy_s`` (wall the phase worked), ``wait_s`` (wall the
    CONSUMER blocked on it), ``hidden_s = max(busy − wait, 0)`` and
    ``overlap = hidden/busy`` (None when the site did no work) — 1.0
    means the phase ran entirely behind compute, 0.0 fully serial. A
    serial ``prefetch_depth=0`` leg records busy == wait for ``read``,
    so the oracle path reads 0 overlap by construction. This is what
    makes a fold-floor claim (the Amazon 131.4 s) auditable per phase:
    wall − compute.busy must be accounted for by the visible waits.

    Reads the ``MetricsRegistry`` a real :class:`~keystone_tpu.data.
    prefetch.PrefetchStats` carries (ISSUE 9 — the registry is the
    single store); plain objects exposing ``site_busy_s``/``site_wait_s``
    dicts still work through a deprecated attribute shim."""
    busy, wait = _site_dicts(stats)
    report: Dict[str, Dict[str, Optional[float]]] = {}
    for site in sorted(set(busy) | set(wait)):
        b = float(busy.get(site, 0.0))
        w = float(wait.get(site, 0.0))
        hidden = max(b - w, 0.0)
        report[site] = {
            "busy_s": b,
            "wait_s": w,
            "hidden_s": hidden,
            "overlap": (min(hidden / b, 1.0) if b > 0.0 else None),
        }
    return report


def _site_dicts(stats):
    """(busy, wait) per-site dicts: from the stats object's
    ``MetricsRegistry`` when it carries one (the PrefetchStats form —
    the single store), else the deprecated bare-attribute shim for
    plain objects (kept so pre-registry callers and tests keep
    working)."""
    reg = getattr(stats, "registry", None)
    if reg is not None and hasattr(reg, "values_by_label"):
        from keystone_tpu.obs.metrics import (
            METRIC_SITE_BUSY_S,
            METRIC_SITE_WAIT_S,
        )

        return (
            reg.values_by_label(METRIC_SITE_BUSY_S, "site"),
            reg.values_by_label(METRIC_SITE_WAIT_S, "site"),
        )
    _warn_legacy_stats("overlap_report")
    return (
        dict(getattr(stats, "site_busy_s", {}) or {}),
        dict(getattr(stats, "site_wait_s", {}) or {}),
    )


def _warn_legacy_stats(fn_name: str) -> None:
    import warnings

    warnings.warn(
        f"{fn_name}: reading bare stats attributes is deprecated — pass "
        "a PrefetchStats (whose MetricsRegistry is the single metrics "
        "store, keystone_tpu/obs) instead of a plain object",
        DeprecationWarning, stacklevel=3,
    )


def prefetch_retry_counters(stats) -> Dict[str, float]:
    """Reliability accounting of one streamed fit's ingestion
    (docs/reliability.md): how many transient read failures the retry
    layer absorbed (``retries``) and the backoff wall it paid for them
    (``backoff_s``), from the fit's
    :class:`~keystone_tpu.data.prefetch.PrefetchStats`. Zero/zero on a
    healthy run — the steady-state cost of the retry layer is nothing
    but the counters themselves. Nonzero values mean the fit SUCCEEDED
    over flaky IO; alert on them before they become exhaustions.

    Reads the stats object's ``MetricsRegistry`` when it carries one
    (ISSUE 9); bare attributes remain as a deprecated shim."""
    reg = getattr(stats, "registry", None)
    if reg is not None and hasattr(reg, "snapshot"):
        from keystone_tpu.obs.metrics import (
            METRIC_PREFETCH_BACKOFF_S,
            METRIC_PREFETCH_RETRIES,
        )

        snap = reg.snapshot()
        return {
            "retries": int(snap.get(METRIC_PREFETCH_RETRIES, 0) or 0),
            "backoff_s": float(
                snap.get(METRIC_PREFETCH_BACKOFF_S, 0.0) or 0.0
            ),
        }
    _warn_legacy_stats("prefetch_retry_counters")
    return {
        "retries": int(getattr(stats, "retries", 0) or 0),
        "backoff_s": float(getattr(stats, "backoff_s", 0.0) or 0.0),
    }


@dataclass(frozen=True)
class RequestSpan:
    """Where one served request's latency went (the serving analog of a
    PhaseTimer breakdown): ``queue_wait_s`` is time spent queued before
    its batch dispatched, ``exec_s`` the batch's execution wall (shared
    by every request coalesced into it), ``batch_size`` the real
    requests in the batch, ``bucket`` the padded shape it ran at, and
    ``pad_fraction`` the share of bucket rows that were padding — the
    amortization price the micro-batcher paid for a warm compile-cache
    hit."""

    queue_wait_s: float
    exec_s: float
    batch_size: int
    bucket: int
    pad_fraction: float
    # Which replica of a replicated serving plane executed the batch
    # (None on a standalone MicroBatchServer) — per-replica span
    # attribution for serving/replicas.py's aggregate stats.
    replica: Optional[int] = None


class SpanLog:
    """Bounded, thread-safe log of :class:`RequestSpan` records.

    The micro-batcher records one span per request from its worker
    thread while ``stats()`` readers snapshot from submitter threads;
    the lock keeps the snapshot consistent and ``maxlen`` bounds a
    long-lived server's profiling memory."""

    def __init__(self, maxlen: int = 4096):
        self._spans: "deque[RequestSpan]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, span: RequestSpan) -> None:
        with self._lock:
            self._spans.append(span)

    def snapshot(self) -> List[RequestSpan]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def summary(self) -> Dict[str, float]:
        """Mean queue wait / exec / pad fraction over the retained window
        (empty dict when nothing has been served)."""
        return summarize_spans(self.snapshot())


def summarize_spans(spans: Sequence["RequestSpan"]) -> Dict[str, float]:
    """The one summary shape for a span collection (SpanLog.summary, the
    per-replica blocks, and callers holding an already-snapshotted list
    — no second ring copy). Empty dict for no spans — EXPLICITLY: the
    empty case is a contract, not a numpy mean-of-empty-slice warning
    (ISSUE 9 satellite). Non-finite span fields raise ValueError naming
    the field: a NaN queue wait silently poisons every mean downstream,
    and numpy would only warn."""
    spans = list(spans)
    if not spans:
        return {}
    n = float(len(spans))
    sums = {"mean_queue_wait_s": 0.0, "mean_exec_s": 0.0,
            "mean_batch_size": 0.0, "mean_pad_fraction": 0.0}
    for i, s in enumerate(spans):
        for key, v in (
            ("mean_queue_wait_s", s.queue_wait_s),
            ("mean_exec_s", s.exec_s),
            ("mean_batch_size", s.batch_size),
            ("mean_pad_fraction", s.pad_fraction),
        ):
            v = float(v)
            if v != v or v in (float("inf"), float("-inf")):
                raise ValueError(
                    f"summarize_spans: span {i} has non-finite "
                    f"{key.replace('mean_', '')} ({v}) — refusing to "
                    "fold it into the means"
                )
            sums[key] += v
    return {"num_spans": len(spans),
            **{k: v / n for k, v in sums.items()}}


def latency_percentiles(
    latencies_s: Sequence[float], qs: Sequence[float] = (50.0, 99.0)
) -> Optional[Dict[str, float]]:
    """p-th percentile latencies in SECONDS keyed ``p50``/``p99``/...;
    None for an empty sample (a server that has completed nothing has no
    percentiles — callers must not report zeros as measurements).

    Edge cases are explicit contracts, not numpy warnings (ISSUE 9
    satellite): a single sample IS every percentile (p50 == p99 ==
    the sample — documented, tested); an out-of-range ``q`` raises
    ValueError naming it (numpy's own message names neither the value
    nor the caller); a NaN/inf sample raises ValueError instead of
    propagating NaN percentiles under a RuntimeWarning; an empty ``qs``
    raises rather than returning a vacuous ``{}`` that reads as "no
    latency problem". Accepts any iterable (a generator no longer
    TypeErrors on ``len``)."""
    import math

    import numpy as np

    samples = [float(v) for v in latencies_s]
    if not samples:
        return None
    qs = list(qs)
    if not qs:
        raise ValueError(
            "latency_percentiles: qs is empty — an empty percentile "
            "request is a caller bug, not a measurement"
        )
    for q in qs:
        if not 0.0 <= float(q) <= 100.0:
            raise ValueError(
                f"latency_percentiles: q={q!r} outside [0, 100]"
            )
    bad = [v for v in samples if not math.isfinite(v)]
    if bad:
        raise ValueError(
            f"latency_percentiles: {len(bad)} non-finite sample(s) "
            f"(first: {bad[0]!r}) — percentiles over NaN/inf are not "
            "measurements"
        )
    arr = np.asarray(samples, dtype=np.float64)
    return {f"p{int(q) if float(q).is_integer() else q}": float(v)
            for q, v in zip(qs, np.percentile(arr, list(qs)))}


@contextlib.contextmanager
def trace(log_dir: str):
    """Emit a jax.profiler trace (TensorBoard 'profile' plugin format) for
    everything run inside the context. No-op if the profiler cannot start
    (e.g. a second concurrent trace).

    This is the XLA device-timeline leg of the obs plane (ISSUE 9
    satellite — previously orphaned): ``obs.tracing(dir,
    xla_profile=True)`` wraps the traced block in it, writing under
    ``dir/xla`` beside the Perfetto span trace, so the deep-dive XLA
    view and the host-side span view come from ONE activation."""
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - depends on runtime state
        logger.warning("profiler trace unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


def compiled_cost(fn, *args, **kwargs) -> Optional[Dict[str, Any]]:
    """FLOPs / memory-traffic estimates for ``jax.jit(fn)(*args)`` from XLA's
    cost analysis of the compiled executable.

    Returns {"flops": float, "bytes accessed": float, ...} (keys as XLA
    reports them) or None when the backend doesn't support cost analysis.
    """
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
        analysis = lowered.compile().cost_analysis()
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("cost analysis unavailable: %s", e)
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    return dict(analysis) if analysis else None
