"""Tracing / profiling utilities (SURVEY.md §5).

The reference has two profiling mechanisms: the AutoCacheRule sampling
profiler (wall-clock + memory per node, AutoCacheRule.scala:153-465) and
ad-hoc per-phase nanosecond logs inside solvers (KernelRidgeRegression.scala:
213-221). The TPU equivalents here:

  - ``PhaseTimer`` — named phase accumulation with a log summary, used by the
    iterative solvers for per-phase breakdowns.
  - ``trace`` — context manager around ``jax.profiler`` emitting a TensorBoard
    trace directory (XLA device timelines), the deep-dive tool.
  - ``compiled_cost`` — static cost extraction from a jitted function's
    compiled XLA executable (FLOPs / bytes accessed), the analog of the
    reference's analytic ``CostModel`` inputs but read from the compiler
    instead of hand-derived.
  - ``prefetch_overlap_fraction`` — the achieved ingestion-overlap share
    of a prefetched streamed fit, from its
    :class:`~keystone_tpu.data.prefetch.PrefetchStats`.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import OrderedDict
from typing import Any, Dict, Optional

import jax

logger = logging.getLogger("keystone_tpu.profiling")


class PhaseTimer:
    """Accumulate wall-clock per named phase.

    >>> t = PhaseTimer("krr")
    >>> with t.phase("kernel_gen"):
    ...     do_work()
    >>> t.log_summary()
    """

    def __init__(self, name: str = ""):
        self.name = name
        self.totals: "OrderedDict[str, float]" = OrderedDict()
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, phase_name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[phase_name] = self.totals.get(phase_name, 0.0) + dt
            self.counts[phase_name] = self.counts.get(phase_name, 0) + 1

    def total(self, phase_name: str) -> float:
        return self.totals.get(phase_name, 0.0)

    def summary(self) -> str:
        parts = [
            f"{k}={v:.3f}s/{self.counts[k]}x" for k, v in self.totals.items()
        ]
        prefix = f"{self.name}: " if self.name else ""
        return prefix + ", ".join(parts) if parts else prefix + "(no phases)"

    def log_summary(self, level: int = logging.INFO) -> None:
        logger.log(level, "%s", self.summary())


def prefetch_overlap_fraction(stats) -> Optional[float]:
    """Achieved ingestion-overlap fraction of one prefetched streamed fit.

    ``stats`` is the :class:`~keystone_tpu.data.prefetch.PrefetchStats` the
    fit's Prefetcher filled: ``load_s`` is total time inside
    ``source.load`` (reader thread — disk + staging copies), ``wait_s`` is
    total time the CONSUMER blocked on the queue (latency the prefetch
    failed to hide). The hidden share is

        (load_s − wait_s) / load_s        clamped to [0, 1]

    — 1.0 means every second of disk→host ingestion ran behind device
    compute; 0.0 means fully serial (every load was waited on). Unlike the
    bench's two-leg A/B (``(wall_off − wall_on) / load_s``), this needs
    ONE run, so any streamed fit can report it (pass ``prefetch_stats`` to
    ``streaming_bcd_fit_segments`` / ``run_lbfgs_gram_streamed``). Returns
    None when no load time was recorded; a serial ``prefetch_depth=0``
    pass (``stats.prefetched`` False — loads ran inline on the consumer,
    nothing overlapped) reports 0.0.
    """
    load_s = float(getattr(stats, "load_s", 0.0) or 0.0)
    if load_s <= 0.0:
        return None
    if not getattr(stats, "prefetched", False):
        return 0.0
    wait_s = float(getattr(stats, "wait_s", 0.0) or 0.0)
    return min(max((load_s - wait_s) / load_s, 0.0), 1.0)


@contextlib.contextmanager
def trace(log_dir: str):
    """Emit a jax.profiler trace (TensorBoard 'profile' plugin format) for
    everything run inside the context. No-op if the profiler cannot start
    (e.g. a second concurrent trace)."""
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - depends on runtime state
        logger.warning("profiler trace unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


def compiled_cost(fn, *args, **kwargs) -> Optional[Dict[str, Any]]:
    """FLOPs / memory-traffic estimates for ``jax.jit(fn)(*args)`` from XLA's
    cost analysis of the compiled executable.

    Returns {"flops": float, "bytes accessed": float, ...} (keys as XLA
    reports them) or None when the backend doesn't support cost analysis.
    """
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
        analysis = lowered.compile().cost_analysis()
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("cost analysis unavailable: %s", e)
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    return dict(analysis) if analysis else None
