"""Numeric comparison helpers (reference: utils/Stats.scala:25-66).

``about_eq`` is the tolerance comparison the reference uses throughout its
solver tests; it accepts scalars, arrays, and nested sequences.
"""

from __future__ import annotations

import numpy as np

DEFAULT_THRESHOLD = 1e-8


def about_eq(a, b, threshold: float = DEFAULT_THRESHOLD) -> bool:
    """True when every element of ``a`` is strictly within ``threshold`` of
    ``b`` (absolute difference — the reference's Stats.aboutEq semantics:
    ``abs(diff) < threshold``, and a shape mismatch is a programming error
    that *throws*, matching the reference's ``require``; Stats.scala:25-66)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.shape != b.shape:
        raise ValueError(
            f"about_eq operands must have the same shape: {a.shape} vs {b.shape}"
        )
    return bool(np.all(np.abs(a - b) < threshold))
