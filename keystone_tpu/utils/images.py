"""Image representation and utilities.

The reference carries an ``Image`` trait with four array storage layouts and
index arithmetic per layout (reference: utils/images/Image.scala,
utils/ImageUtils.scala). On TPU there is exactly one right layout: a dense
``(x, y, channel)`` float array — XLA lays HWC minor-to-major and
``lax.conv_general_dilated`` maps it straight onto the MXU. So here an image
IS an array:

  - single image:  ``(xDim, yDim, numChannels)`` float32
  - batch:         ``(n, xDim, yDim, numChannels)``

Axis 0 corresponds to the reference's ``x`` index and axis 1 to ``y``, so
``img[x, y, c]`` matches ``Image.get(x, y, c)``.

``ImageMetadata`` survives as a plain shape record used by loaders and node
factories.
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclass(frozen=True)
class ImageMetadata:
    """Shape record (reference: utils/images/Image.scala ImageMetadata)."""

    x_dim: int
    y_dim: int
    num_channels: int

    @property
    def shape(self):
        return (self.x_dim, self.y_dim, self.num_channels)


def metadata_of(img) -> ImageMetadata:
    x, y, c = np.shape(img)
    return ImageMetadata(int(x), int(y), int(c))


def load_image(source: Union[str, bytes]) -> np.ndarray:
    """Decode an image file or byte buffer to an (x, y, c) float array
    (replaces the reference's javax.imageio path, utils/ImageUtils.scala)."""
    from PIL import Image as PILImage

    if isinstance(source, (bytes, bytearray)):
        pil = PILImage.open(io.BytesIO(source))
    else:
        pil = PILImage.open(source)
    arr = np.asarray(pil, dtype=np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


# ---------------------------------------------------------------------------
# Elementwise / geometric ops (reference: utils/ImageUtils.scala)
# ---------------------------------------------------------------------------

# MATLAB rgb2gray / NTSC weights, exactly as the reference spells them
# (ImageUtils.toGrayScale: 0.2989 R + 0.5870 G + 0.1140 B on BGR data; our
# arrays are RGB so the weight vector is applied in R,G,B order).
_LUMA = np.array([0.2989, 0.5870, 0.1140], dtype=np.float64)


def as_float(img):
    """Promote to float32 unless the input is already float32-or-wider.

    Golden-parity tests run the extractors in float64 (jax x64 mode) to match
    the reference's double-precision math; normal TPU paths stay float32, and
    half-precision inputs (bf16/f16) are promoted so histogram/gradient
    accumulation never runs with an 8-bit mantissa."""
    img = jnp.asarray(img)
    if (
        not jnp.issubdtype(img.dtype, jnp.floating)
        or jnp.finfo(img.dtype).bits < 32
    ):
        img = img.astype(jnp.float32)
    return img


def to_grayscale(img):
    """(x, y, c) -> (x, y, 1) luminance (ImageUtils.toGrayScale)."""
    img = as_float(img)
    if img.shape[-1] == 1:
        return img
    if img.shape[-1] == 3:
        luma = jnp.asarray(_LUMA, dtype=img.dtype)
        return jnp.tensordot(img, luma, axes=[[-1], [0]])[..., None]
    return jnp.mean(img, axis=-1, keepdims=True)


def crop(img, start_x: int, start_y: int, end_x: int, end_y: int):
    """Crop [start_x, end_x) × [start_y, end_y) (ImageUtils.crop)."""
    return jnp.asarray(img)[start_x:end_x, start_y:end_y, :]


def flip_horizontal(img):
    """Mirror along the y (second) axis (ImageUtils.flipHorizontal)."""
    return jnp.asarray(img)[:, ::-1, :]


def flip_image(img):
    """Flip both spatial axes AND channels (ImageUtils.flipImage reverses
    x, y and c — MATLAB convnd-style full reversal, ImageUtils.scala:376-389;
    used for convolution filter flipping)."""
    return jnp.asarray(img)[::-1, ::-1, ::-1]


def conv2d_valid(img, kernel):
    """Per-channel 2-D valid cross-correlation of one (x, y, c) image with one
    (kx, ky) kernel (ImageUtils.conv2D). Compiles to an XLA conv (MXU)."""
    img = as_float(img)
    kernel = jnp.asarray(kernel, dtype=img.dtype)
    lhs = jnp.transpose(img, (2, 0, 1))[:, None, :, :]  # (c, 1, x, y)
    rhs = kernel[None, None, :, :]  # (1, 1, kx, ky)
    out = lax.conv_general_dilated(
        lhs, rhs, window_strides=(1, 1), padding="VALID"
    )  # (c, 1, x', y')
    return jnp.transpose(out[:, 0, :, :], (1, 2, 0))


def separable_conv2d_same(img, x_filter, y_filter):
    """Separable same-size true convolution with zero padding, matching the
    reference's ImageUtils.conv2D (utils/images/ImageUtils.scala:226-320):
    kernels are flipped (convolution, not correlation) and the output has the
    input's spatial size."""
    img = as_float(img)
    if img.ndim == 2:
        img = img[:, :, None]
    kx = jnp.asarray(x_filter, dtype=img.dtype)[::-1]
    ky = jnp.asarray(y_filter, dtype=img.dtype)[::-1]
    lx = kx.shape[0]
    ly = ky.shape[0]
    pad_xl, pad_xh = (lx - 1) // 2, lx - 1 - (lx - 1) // 2
    pad_yl, pad_yh = (ly - 1) // 2, ly - 1 - (ly - 1) // 2
    padded = jnp.pad(img, ((pad_xl, pad_xh), (0, 0), (0, 0)))
    out = conv2d_valid(padded, kx[:, None])
    padded = jnp.pad(out, ((0, 0), (pad_yl, pad_yh), (0, 0)))
    return conv2d_valid(padded, ky[None, :])


def gaussian_kernel_1d(sigma: float, radius: Optional[int] = None) -> np.ndarray:
    if radius is None:
        radius = max(1, int(np.ceil(3.0 * sigma)))
    xs = np.arange(-radius, radius + 1, dtype=np.float32)
    k = np.exp(-0.5 * (xs / sigma) ** 2)
    return k / k.sum()


def gaussian_blur(img, sigma: float):
    """Separable Gaussian smoothing with edge replication (the role of
    vl_imsmooth_f in the reference's native SIFT path,
    src/main/cpp/VLFeat.cxx:38-180)."""
    if sigma <= 0:
        return jnp.asarray(img)
    k = jnp.asarray(gaussian_kernel_1d(sigma))
    r = (k.shape[0] - 1) // 2
    img = jnp.asarray(img, dtype=jnp.float32)
    padded = jnp.pad(img, ((r, r), (0, 0), (0, 0)), mode="edge")
    img = conv2d_valid(padded, k[:, None].astype(jnp.float32))
    padded = jnp.pad(img, ((0, 0), (r, r), (0, 0)), mode="edge")
    return conv2d_valid(padded, k[None, :].astype(jnp.float32))


def crop_to_multiple(img, multiple: int = 8):
    """Center-crop spatial dims down to multiples of ``multiple``.

    Shape-bucketing policy for real-image archives (SURVEY.md §7 hard part
    (d)): XLA programs are specialized per shape, so arbitrary-size photos
    would compile one executable each. Cropping at the loader boundary to a
    coarse grid makes images of similar size share executables while losing
    at most ``multiple - 1`` border pixels per axis (the extractors' dense
    grids exclude borders anyway). Images smaller than one multiple are
    returned unchanged.
    """
    img = np.asarray(img)
    h, w = img.shape[0], img.shape[1]
    # Bucket each axis independently: a sub-multiple axis stays as-is but
    # must not exempt the other axis from cropping.
    nh = (h // multiple) * multiple or h
    nw = (w // multiple) * multiple or w
    if nh == h and nw == w:
        return img
    top = (h - nh) // 2
    left = (w - nw) // 2
    return img[top : top + nh, left : left + nw]
