"""Evaluators (reference: evaluation/ — Evaluator.scala:19-35,
MulticlassClassifierEvaluator.scala:23-161, BinaryClassifierEvaluator.scala:17-79).

Confusion-matrix accumulation is a single device pass (scatter-add over the
sharded batch), the analog of the reference's one-pass ``aggregate``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Generic, TypeVar

import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.workflow import PipelineDataset

P = TypeVar("P")
L = TypeVar("L")
E = TypeVar("E")


def _as_dataset(x) -> Dataset:
    if isinstance(x, PipelineDataset):
        return x.get()
    return Dataset.of(x)


class Evaluator(Generic[P, L, E]):
    """Computes a metric of predictions vs labels (Evaluator.scala:19-35)."""

    def evaluate(self, predictions: Any, labels: Any) -> E:
        return self._evaluate(_as_dataset(predictions), _as_dataset(labels))

    def _evaluate(self, predictions: Dataset, labels: Dataset) -> E:
        raise NotImplementedError


class MulticlassMetrics:
    """Derived metrics over a confusion matrix
    (reference: MulticlassClassifierEvaluator.scala:44-161).

    confusion[i, j] = count of items with true class i predicted as class j.
    """

    def __init__(self, confusion: np.ndarray):
        self.confusion = np.asarray(confusion, dtype=np.float64)
        self.num_classes = self.confusion.shape[0]
        self.total = self.confusion.sum()

    # -- per-class --

    def class_precision(self, c: int) -> float:
        denom = self.confusion[:, c].sum()
        return float(self.confusion[c, c] / denom) if denom > 0 else 0.0

    def class_recall(self, c: int) -> float:
        denom = self.confusion[c, :].sum()
        return float(self.confusion[c, c] / denom) if denom > 0 else 0.0

    def class_f1(self, c: int) -> float:
        return self.class_fscore(c)

    def class_fscore(self, c: int, beta: float = 1.0) -> float:
        """F_β (the reference's ``classMetrics(c).fScore(beta)``,
        MulticlassClassifierEvaluator.scala:56-66)."""
        p, r = self.class_precision(c), self.class_recall(c)
        b2 = beta * beta
        denom = b2 * p + r
        return (1 + b2) * p * r / denom if denom > 0 else 0.0

    def macro_fscore(self, beta: float = 1.0) -> float:
        return float(
            np.mean([self.class_fscore(c, beta) for c in range(self.num_classes)])
        )

    def micro_fscore(self, beta: float = 1.0) -> float:
        # Micro P == micro R == accuracy for single-label multiclass, so
        # every F_β equals the accuracy too.
        return self.accuracy

    # -- aggregate --

    @property
    def accuracy(self) -> float:
        return float(np.trace(self.confusion) / self.total) if self.total > 0 else 0.0

    @property
    def total_error(self) -> float:
        return 1.0 - self.accuracy

    @property
    def macro_precision(self) -> float:
        return float(np.mean([self.class_precision(c) for c in range(self.num_classes)]))

    @property
    def macro_recall(self) -> float:
        return float(np.mean([self.class_recall(c) for c in range(self.num_classes)]))

    @property
    def macro_f1(self) -> float:
        return self.macro_fscore()

    @property
    def micro_precision(self) -> float:
        # Micro P == micro R == accuracy for single-label multiclass.
        return self.accuracy

    micro_recall = micro_precision

    @property
    def micro_f1(self) -> float:
        return self.micro_fscore()

    def summary(self, class_names=None) -> str:
        """Mahout-style pretty print (MulticlassClassifierEvaluator.scala:85-105)."""
        names = class_names or [str(i) for i in range(self.num_classes)]
        lines = [
            "=" * 48,
            "Summary Statistics",
            "-" * 48,
            f"Accuracy          {self.accuracy:.4f}",
            f"Total Error       {self.total_error:.4f}",
            f"Macro Precision   {self.macro_precision:.4f}",
            f"Macro Recall      {self.macro_recall:.4f}",
            f"Macro F1          {self.macro_f1:.4f}",
            "-" * 48,
            "Per-class (precision / recall / f1):",
        ]
        for c in range(self.num_classes):
            lines.append(
                f"  {names[c]:>8}: {self.class_precision(c):.4f} / "
                f"{self.class_recall(c):.4f} / {self.class_f1(c):.4f}"
            )
        lines.append("=" * 48)
        return "\n".join(lines)

    def __repr__(self) -> str:
        return f"MulticlassMetrics(accuracy={self.accuracy:.4f}, n={int(self.total)})"


class MulticlassClassifierEvaluator(Evaluator):
    """Single-pass confusion matrix from predicted/true int labels."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def _evaluate(self, predictions: Dataset, labels: Dataset) -> MulticlassMetrics:
        preds = jnp.asarray(predictions.array).reshape(-1).astype(jnp.int32)
        labs = jnp.asarray(labels.array).reshape(-1).astype(jnp.int32)
        npad = preds.shape[0]
        if labs.shape[0] != npad:
            # Align physical shapes (padding may differ between the two).
            preds = preds[: predictions.n]
            labs = labs[: labels.n]
            mask = jnp.ones_like(preds, dtype=jnp.int32)
        else:
            mask = (jnp.arange(npad) < predictions.n).astype(jnp.int32)
        conf = jnp.zeros((self.num_classes, self.num_classes), dtype=jnp.int32)
        conf = conf.at[labs, preds].add(mask)
        return MulticlassMetrics(np.asarray(conf))


@dataclass
class BinaryClassificationMetrics:
    """Contingency counts (reference: BinaryClassifierEvaluator.scala:17-79)."""

    tp: float
    fp: float
    tn: float
    fn: float

    @property
    def accuracy(self) -> float:
        total = self.tp + self.fp + self.tn + self.fn
        return (self.tp + self.tn) / total if total > 0 else 0.0

    @property
    def error(self) -> float:
        return 1.0 - self.accuracy

    @property
    def precision(self) -> float:
        denom = self.tp + self.fp
        return self.tp / denom if denom > 0 else 0.0

    @property
    def recall(self) -> float:
        denom = self.tp + self.fn
        return self.tp / denom if denom > 0 else 0.0

    @property
    def specificity(self) -> float:
        denom = self.tn + self.fp
        return self.tn / denom if denom > 0 else 0.0

    @property
    def f1(self) -> float:
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class BinaryClassifierEvaluator(Evaluator):
    """Predictions/labels are booleans (or {0,1} ints)."""

    def _evaluate(self, predictions: Dataset, labels: Dataset) -> BinaryClassificationMetrics:
        preds = jnp.asarray(predictions.array).reshape(-1).astype(bool)[: predictions.n]
        labs = jnp.asarray(labels.array).reshape(-1).astype(bool)[: labels.n]
        tp = float(jnp.sum(preds & labs))
        fp = float(jnp.sum(preds & ~labs))
        tn = float(jnp.sum(~preds & ~labs))
        fn = float(jnp.sum(~preds & labs))
        return BinaryClassificationMetrics(tp, fp, tn, fn)


class MeanAveragePrecisionEvaluator(Evaluator):
    """VOC-style per-class average precision (reference:
    evaluation/MeanAveragePrecisionEvaluator.scala:13-87, after the enceval
    toolkit MATLAB code).

    predictions: per-example class-score vectors (n, numClasses);
    labels: per-example arrays of valid class ids (host list or (n, k) array).
    Returns a (numClasses,) array of 11-point interpolated APs.
    """

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def _evaluate(self, predictions: Dataset, labels: Dataset):
        scores = np.asarray(predictions.to_numpy(), dtype=np.float64)  # (n, C)
        actual = labels.to_list()
        n = scores.shape[0]
        # (n, C) membership indicators
        gt = np.zeros((n, self.num_classes), dtype=np.float64)
        for i, labs in enumerate(actual):
            for l in np.atleast_1d(np.asarray(labs, dtype=np.int64)):
                if 0 <= l < self.num_classes:
                    gt[i, l] = 1.0

        # Per class: sort by descending score (stable, matching the
        # reference's sortBy(..).reverse tie order), accumulate tp/fp.
        order = np.argsort(-scores, axis=0, kind="stable")  # (n, C)
        gt_sorted = np.take_along_axis(gt, order, axis=0)
        tps = np.cumsum(gt_sorted, axis=0)
        fps = np.cumsum(1.0 - gt_sorted, axis=0)
        totals = gt.sum(axis=0)  # positives per class

        aps = np.zeros(self.num_classes)
        with np.errstate(invalid="ignore", divide="ignore"):
            recalls = tps / totals[None, :]
            precisions = tps / (tps + fps)
        for c in range(self.num_classes):
            ap = 0.0
            for t in np.linspace(0.0, 1.0, 11):
                px = precisions[recalls[:, c] >= t, c]
                ap += (px.max() if px.size else 0.0) / 11.0
            aps[c] = ap
        return jnp.asarray(aps)


class AggregationPolicy:
    """Vote-aggregation policies for augmented test copies
    (reference: AugmentedExamplesEvaluator.scala:9-13)."""

    AVERAGE = "average"
    BORDA = "borda"


class AugmentedExamplesEvaluator(Evaluator):
    """Aggregate predictions of augmented copies of each underlying example
    (grouped by name) before multiclass evaluation
    (reference: evaluation/AugmentedExamplesEvaluator.scala:15-76)."""

    def __init__(self, names, num_classes: int, policy: str = AggregationPolicy.AVERAGE):
        self.names = names if isinstance(names, list) else list(names)
        self.num_classes = num_classes
        if policy not in (AggregationPolicy.AVERAGE, AggregationPolicy.BORDA):
            raise ValueError(f"unknown aggregation policy {policy}")
        self.policy = policy

    @staticmethod
    def _borda(preds: np.ndarray) -> np.ndarray:
        # rank of each class per augmented copy, summed
        # (AugmentedExamplesEvaluator.scala:31-39)
        ranks = np.argsort(np.argsort(preds, axis=1, kind="stable"), axis=1)
        return ranks.sum(axis=0).astype(np.float64)

    def _evaluate(self, predictions: Dataset, labels: Dataset) -> MulticlassMetrics:
        scores = np.asarray(predictions.to_numpy(), dtype=np.float64)
        labs = np.asarray(labels.to_numpy()).reshape(-1).astype(np.int64)
        if len(self.names) != scores.shape[0]:
            raise ValueError("names must align with predictions")

        groups: Dict[Any, list] = {}
        for i, name in enumerate(self.names):
            groups.setdefault(name, []).append(i)

        agg_preds = []
        agg_labels = []
        for name, idxs in groups.items():
            group_labels = labs[idxs]
            if len(set(group_labels.tolist())) != 1:
                raise AssertionError(f"conflicting labels for group {name}")
            p = scores[idxs]
            if self.policy == AggregationPolicy.BORDA:
                agg = self._borda(p)
            else:
                agg = p.mean(axis=0)
            agg_preds.append(int(np.argmax(agg)))
            agg_labels.append(int(group_labels[0]))

        return MulticlassClassifierEvaluator(self.num_classes).evaluate(
            Dataset.of(np.asarray(agg_preds)), Dataset.of(np.asarray(agg_labels))
        )
