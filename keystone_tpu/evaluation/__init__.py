"""Evaluation: metrics and evaluators for pipeline outputs."""

from .metrics import (
    BinaryClassificationMetrics,
    BinaryClassifierEvaluator,
    Evaluator,
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
