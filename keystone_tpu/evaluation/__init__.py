"""Evaluation: metrics and evaluators for pipeline outputs."""

from .metrics import (
    AggregationPolicy,
    AugmentedExamplesEvaluator,
    BinaryClassificationMetrics,
    BinaryClassifierEvaluator,
    Evaluator,
    MeanAveragePrecisionEvaluator,
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
