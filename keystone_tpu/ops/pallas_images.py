"""Pallas TPU kernels for the image-geometry pipeline's hot path.

The reference's image featurizer is im2col into a reused patch-matrix
buffer followed by one BLAS-3 GEMM per image (nodes/images/
Convolver.scala:128-220). The XLA path here (`ops/images/conv.py`)
already fuses the batch into one program, but it still materializes the
full patch tensor ``(n, x', y', p²·c)`` in HBM between the patch
extraction and the filter GEMM — for CIFAR geometry (32×32×3, 6×6
patches) that intermediate is 12× the size of the images themselves, so
the node is HBM-traffic-bound long before the MXU saturates.

The kernel below processes ONE IMAGE PER GRID STEP with the whole
featurization fused in VMEM:

    grid = (n,)
    img (1, X, Y, C) block  ->  in-kernel im2col (static (dx, dy) slices)
                            ->  per-patch mean/variance normalization
                            ->  whitening-mean subtraction
                            ->  (P − μ) @ Fᵀ on the MXU
    out (1, x', y', K) block

so the patch matrix lives only as a (x'·y', p²·c) VMEM tile and the HBM
traffic drops to images-in + features-out. Column order inside a patch
row is row-major over ``(px, py, c)`` — the same contract as
``conv.im2col`` / ``Convolver.pack_filters``, pinned by the
interpreter-equality test against the XLA path.

Numerics: everything is float32 with ``preferred_element_type=float32``
and ``precision=HIGHEST`` on the dot (the same recipe as `pallas_ops`);
the normalization uses the reference's (d−1) variance denominator. The
fused path matches the XLA path to float-associativity tolerance (the
mean/variance reductions associate differently), pinned at 1e-5 relative
in tests/test_pallas_images.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from keystone_tpu.ops.pallas_ops import (
    _COMPILER_PARAMS,  # noqa: F401  (re-exported for symmetry with pallas_ops)
    _dot_kwargs,
    _interpret,
    pallas_direct_ok,
)

__all__ = [
    "conv_featurize",
    "conv_featurize_flops",
    "conv_featurize_ok",
]

# One image block + its patch matrix + the output tile must fit VMEM
# (~16 MB/core) alongside the filter matrix. Past this budget the caller
# should stay on the XLA path (which tiles freely through HBM).
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def _conv_featurize_kernel(
    img_ref, ft_ref, mn_ref, out_ref, *,
    patch_size, xo, yo, channels, normalize, var_constant,
):
    img = img_ref[0]  # (X, Y, C)
    cols = []
    # Static-slice im2col: dx-outer / dy-inner with the channel axis kept
    # intact reproduces row-major (px, py, c) patch columns exactly.
    for dx in range(patch_size):
        for dy in range(patch_size):
            window = img[dx:dx + xo, dy:dy + yo, :]
            cols.append(window.reshape(xo * yo, channels))
    patches = jnp.concatenate(cols, axis=1)  # (xo·yo, p²·c)
    d = patch_size * patch_size * channels
    if normalize:
        mean = jnp.mean(patches, axis=-1, keepdims=True)
        centered = patches - mean
        var = jnp.sum(centered * centered, axis=-1, keepdims=True) / (d - 1.0)
        patches = centered / jnp.sqrt(var + var_constant)
    patches = patches - mn_ref[0]
    feats = jax.lax.dot_general(
        patches, ft_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        **_dot_kwargs(jnp.float32),
    )
    out_ref[0] = feats.reshape(xo, yo, ft_ref.shape[1])


def conv_featurize_flops(n: int, xo: int, yo: int, d: int, k: int) -> float:
    """Executed-FLOP model for the fused featurizer: the filter GEMM's
    2·n·x'·y'·d·k dominates (normalization is O(n·x'·y'·d) — <1% beside a
    k≥128 filter bank and excluded, the same convention as the roofline
    rows in bench.py)."""
    return 2.0 * n * xo * yo * d * k


def conv_featurize_ok(images, filters) -> bool:
    """True when the fused kernel may be dispatched directly on these
    eager operands: Pallas on, operands unsharded, batch-of-images rank,
    and the per-image working set within the VMEM budget."""
    if not pallas_direct_ok(images, filters):
        return False
    if getattr(images, "ndim", 0) != 4:
        return False
    n, X, Y, C = images.shape
    k, d = filters.shape
    p = int(round((d / C) ** 0.5))
    xo, yo = X - p + 1, Y - p + 1
    if xo <= 0 or yo <= 0:
        return False
    working_set = 4 * (X * Y * C + xo * yo * d + xo * yo * k + d * k)
    return working_set <= _VMEM_BUDGET_BYTES


def conv_featurize(
    images,
    filters,
    means=None,
    *,
    patch_size: int,
    normalize_patches: bool = True,
    var_constant: float = 10.0,
    interpret: Optional[bool] = None,
):
    """Fused im2col + normalize + whiten-center + filter GEMM.

    images: (n, X, Y, C) float32, filters: (k, p²·c) packed rows (the
    `Convolver.pack_filters` layout), means: optional (p²·c,) whitening
    means. Returns (n, X−p+1, Y−p+1, k) float32 — bit-for-bit the same
    contract as ``Convolver._convolve``'s XLA path, to the stated
    associativity tolerance.
    """
    images = jnp.asarray(images, dtype=jnp.float32)
    filters = jnp.asarray(filters, dtype=jnp.float32)
    n, X, Y, C = images.shape
    k, d = filters.shape
    xo, yo = X - patch_size + 1, Y - patch_size + 1
    ft = filters.T  # (d, k): contraction layout for the in-kernel dot
    if means is None:
        mn = jnp.zeros((1, d), dtype=jnp.float32)
    else:
        mn = jnp.asarray(means, dtype=jnp.float32).reshape(1, d)

    return pl.pallas_call(
        functools.partial(
            _conv_featurize_kernel,
            patch_size=patch_size,
            xo=xo,
            yo=yo,
            channels=C,
            normalize=bool(normalize_patches),
            var_constant=float(var_constant),
        ),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, X, Y, C), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((d, k), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, xo, yo, k), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, xo, yo, k), jnp.float32),
        interpret=_interpret() if interpret is None else interpret,
    )(images, ft, mn)
