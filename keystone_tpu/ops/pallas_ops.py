"""Pallas TPU kernels for the framework's hot ops.

The reference's performance-critical inner loops are per-partition BLAS-3
calls (Convolver im2col GEMM, KernelGenerator's blocked ``‖x−y‖²`` + exp,
CosineRandomFeatures' broadcast-W GEMM + cos, the BCD solvers' Gramian /
correlation GEMMs — nodes/learning/*, nodes/stats/CosineRandomFeatures.scala).
On TPU those are MXU matmuls; the wins left on the table by stock XLA are
(a) fusing the elementwise epilogue (exp/cos) into the matmul's output tiles
so the (m, n) intermediate never round-trips HBM, and (b) computing AᵀA and
AᵀR in a single pass over A (one HBM read instead of two).

Every kernel here is a tiled matmul with a K-innermost accumulation grid:

    grid = (m_tiles, n_tiles, k_tiles)        # k varies fastest
    acc  = VMEM scratch, zeroed at k == 0
    epilogue applied and written out at k == k_tiles - 1

All kernels take a ``compute_dtype``: with ``bfloat16`` the operand tiles are
cast before hitting the MXU while the accumulator and epilogue stay float32
(preferred_element_type) — the standard TPU mixed-precision recipe.

Wrappers pad inputs to tile multiples (zero rows/cols are exact for the dot
contractions) and slice the result; `interpret=True` is used automatically on
non-TPU backends so the same code paths are unit-testable on CPU.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "countsketch_scatter",
    "gaussian_kernel_block",
    "gaussian_resid_block",
    "cosine_features",
    "gram_corr",
    "gram_corr_sym",
    "gram_corr_sym_acc",
    "gram_corr_acc_ok",
    "pallas_enabled",
    "pallas_direct_ok",
]

_TILE_M = 256
_TILE_N = 256
_TILE_K = 512

# jax >= 0.6 renamed TPUCompilerParams -> CompilerParams; support both so
# the interpreter-mode tests run on either (the dev container pins the
# older spelling, the TPU host the newer).
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot_kwargs(compute_dtype):
    """MXU precision recipe: float32 operands need precision=HIGHEST (the
    TPU hardware default is a single bf16 pass, ~1e-1 absolute error on O(1)
    data); bfloat16 operands hit the MXU natively and accumulate in float32
    via preferred_element_type — with precision pinned to DEFAULT so the
    package-wide f32 matmul default cannot leak a contract_precision<fp32>
    attribute onto bf16 vectors (which crashes Mosaic)."""
    if compute_dtype == jnp.float32:
        return dict(
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    return dict(
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT,
    )


def pallas_enabled() -> bool:
    """True when the Pallas kernels should be used.

    Requires the TPU backend. Multi-device callers reach the kernels through
    ``shard_map`` wrappers (each shard's tile is unsharded inside the body,
    so ``pallas_call`` composes; the collectives around it are explicit
    psums/ppermutes) — see ``parallel.linalg`` (sharded BCD gram+corr) and
    ``parallel.ring`` (ring kernel blocks). Callers that dispatch a kernel
    *directly* on eager arrays must additionally check
    :func:`pallas_direct_ok`, since GSPMD cannot partition a bare
    ``pallas_call`` over a sharded operand. ``KEYSTONE_PALLAS=1`` forces the
    kernels on off-TPU (interpret mode); ``KEYSTONE_NO_PALLAS=1`` forces
    them off.
    """
    if os.environ.get("KEYSTONE_NO_PALLAS"):
        return False
    if os.environ.get("KEYSTONE_PALLAS"):
        return True
    return jax.default_backend() == "tpu"


def pallas_direct_ok(*arrays) -> bool:
    """True when a *direct* (non-shard_map) kernel dispatch is safe for these
    eager operands: Pallas enabled and no operand sharded across devices.
    A bare ``pallas_call`` on a multi-device-sharded array would force XLA
    to gather it to one device — such callers should take a shard_map
    wrapper or the XLA path instead."""
    if not pallas_enabled():
        return False
    for a in arrays:
        sharding = getattr(a, "sharding", None)
        if sharding is None:
            continue
        try:
            if len(sharding.device_set) > 1 and not sharding.is_fully_replicated:
                return False
        except Exception:
            return False
    return True


def _pad_to(x, multiple: int, axis: int):
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, rem)
    return jnp.pad(x, widths)


# ---------------------------------------------------------------------------
# Fused Gaussian kernel block: exp(-gamma * (‖x‖² + ‖y‖² − 2 x·y))
# ---------------------------------------------------------------------------


def _gaussian_kernel_kernel(
    x_ref, y_ref, xn_ref, yn_ref, out_ref, acc_ref, *, gamma, nk, compute_dtype
):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:].astype(compute_dtype),
        y_ref[:].astype(compute_dtype),
        dimension_numbers=(((1,), (1,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )

    @pl.when(k == nk - 1)
    def _():
        sq = xn_ref[:] + yn_ref[:] - 2.0 * acc_ref[:]
        out_ref[:] = jnp.exp(-gamma * jnp.maximum(sq, 0.0)).astype(out_ref.dtype)


def gaussian_kernel_block(
    X,
    Y,
    x_norms,
    y_norms,
    gamma: float,
    compute_dtype=jnp.float32,
    interpret: Optional[bool] = None,
):
    """K[i, j] = exp(-gamma * ‖X_i − Y_j‖²) as one fused Pallas kernel.

    X: (m, d), Y: (n, d), x_norms: (m,), y_norms: (n,). The distance matrix
    is never materialized in HBM — the norm-broadcast + exp epilogue runs on
    the accumulator tile in VMEM (reference computes the same algebra
    unfused: KernelGenerator.scala:121-205). (The bf16x3 / Precision.HIGH
    kernel mode lives on the XLA path only — Mosaic has no 3-pass dot
    lowering; see kernel.py::_gaussian_block.)
    """
    X = jnp.asarray(X, dtype=jnp.float32)
    Y = jnp.asarray(Y, dtype=jnp.float32)
    m, d = X.shape
    n = Y.shape[0]
    xn = jnp.asarray(x_norms, dtype=jnp.float32).reshape(m, 1)
    yn = jnp.asarray(y_norms, dtype=jnp.float32).reshape(1, n)

    tm, tn, tk = min(_TILE_M, m), min(_TILE_N, n), min(_TILE_K, d)
    Xp = _pad_to(_pad_to(X, tm, 0), tk, 1)
    Yp = _pad_to(_pad_to(Y, tn, 0), tk, 1)
    xnp = _pad_to(xn, tm, 0)
    ynp = _pad_to(yn, tn, 1)
    mp, dp = Xp.shape
    np_ = Yp.shape[0]
    nk = dp // tk

    out = pl.pallas_call(
        functools.partial(
            _gaussian_kernel_kernel,
            gamma=float(gamma),
            nk=nk,
            compute_dtype=compute_dtype,
        ),
        grid=(mp // tm, np_ // tn, nk),
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, k: (i, k)),
            pl.BlockSpec((tn, tk), lambda i, j, k: (j, k)),
            pl.BlockSpec((tm, 1), lambda i, j, k: (i, 0)),
            pl.BlockSpec((1, tn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        interpret=_interpret() if interpret is None else interpret,
    )(Xp, Yp, xnp, ynp)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# Fused Gaussian kernel block + residual epilogue: (K_block)ᵀ W
# ---------------------------------------------------------------------------


def _gaussian_resid_kernel(
    x_ref, y_ref, xn_ref, yn_ref, w_ref, out_ref, acc_ref, *,
    gamma, nk, compute_dtype
):
    """Grid (j, i, k): j over the block's columns (slowest — the resid tile
    (j, 0) stays resident across the whole i sweep), i over train-row
    tiles, k over feature tiles. The kernel tile K(i, j) is assembled in
    VMEM at k == nk − 1 and immediately contracted into the residual —
    it is never written to HBM."""
    i = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:].astype(compute_dtype),
        y_ref[:].astype(compute_dtype),
        dimension_numbers=(((1,), (1,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )

    @pl.when((i == 0) & (k == 0))
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(k == nk - 1)
    def _():
        sq = xn_ref[:] + yn_ref[:] - 2.0 * acc_ref[:]
        kt = jnp.exp(-gamma * jnp.maximum(sq, 0.0))
        # The residual contraction runs exact-f32 (kt is f32 from the exp
        # epilogue); its MXU cost is one 128-lane tile per K tile — noise
        # beside the kernel-generation GEMM it rides on.
        out_ref[:] += jax.lax.dot_general(
            kt, w_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            **_dot_kwargs(jnp.float32),
        )


def gaussian_resid_block(
    X,
    Y,
    x_norms,
    y_norms,
    W,
    gamma: float,
    compute_dtype=jnp.float32,
    interpret: Optional[bool] = None,
):
    """resid = K(X, Y)ᵀ @ W with the kernel block fused away.

    X: (m, d) train rows, Y: (n, d) the block's rows, W: (m, k) the dual
    model. Computes K[i, j] = exp(-γ‖X_i − Y_j‖²) tile-by-tile in VMEM and
    contracts each finished tile into the (n, k) residual in the same grid
    step — the (m, n) kernel block never exists in HBM (the separate
    ``gaussian_kernel_block`` + XLA ``K.T @ W`` composition writes and
    re-reads it: 2·m·n·4 bytes per block step at the KRR geometry).

    Padding is exact: ghost train rows have nonzero kernel values but zero
    W rows; ghost feature columns are zero in both operands; ghost block
    rows are sliced off the result. The caller masks ghost-column rows of
    the residual downstream (the same ``valid_col`` mask the unfused path
    applies). Requires W's rows beyond the true train count to be zero —
    the KRR solver's invariant (ghost solves are exactly zero).
    """
    X = jnp.asarray(X, dtype=jnp.float32)
    Y = jnp.asarray(Y, dtype=jnp.float32)
    W = jnp.asarray(W, dtype=jnp.float32)
    m, d = X.shape
    n = Y.shape[0]
    kdim = W.shape[1]
    xn = jnp.asarray(x_norms, dtype=jnp.float32).reshape(m, 1)
    yn = jnp.asarray(y_norms, dtype=jnp.float32).reshape(1, n)

    tm, tn, tk = min(_TILE_M, m), min(_TILE_N, n), min(_TILE_K, d)
    tr = max(128, ((kdim + 127) // 128) * 128)
    Xp = _pad_to(_pad_to(X, tm, 0), tk, 1)
    Yp = _pad_to(_pad_to(Y, tn, 0), tk, 1)
    xnp = _pad_to(xn, tm, 0)
    ynp = _pad_to(yn, tn, 1)
    Wp = _pad_to(_pad_to(W, tm, 0), tr, 1)
    mp, dp = Xp.shape
    np_ = Yp.shape[0]
    nk = dp // tk

    out = pl.pallas_call(
        functools.partial(
            _gaussian_resid_kernel,
            gamma=float(gamma),
            nk=nk,
            compute_dtype=compute_dtype,
        ),
        grid=(np_ // tn, mp // tm, nk),
        in_specs=[
            pl.BlockSpec((tm, tk), lambda j, i, k: (i, k)),
            pl.BlockSpec((tn, tk), lambda j, i, k: (j, k)),
            pl.BlockSpec((tm, 1), lambda j, i, k: (i, 0)),
            pl.BlockSpec((1, tn), lambda j, i, k: (0, j)),
            pl.BlockSpec((tm, tr), lambda j, i, k: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tn, tr), lambda j, i, k: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((np_, tr), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        interpret=_interpret() if interpret is None else interpret,
    )(Xp, Yp, xnp, ynp, Wp)
    return out[:n, :kdim]


# ---------------------------------------------------------------------------
# Fused cosine random features: cos(X Wᵀ + b)
# ---------------------------------------------------------------------------

# Even minimax polynomial for cos on [-π, π] (degree 12, fitted by iterated
# weighted lstsq; max abs error 3.8e-7 in f32 Horner — ~f32 ulp). The VPU's
# library cos costs ~50ms over the bench's 4.3e9 outputs; this Horner form
# is ~2x cheaper and exact to well below bf16 resolution.
_COS_COEFFS = (
    9.999999892578e-01,
    -4.999998919802e-01,
    4.166649038026e-02,
    -1.388780871411e-03,
    2.476998508524e-05,
    -2.707995836252e-07,
    1.724826627109e-09,
)
_TWO_PI = 6.283185307179586


def _fast_cos(x):
    """Range-reduce to [-π, π] and evaluate the even minimax polynomial.

    Accuracy is |x|-proportional through the single-constant f32 range
    reduction: ~4e-7 for |x| ≲ 10 (the cosine-feature regime — O(1)
    pre-activations plus a [0, 2π) phase), ~6e-6 at |x| ≈ 100, ~2e-5 at
    |x| ≈ 300 — the same order as f32's own argument-rounding error for
    the library cos at those magnitudes."""
    q = jnp.floor(x * (1.0 / _TWO_PI) + 0.5)
    r = x - q * _TWO_PI
    r2 = r * r
    acc = jnp.full_like(x, _COS_COEFFS[-1])
    for c in _COS_COEFFS[-2::-1]:
        acc = acc * r2 + c
    return acc


def _cosine_kernel(x_ref, w_ref, b_ref, out_ref, acc_ref, *, nk, compute_dtype):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:].astype(compute_dtype),
        w_ref[:].astype(compute_dtype),
        dimension_numbers=(((1,), (1,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )

    @pl.when(k == nk - 1)
    def _():
        out_ref[:] = _fast_cos(acc_ref[:] + b_ref[:]).astype(out_ref.dtype)


def cosine_features(
    X,
    W,
    b,
    compute_dtype=jnp.float32,
    out_dtype=None,
    interpret: Optional[bool] = None,
):
    """cos(X @ Wᵀ + b) fused into the matmul epilogue.

    X: (m, d), W: (num_out, d), b: (num_out,). The featurized (m, num_out)
    matrix is written once; the pre-activation never exists in HBM
    (reference: CosineRandomFeatures.scala:19-45). ``out_dtype=bfloat16``
    writes the feature matrix at half the HBM footprint for downstream
    bf16 solvers.
    """
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    X = jnp.asarray(X, dtype=jnp.float32)
    W = jnp.asarray(W, dtype=jnp.float32)
    m, d = X.shape
    n = W.shape[0]
    bias = jnp.asarray(b, dtype=jnp.float32).reshape(1, n)

    tm, tn, tk = min(_TILE_M, m), min(_TILE_N, n), min(_TILE_K, d)
    Xp = _pad_to(_pad_to(X, tm, 0), tk, 1)
    Wp = _pad_to(_pad_to(W, tn, 0), tk, 1)
    bp = _pad_to(bias, tn, 1)
    mp, dp = Xp.shape
    np_ = Wp.shape[0]
    nk = dp // tk

    out = pl.pallas_call(
        functools.partial(_cosine_kernel, nk=nk, compute_dtype=compute_dtype),
        grid=(mp // tm, np_ // tn, nk),
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, k: (i, k)),
            pl.BlockSpec((tn, tk), lambda i, j, k: (j, k)),
            pl.BlockSpec((1, tn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        interpret=_interpret() if interpret is None else interpret,
    )(Xp, Wp, bp)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# One-pass Gramian + correlation: (AᵀA, AᵀR)
# ---------------------------------------------------------------------------


def _gram_corr_kernel(
    ai_ref, aj_ref, r_ref, gram_ref, corr_ref, gacc_ref, cacc_ref, *, nk, compute_dtype
):
    """Grid (i, j, k): gram tile (i, j) accumulates AᵢᵀAⱼ over row-tiles k;
    the corr tile (i, :) piggybacks on Aᵢ's residency when j == 0."""
    j = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        gacc_ref[:] = jnp.zeros_like(gacc_ref)

    ai = ai_ref[:].astype(compute_dtype)
    gacc_ref[:] += jax.lax.dot_general(
        ai,
        aj_ref[:].astype(compute_dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )

    @pl.when(k == nk - 1)
    def _():
        gram_ref[:] = gacc_ref[:].astype(gram_ref.dtype)

    @pl.when((j == 0) & (k == 0))
    def _():
        cacc_ref[:] = jnp.zeros_like(cacc_ref)

    @pl.when(j == 0)
    def _():
        cacc_ref[:] += jax.lax.dot_general(
            ai,
            r_ref[:].astype(compute_dtype),
            dimension_numbers=(((0,), (0,)), ((), ())),
            **_dot_kwargs(compute_dtype),
        )

    @pl.when((j == 0) & (k == nk - 1))
    def _():
        corr_ref[:] = cacc_ref[:].astype(corr_ref.dtype)


def gram_corr(
    A,
    R,
    compute_dtype=jnp.float32,
    interpret: Optional[bool] = None,
):
    """(AᵀA, AᵀR) in a single pass over A's rows.

    A: (n, d), R: (n, k). This is the hot contraction of every normal-
    equations / BCD step (reference: mlmatrix NormalEquations; the in-tree
    pattern at BlockWeightedLeastSquares.scala:212-221 computes exactly this
    pair per block). Fusing them halves HBM traffic for A on the correlation
    side and shares the row-tile DMA schedule.
    """
    A = jnp.asarray(A)
    R = jnp.asarray(R, dtype=jnp.float32)
    if A.dtype == jnp.bfloat16:
        compute_dtype = jnp.bfloat16
    n, d = A.shape
    kdim = R.shape[1]

    ti = min(_TILE_M, d)
    tk = min(_TILE_K, n)
    Ap = _pad_to(_pad_to(A, tk, 0), ti, 1)
    # R's column count is small (num classes); pad to the 128-lane minimum.
    tr = max(128, ((kdim + 127) // 128) * 128)
    Rp = _pad_to(_pad_to(R, tk, 0), tr, 1)
    npad, dp = Ap.shape
    nk = npad // tk

    gram, corr = pl.pallas_call(
        functools.partial(_gram_corr_kernel, nk=nk, compute_dtype=compute_dtype),
        grid=(dp // ti, dp // ti, nk),
        in_specs=[
            pl.BlockSpec((tk, ti), lambda i, j, k: (k, i)),
            pl.BlockSpec((tk, ti), lambda i, j, k: (k, j)),
            pl.BlockSpec((tk, tr), lambda i, j, k: (k, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ti, ti), lambda i, j, k: (i, j)),
            pl.BlockSpec((ti, tr), lambda i, j, k: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((dp, dp), jnp.float32),
            jax.ShapeDtypeStruct((dp, tr), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((ti, ti), jnp.float32),
            pltpu.VMEM((ti, tr), jnp.float32),
        ],
        interpret=_interpret() if interpret is None else interpret,
    )(Ap, Ap, Rp)
    return gram[:d, :d], corr[:d, :kdim]


# ---------------------------------------------------------------------------
# Symmetric one-pass Gramian + correlation (upper-triangle blocks only)
# ---------------------------------------------------------------------------


def _gram_corr_sym_kernel(
    ii_ref, jj_ref, ai_ref, aj_ref, r_ref, gram_ref, corr_ref, *,
    nk, compute_dtype
):
    """Grid (p, k): p walks the upper-triangle block pairs (ii[p], jj[p]) in
    row-major order; k sweeps row tiles. The correlation AᵀR rides along on
    the diagonal pairs (one per block row) where Aᵢ is already resident.

    Accumulation happens directly in the f32 OUTPUT tiles: their block
    indices are k-invariant, so Mosaic keeps them resident in VMEM across
    the whole k sweep. With the riding R/corr buffers the column tile must
    stay at 512 (1024-wide bf16 tiles measure ~16.01 MB scoped VMEM — just
    over the limit; see the tiling comment in :func:`gram_corr_sym`); the
    1024-wide layout lives in the R-free split kernels."""
    p = pl.program_id(0)
    k = pl.program_id(1)
    diag = ii_ref[p] == jj_ref[p]

    @pl.when(k == 0)
    def _():
        gram_ref[:] = jnp.zeros_like(gram_ref)

    ai = ai_ref[:].astype(compute_dtype)
    gram_ref[:] += jax.lax.dot_general(
        ai,
        aj_ref[:].astype(compute_dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )

    @pl.when(diag & (k == 0))
    def _():
        corr_ref[:] = jnp.zeros_like(corr_ref)

    @pl.when(diag)
    def _():
        corr_ref[:] += jax.lax.dot_general(
            ai,
            r_ref[:].astype(compute_dtype),
            dimension_numbers=(((0,), (0,)), ((), ())),
            **_dot_kwargs(compute_dtype),
        )


def gram_corr_sym(
    A,
    R,
    compute_dtype=jnp.float32,
    interpret: Optional[bool] = None,
):
    """(AᵀA, AᵀR) computing only the upper triangle of AᵀA and mirroring.

    Does ~half the MXU work and HBM traffic of the dense version for the
    Gramian — the symmetric-rank-k update (BLAS ``syrk``) the reference gets
    from netlib and XLA does not exploit. Block pairs are enumerated
    row-major via scalar-prefetched index arrays.

    A may be bfloat16 — tiles then hit the MXU natively with float32
    accumulation, and HBM traffic is half that of an f32 layout.

    (Column-window variants for the fused BCD solvers live in
    :func:`block_gram_sym` / :func:`block_corr` — those read the window
    strided out of the flat feature buffer with no slice copy.)
    """
    A = jnp.asarray(A)
    R = jnp.asarray(R, dtype=jnp.float32)
    if A.dtype == jnp.bfloat16:
        compute_dtype = jnp.bfloat16
    n, d = A.shape
    kdim = R.shape[1]

    # 512-wide column tiles: with R riding along (corr output + its tile
    # double-buffered next to the gram tile), 1024-wide bf16 tiles measure
    # ~16.01 MB scoped VMEM — 12 KB OVER the 16 MB limit at bs=4096
    # blocks (found by parity.py's TIMIT row through the stacked BCD
    # path). The 1024-wide bf16 layout lives in the R-free split kernels
    # (:func:`block_gram_sym` / :func:`block_corr`), which the flat BCD
    # path uses. Smaller models fall back to one 128-multiple tile.
    ti = min(512, ((d + 127) // 128) * 128)
    tk = min(_TILE_K, n)
    Ap = _pad_to(_pad_to(A, tk, 0), ti, 1)
    Rp = _pad_to(R, tk, 0)
    tr = max(128, ((kdim + 127) // 128) * 128)
    Rp = _pad_to(Rp, tr, 1)
    npad, dp = Ap.shape
    nk = npad // tk
    nt = dp // ti

    pairs = [(i, j) for i in range(nt) for j in range(i, nt)]
    ii = jnp.asarray(np.array([p[0] for p in pairs], dtype=np.int32))
    jj = jnp.asarray(np.array([p[1] for p in pairs], dtype=np.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(len(pairs), nk),
        in_specs=[
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, ii[p])),
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, jj[p])),
            # Off-diagonal pairs never read R: pin their index to block
            # (0, 0) so the tile stays resident instead of streaming the
            # whole of R past every pair.
            pl.BlockSpec(
                (tk, tr),
                lambda p, k, ii, jj: (jnp.where(ii[p] == jj[p], k, 0), 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((ti, ti), lambda p, k, ii, jj: (ii[p], jj[p])),
            pl.BlockSpec((ti, tr), lambda p, k, ii, jj: (ii[p], 0)),
        ],
    )
    gram_u, corr = pl.pallas_call(
        functools.partial(
            _gram_corr_sym_kernel, nk=nk, compute_dtype=compute_dtype
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((dp, dp), jnp.float32),
            jax.ShapeDtypeStruct((dp, tr), jnp.float32),
        ],
        interpret=_interpret() if interpret is None else interpret,
    )(ii, jj, Ap, Ap, Rp)
    # Mirror the (written) upper triangle; lower-triangle blocks are
    # undefined memory, so build from triu explicitly.
    upper = jnp.triu(gram_u)
    gram = upper + jnp.triu(gram_u, 1).T
    return gram[:d, :d], corr[:d, :kdim]


def _strided_ti(dtype, block: int) -> int:
    """Column-tile width for the strided window kernels: 1024 for bf16
    layouts, 512 for f32 (whose doubled tile bytes overflow the 16 MB
    scoped-VMEM limit at 1024)."""
    wide = 1024 if dtype == jnp.bfloat16 else 512
    return min(wide, ((block + 127) // 128) * 128)


def _gram_sym_kernel(ii_ref, jj_ref, ai_ref, aj_ref, gram_ref, *, nk,
                     compute_dtype):
    """Gram-only variant of _gram_corr_sym_kernel: no R operand, no corr
    output — the in-loop strided BCD path computes the correlation with
    :func:`block_corr` instead, because the riding-R buffers are exactly
    what pushes the 1024-tile layout past the 16 MB scoped-VMEM limit
    inside a while_loop."""
    p = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        gram_ref[:] = jnp.zeros_like(gram_ref)

    gram_ref[:] += jax.lax.dot_general(
        ai_ref[:].astype(compute_dtype),
        aj_ref[:].astype(compute_dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )


def block_gram_sym(F, col_start, block: int, interpret: Optional[bool] = None):
    """Symmetric Gramian of a column window of F, tiles read strided (no
    slice copy); ``col_start`` may be traced. Requires ``strided_gram_ok``."""
    F = jnp.asarray(F)
    compute_dtype = jnp.bfloat16 if F.dtype == jnp.bfloat16 else jnp.float32
    n, d = F.shape
    ti = _strided_ti(F.dtype, block)
    tk = min(_TILE_K, n)
    nt = block // ti
    nk = n // tk
    base = jnp.asarray(col_start, jnp.int32) // ti
    pairs = [(i, j) for i in range(nt) for j in range(i, nt)]
    ii = base + jnp.asarray(np.array([p[0] for p in pairs], dtype=np.int32))
    jj = base + jnp.asarray(np.array([p[1] for p in pairs], dtype=np.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(len(pairs), nk),
        in_specs=[
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, ii[p])),
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, jj[p])),
        ],
        out_specs=pl.BlockSpec(
            (ti, ti), lambda p, k, ii, jj: (ii[p] - ii[0], jj[p] - ii[0])
        ),
    )
    gram_u = pl.pallas_call(
        functools.partial(_gram_sym_kernel, nk=nk, compute_dtype=compute_dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((block, block), jnp.float32),
        interpret=_interpret() if interpret is None else interpret,
    )(ii, jj, F, F)
    upper = jnp.triu(gram_u)
    return upper + jnp.triu(gram_u, 1).T


def strided_gram_ok(F, block: int) -> bool:
    """Static alignment check for the strided column-window kernels: row
    count divisible by the k tile, block width by the column tile."""
    n, d = F.shape
    ti = _strided_ti(F.dtype, block)
    return n % min(_TILE_K, n) == 0 and block % ti == 0 and d % block == 0


def _gram_sym_acc_kernel(ii_ref, jj_ref, g_ref, ai_ref, aj_ref, out_ref, *,
                         compute_dtype):
    """out[pair p] = g[pair p] + Σ_k AᵢᵀAⱼ — the accumulating syrk the
    streaming (out-of-core) fit path folds over row tiles: the running
    Gramian rides through as an operand, so the per-tile contribution never
    materializes as a separate (d, d) buffer + add. Upper-triangle pairs
    only (mirror once at the end of the sweep)."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        out_ref[:] = g_ref[:]

    out_ref[:] += jax.lax.dot_general(
        ai_ref[:].astype(compute_dtype),
        aj_ref[:].astype(compute_dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )


def gram_sym_acc(G, F, interpret: Optional[bool] = None):
    """G + FᵀF, accumulating only upper-triangle blocks of the Gramian.

    G: (d, d) float32 with a *meaningful upper triangle only*; F: (n, d).
    Returns a NEW (d, d) buffer whose upper-triangle blocks hold the
    accumulation and whose strictly-lower blocks are UNDEFINED memory
    (never written by any grid step — do not read them). Callers mirror
    once after the last accumulation
    (``jnp.triu(G) + jnp.triu(G, 1).T``). This is the
    per-partition Gramian accumulation of the reference's streaming
    solvers (BlockWeightedLeastSquares.scala:177-313's per-partition
    AᵀA + treeReduce) as a TPU kernel folded over row tiles.

    Alignment: requires ``gram_acc_ok(F)`` (row count divisible by the k
    tile, d by the column tile).
    """
    F = jnp.asarray(F)
    G = jnp.asarray(G, dtype=jnp.float32)
    compute_dtype = jnp.bfloat16 if F.dtype == jnp.bfloat16 else jnp.float32
    n, d = F.shape
    ti = _strided_ti(F.dtype, d)
    tk = min(_TILE_K, n)
    nt = d // ti
    nk = n // tk
    pairs = [(i, j) for i in range(nt) for j in range(i, nt)]
    ii = jnp.asarray(np.array([p[0] for p in pairs], dtype=np.int32))
    jj = jnp.asarray(np.array([p[1] for p in pairs], dtype=np.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(len(pairs), nk),
        in_specs=[
            pl.BlockSpec((ti, ti), lambda p, k, ii, jj: (ii[p], jj[p])),
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, ii[p])),
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, jj[p])),
        ],
        out_specs=pl.BlockSpec(
            (ti, ti), lambda p, k, ii, jj: (ii[p], jj[p])
        ),
    )
    return pl.pallas_call(
        functools.partial(
            _gram_sym_acc_kernel, compute_dtype=compute_dtype
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((d, d), jnp.float32),
        # The riding G operand (f32 in + out at (ti, ti)) pushes scoped
        # VMEM to ~20 MB at 1024-wide bf16 tiles — past the compiler's
        # conservative 16 MB default but well under the chip's 128 MB.
        # Raising the limit keeps the wide tiles (F is re-read (nt+1)
        # times per row tile, so halving nt halves that traffic).
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=48 * 1024 * 1024
        ),
        interpret=_interpret() if interpret is None else interpret,
    )(ii, jj, G, F, F)


def gram_acc_ok(F) -> bool:
    """Static alignment check for :func:`gram_sym_acc`."""
    n, d = F.shape
    ti = _strided_ti(F.dtype, d)
    return n % min(_TILE_K, n) == 0 and d % ti == 0


def _gram_corr_sym_acc_kernel(
    ii_ref, jj_ref, g_ref, c_ref, ai_ref, aj_ref, r_ref, gout_ref, cout_ref,
    *, compute_dtype
):
    """The streaming fold's ONE-kernel chunk step: grid (p, k) over
    upper-triangle block pairs × row tiles, accumulating BOTH

        gout[pair p] = g[pair p] + Σ_k FᵢᵀFⱼ        (the syrk)
        cout[row i]  = c[row i]  + Σ_k FᵢᵀR          (the correlation)

    with the correlation riding the diagonal pairs exactly like
    :func:`gram_corr_sym` — Fᵢ's tiles are already resident there, so the
    correlation adds one (tk, tr) R stream and zero extra reads of F. The
    running (G, C) ride through as operands (same contract as
    :func:`gram_sym_acc`): the per-chunk contribution never materializes
    as separate (d, d)/(d, k) buffers + adds, and the separate XLA FᵀR
    GEMM — which re-read the whole chunk slab from HBM — disappears."""
    p = pl.program_id(0)
    k = pl.program_id(1)
    diag = ii_ref[p] == jj_ref[p]

    @pl.when(k == 0)
    def _():
        gout_ref[:] = g_ref[:]

    ai = ai_ref[:].astype(compute_dtype)
    gout_ref[:] += jax.lax.dot_general(
        ai,
        aj_ref[:].astype(compute_dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )

    @pl.when(diag & (k == 0))
    def _():
        cout_ref[:] = c_ref[:]

    @pl.when(diag)
    def _():
        cout_ref[:] += jax.lax.dot_general(
            ai,
            r_ref[:].astype(compute_dtype),
            dimension_numbers=(((0,), (0,)), ((), ())),
            **_dot_kwargs(compute_dtype),
        )


def gram_corr_sym_acc(G, C, F, R, interpret: Optional[bool] = None):
    """(G + FᵀF, C + FᵀR) in a single pass over F — the fused form of
    ``gram_sym_acc(G, F)`` + an XLA ``FᵀR`` GEMM.

    G: (d, d) f32 with a *meaningful upper triangle only* (the
    :func:`gram_sym_acc` contract — strictly-lower blocks of the result
    are UNDEFINED memory; mirror once after the last accumulation).
    C: (d, k) f32, fully valid in and out. F: (n, d), R: (n, k) — R is
    quantized to F's compute dtype inside the kernel, matching the
    unfused composition's ``FᵀR.astype(F.dtype)`` recipe bit-for-bit in
    operand precision. Requires :func:`gram_corr_acc_ok`.
    """
    F = jnp.asarray(F)
    G = jnp.asarray(G, dtype=jnp.float32)
    R = jnp.asarray(R, dtype=jnp.float32)
    C = jnp.asarray(C, dtype=jnp.float32)
    compute_dtype = jnp.bfloat16 if F.dtype == jnp.bfloat16 else jnp.float32
    n, d = F.shape
    kdim = R.shape[1]
    ti = _strided_ti(F.dtype, d)
    tk = min(_TILE_K, n)
    tr = max(128, ((kdim + 127) // 128) * 128)
    Cp = _pad_to(C, tr, 1)
    Rp = _pad_to(R, tr, 1)
    nt = d // ti
    nk = n // tk
    pairs = [(i, j) for i in range(nt) for j in range(i, nt)]
    ii = jnp.asarray(np.array([p[0] for p in pairs], dtype=np.int32))
    jj = jnp.asarray(np.array([p[1] for p in pairs], dtype=np.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(len(pairs), nk),
        in_specs=[
            pl.BlockSpec((ti, ti), lambda p, k, ii, jj: (ii[p], jj[p])),
            pl.BlockSpec((ti, tr), lambda p, k, ii, jj: (ii[p], 0)),
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, ii[p])),
            pl.BlockSpec((tk, ti), lambda p, k, ii, jj: (k, jj[p])),
            # Off-diagonal pairs never read R: pin their index so the tile
            # stays resident instead of streaming R past every pair.
            pl.BlockSpec(
                (tk, tr),
                lambda p, k, ii, jj: (jnp.where(ii[p] == jj[p], k, 0), 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((ti, ti), lambda p, k, ii, jj: (ii[p], jj[p])),
            # The corr tile (i, 0) is written on row i's diagonal pair —
            # FIRST in the row-major pair order — then stays resident
            # (untouched) across the row's off-diagonal pairs and flushes
            # at the row boundary.
            pl.BlockSpec((ti, tr), lambda p, k, ii, jj: (ii[p], 0)),
        ],
    )
    gout, cout = pl.pallas_call(
        functools.partial(
            _gram_corr_sym_acc_kernel, compute_dtype=compute_dtype
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((d, tr), jnp.float32),
        ],
        # Riding G in+out at (ti, ti) f32 plus the corr/R tiles measures
        # ~22 MB scoped VMEM at 1024-wide bf16 tiles — past the compiler's
        # conservative 16 MB default, well under the chip's 128 MB (same
        # reasoning as gram_sym_acc, plus the ~3 MB corr ride).
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=64 * 1024 * 1024
        ),
        interpret=_interpret() if interpret is None else interpret,
    )(ii, jj, G, Cp, F, F, Rp)
    return gout, cout[:, :kdim]


def gram_corr_acc_ok(F) -> bool:
    """Static alignment check for :func:`gram_corr_sym_acc` (same tiling
    as the gram-only accumulator; R/C widths are lane-padded internally)."""
    return gram_acc_ok(F)


def _block_corr_kernel(base_ref, f_ref, r_ref, out_ref, *, compute_dtype):
    """out[p] = F_windowᵀ R accumulated over row tiles (grid (p, k))."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jax.lax.dot_general(
        f_ref[:].astype(compute_dtype),
        r_ref[:].astype(compute_dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )


def block_corr(F, col_start, block: int, R, interpret: Optional[bool] = None):
    """F[:, col_start:col_start+block]ᵀ @ R with strided reads of F (no
    column-slice copy). ``col_start`` may be traced. Returns (block, k) f32.
    Requires ``strided_gram_ok``."""
    F = jnp.asarray(F)
    R = jnp.asarray(R, dtype=jnp.float32)
    compute_dtype = jnp.bfloat16 if F.dtype == jnp.bfloat16 else jnp.float32
    n, d = F.shape
    kdim = R.shape[1]
    ti = _strided_ti(F.dtype, block)
    tk = min(_TILE_K, n)
    tr = max(128, ((kdim + 127) // 128) * 128)
    Rp = _pad_to(R, tr, 1)
    nt = block // ti
    nk = n // tk
    base = jnp.asarray(col_start, jnp.int32).reshape(1) // ti

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nk),
        in_specs=[
            pl.BlockSpec((tk, ti), lambda p, k, b: (k, b[0] + p)),
            pl.BlockSpec((tk, tr), lambda p, k, b: (k, 0)),
        ],
        out_specs=pl.BlockSpec((ti, tr), lambda p, k, b: (p, 0)),
    )
    corr = pl.pallas_call(
        functools.partial(_block_corr_kernel, compute_dtype=compute_dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((block, tr), jnp.float32),
        interpret=_interpret() if interpret is None else interpret,
    )(base, F, Rp)
    return corr[:, :kdim]


def _block_resid_kernel(base_ref, f_ref, w_ref, r_ref, out_ref, *, compute_dtype):
    """out[m] = R[m] − F_window[m] @ dW accumulated over column tiles
    (grid (m, dstep); the R tile is resident across dstep)."""
    dstep = pl.program_id(1)

    @pl.when(dstep == 0)
    def _():
        out_ref[:] = r_ref[:]

    out_ref[:] -= jax.lax.dot_general(
        f_ref[:].astype(compute_dtype),
        w_ref[:].astype(compute_dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        **_dot_kwargs(compute_dtype),
    )


def block_residual_update(
    F, col_start, block: int, dW, R, interpret: Optional[bool] = None
):
    """R − F[:, col_start:col_start+block] @ dW with strided reads of F —
    the Gauss-Seidel residual update without the column-slice copy. dW is
    (block, k) (cast to F's compute dtype by the caller for MXU-native
    bf16); R is (n, k) f32 and the result keeps f32 accumulation. Requires
    ``strided_gram_ok``."""
    F = jnp.asarray(F)
    R = jnp.asarray(R, dtype=jnp.float32)
    dW = jnp.asarray(dW)
    compute_dtype = jnp.bfloat16 if F.dtype == jnp.bfloat16 else jnp.float32
    n, d = F.shape
    kdim = R.shape[1]
    ti = _strided_ti(F.dtype, block)
    tm = min(_TILE_K, n)
    tr = max(128, ((kdim + 127) // 128) * 128)
    Rp = _pad_to(R, tr, 1)
    Wp = _pad_to(jnp.asarray(dW, dtype=compute_dtype), tr, 1)
    nd = block // ti
    nm = n // tm
    base = jnp.asarray(col_start, jnp.int32).reshape(1) // ti

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nm, nd),
        in_specs=[
            pl.BlockSpec((tm, ti), lambda m, ds, b: (m, b[0] + ds)),
            pl.BlockSpec((ti, tr), lambda m, ds, b: (ds, 0)),
            pl.BlockSpec((tm, tr), lambda m, ds, b: (m, 0)),
        ],
        out_specs=pl.BlockSpec((tm, tr), lambda m, ds, b: (m, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_block_resid_kernel, compute_dtype=compute_dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, tr), jnp.float32),
        interpret=_interpret() if interpret is None else interpret,
    )(base, F, Wp, Rp)
    return out[:, :kdim]


# ---------------------------------------------------------------------------
# Fused CountSketch sparse×dense-random product: S·A without the HBM scatter
# ---------------------------------------------------------------------------


def _countsketch_kernel(
    bucket_ref, sign_ref, idx_ref, val_ref, out_ref, acc_ref, *, s, nc
):
    """Grid (m_tiles, n_tiles, c_tiles), c fastest. Each step forms two
    VMEM tiles and contracts them on the MXU:

      B (tm, tc): the one-hot sketch tile, B[b, i] = sign_i·[bucket_i = b]
                  via a broadcasted-iota comparison against the global
                  bucket row.
      D (tc, tn): the densified chunk-row tile, accumulated over the s
                  nnz slots by one-hot column comparison (a masked slot
                  carries idx = −1 and never matches).

    The densify loop re-runs for every m tile, amortized over the tm
    output rows of the MXU contraction it feeds: its VPU cost is s/tm of
    the MXU MAC count, which is why tm is the largest tile."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    tm, tn = acc_ref.shape
    tc = bucket_ref.shape[1]
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (tm, tc), 0) + i * tm
    B = jnp.where(bucket_ref[:] == b_iota, sign_ref[:], 0.0)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (tc, tn), 1) + j * tn
    D = jnp.zeros((tc, tn), jnp.float32)
    for t in range(s):
        D = D + jnp.where(idx_ref[:, t:t + 1] == col_iota, val_ref[:, t:t + 1], 0.0)
    acc_ref[:] += jax.lax.dot_general(
        B, D,
        dimension_numbers=(((1,), (0,)), ((), ())),
        **_dot_kwargs(jnp.float32),
    )

    @pl.when(k == nc - 1)
    def _():
        out_ref[:] = acc_ref[:]


def countsketch_scatter(
    idx, val, bucket, sign, m: int, d1: int,
    interpret: Optional[bool] = None,
):
    """SA[b, j] = Σ_{i: bucket_i = b} sign_i · Σ_{t: idx[i,t] = j} val[i,t]
    — one chunk's CountSketch contribution S·A as a fused kernel (the
    remaining PAPERS.md item: fast sparse × dense-random products).

    idx: (c, s) int32 global column ids with −1 marking masked/pad slots;
    val: (c, s) float32 with 0 on masked slots; bucket: (c,) int32 in
    [0, m); sign: (c,) float32 ±1 (0 on pad rows). Returns (m, d1) f32.

    The XLA path this replaces flattens (bucket, column) to a scatter-add
    into an (m·d1,) HBM buffer — random single-element updates that
    serialize on TPU. Here the sketch matrix is never materialized in HBM
    at all: both operand tiles are built in VMEM from the (c, s) operands
    and contracted immediately. Accumulation order differs from the
    scatter (tiled f32 MXU sums), so equality against the numpy reference
    is pinned at 1e-5 relative in tests/test_pallas_ops.py, including
    chunk-fold composition.
    """
    idx = jnp.asarray(idx, jnp.int32)
    val = jnp.asarray(val, jnp.float32)
    c, s = idx.shape
    tm = min(512, max(8, ((m + 7) // 8) * 8))
    tn = min(_TILE_N, max(128, ((d1 + 127) // 128) * 128))
    tc = min(_TILE_N, max(128, ((c + 127) // 128) * 128))
    idx_p = jnp.pad(idx, ((0, (-c) % tc), (0, 0)), constant_values=-1)
    val_p = _pad_to(val, tc, 0)
    bkt = _pad_to(jnp.asarray(bucket, jnp.int32).reshape(1, c), tc, 1)
    sgn = _pad_to(jnp.asarray(sign, jnp.float32).reshape(1, c), tc, 1)
    mp = m + ((-m) % tm)
    np_ = d1 + ((-d1) % tn)
    cp = idx_p.shape[0]
    nc = cp // tc

    out = pl.pallas_call(
        functools.partial(_countsketch_kernel, s=s, nc=nc),
        grid=(mp // tm, np_ // tn, nc),
        in_specs=[
            pl.BlockSpec((1, tc), lambda i, j, k: (0, k)),
            pl.BlockSpec((1, tc), lambda i, j, k: (0, k)),
            pl.BlockSpec((tc, s), lambda i, j, k: (k, 0)),
            pl.BlockSpec((tc, s), lambda i, j, k: (k, 0)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        interpret=_interpret() if interpret is None else interpret,
    )(bkt, sgn, idx_p, val_p)
    return out[:m, :d1]
