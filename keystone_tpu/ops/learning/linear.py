"""Dense linear models and exact least-squares estimators.

Reference: nodes/learning/LinearMapper.scala (apply + NormalEquations solve),
nodes/learning/LocalLeastSquaresEstimator.scala (collect-and-solve).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import StandardScaler, StandardScalerModel
from keystone_tpu.parallel import linalg
from keystone_tpu.workflow import LabelEstimator, Transformer


class LinearMapper(Transformer):
    """x -> xᵀX + b, with optional feature scaling
    (reference: LinearMapper.scala:45-62)."""

    def __init__(self, x, b_opt=None, feature_scaler: Optional[StandardScalerModel] = None):
        self.x = jnp.asarray(x)
        self.b_opt = None if b_opt is None else jnp.asarray(b_opt)
        self.feature_scaler = feature_scaler

    def apply(self, v):
        v = jnp.asarray(v)
        if self.feature_scaler is not None:
            v = self.feature_scaler.apply(v)
        out = v @ self.x
        if self.b_opt is not None:
            out = out + self.b_opt
        return out

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.map_batch(self.apply)

    def device_fn(self):
        """Stage-fusion contract: center-scale + GEMM + intercept as one
        row-local array function, so apply chains fuse through the model."""
        return self.apply


class SparseLinearMapper(Transformer):
    """Sparse-input dense-model apply: ``out = X W + b`` over padded-COO
    batches via a model-row gather + nnz reduction — the design matrix is
    never densified (reference: SparseLinearMapper.scala:13-50, the apply
    used by SparseLBFGS's fitted models). Dense inputs fall through to a
    plain GEMM so the mapper slots anywhere a LinearMapper does.
    """

    def __init__(self, x, b_opt=None):
        self.x = jnp.asarray(x)
        self.b_opt = None if b_opt is None else jnp.asarray(b_opt)

    def apply(self, v):
        if isinstance(v, dict) and set(v.keys()) == {"indices", "values"}:
            idx = np.asarray(v["indices"])
            val = np.asarray(v["values"])
            # Drop out-of-range indices on both sides, matching
            # sparse_matmul's documented drop semantics (a bare idx >= 0
            # would clamp idx >= d to the last model row under JAX fancy
            # indexing and add a spurious contribution).
            m = (idx >= 0) & (idx < self.x.shape[0])
            out = jnp.asarray(val[m]) @ self.x[jnp.asarray(idx[m])]
        else:
            out = jnp.asarray(v) @ self.x
        if self.b_opt is not None:
            out = out + self.b_opt
        return out

    def batch_apply(self, data: Dataset) -> Dataset:
        from keystone_tpu.ops.sparse import is_sparse_dataset, sparse_matmul

        if is_sparse_dataset(data):
            out = sparse_matmul(
                jnp.asarray(data.data["indices"]),
                jnp.asarray(data.data["values"]),
                self.x,
            )
            if self.b_opt is not None:
                out = out + self.b_opt
            return Dataset(out, n=data.n, mesh=data.mesh)._rezero_padding()
        return data.map_batch(self.apply)


class LinearMapEstimator(LabelEstimator):
    """Exact OLS/ridge via distributed normal equations
    (reference: LinearMapper.scala:64-98): mean-center features and labels,
    solve (AᵀA + λI) X = AᵀB, keep the label mean as intercept."""

    def __init__(self, lam: Optional[float] = None):
        self.lam = lam

    def device_fit_fn(self):
        """Fit-fusion contract (workflow/fusion.py): mean-centering + the
        normal-equations solve as one traceable function, so upstream
        featurization compiles INTO the fit (same pattern as
        BlockLeastSquaresEstimator.device_fit_fn)."""
        from keystone_tpu.parallel.linalg import _solve_psd
        from keystone_tpu.workflow.fusion import DeviceFit, masked_center

        def fit_fn(F, Y, n_true: int, lam):
            Fc, Yc, fmean, ymean = masked_center(F, Y, n_true)
            Yc = Yc.astype(Fc.dtype)
            # Same normal-equations kernel body as the materialized-
            # features fit(), with λ as a traced operand (λ-sweeps share
            # one compiled program).
            gram = Fc.T @ Fc
            corr = Fc.T @ Yc
            x = _solve_psd(gram, corr, jnp.asarray(lam, Fc.dtype))
            return x, fmean, ymean

        def build(params):
            x, fmean, ymean = params
            return LinearMapper(
                x, b_opt=ymean, feature_scaler=StandardScalerModel(fmean)
            )

        return DeviceFit(
            fit_fn, build,
            operands=(jnp.asarray(float(self.lam or 0.0), jnp.float32),),
            program_key=("LinearMap",),
        )

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        feature_scaler = StandardScaler(normalize_std_dev=False).fit(data)
        label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)

        A = jnp.asarray(feature_scaler.batch_apply(data).array)
        B = jnp.asarray(label_scaler.batch_apply(labels).array)

        x = linalg.normal_equations_solve(A, B, self.lam or 0.0)
        return LinearMapper(x, b_opt=label_scaler.mean, feature_scaler=feature_scaler)

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight
    ) -> float:
        """Analytic cost model (LinearMapper.scala:100-115)."""
        flops = n * d * (d + k) / num_machines
        bytes_scanned = n * d / num_machines + d * d
        network = d * (d + k)
        return max(cpu_weight * flops, mem_weight * bytes_scanned) + network_weight * network

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Capacity model: the matrix plus its centered copy (f32), labels,
        and the Gramian with its Cholesky factor."""
        return (
            8.0 * n * d / num_machines
            + 8.0 * n * k / num_machines
            + 8.0 * d * d
        )

    @staticmethod
    def compute_cost(data: Dataset, labels: Dataset, lam: float, x, b_opt=None) -> float:
        """Ridge loss ||Ax+b - y||²/(2n) + λ/2 ||x||²
        (reference: LinearMapper.scala:124-160)."""
        X = jnp.asarray(data.array)
        Y = jnp.asarray(labels.array)
        preds = X @ jnp.asarray(x)
        if b_opt is not None:
            preds = preds + jnp.asarray(b_opt)
        # Padding rows are zero in X and Y; (0@x + b) - 0 would pollute the sum,
        # so mask to real rows.
        mask = data.valid_mask().astype(preds.dtype)[:, None]
        cost = jnp.sum(((preds - Y) * mask) ** 2) / (2.0 * data.n)
        if lam != 0:
            cost = cost + lam / 2.0 * jnp.sum(jnp.asarray(x) ** 2)
        return float(cost)


class LocalLeastSquaresEstimator(LabelEstimator):
    """Collect-to-host exact least squares via LAPACK lstsq
    (reference: LocalLeastSquaresEstimator.scala:16-61)."""

    def __init__(self, lam: float = 0.0):
        self.lam = lam

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = np.asarray(data.to_numpy(), dtype=np.float64)
        B = np.asarray(labels.to_numpy(), dtype=np.float64)
        a_mean = A.mean(axis=0)
        b_mean = B.mean(axis=0)
        A = A - a_mean
        B = B - b_mean
        if self.lam > 0:
            d = A.shape[1]
            A = np.vstack([A, np.sqrt(self.lam) * np.eye(d)])
            B = np.vstack([B, np.zeros((d, B.shape[1]))])
        x, *_ = np.linalg.lstsq(A, B, rcond=None)
        return LinearMapper(
            x, b_opt=b_mean, feature_scaler=StandardScalerModel(a_mean)
        )


class SketchedLeastSquaresEstimator(LabelEstimator):
    """Randomized (sketch-and-solve) least squares with optional iterative
    Hessian-sketch refinement.

    Beyond-parity solver motivated by the randomized NLA literature
    (Drineas et al., "Faster Least Squares Approximation", arXiv:0710.1435;
    Pilanci & Wainwright iterative Hessian sketch, cf. arXiv:1910.14166):
    a CountSketch S with m = sketch_factor*d rows compresses (A, B) in ONE
    bandwidth-bound pass — a segment-sum scatter of sign-flipped rows, O(nd)
    versus the normal equations' O(nd²) MXU work — then the m×d sketched
    system solves locally. ``refine_iters`` Hessian-sketch steps close the
    gap to the exact solution using the sketched Gramian as a preconditioner
    with exact full-data gradients (each an O(ndk) pass).

    TPU-native: the scatter is ``jax.ops.segment_sum`` over the sharded row
    axis, with per-row signs/buckets drawn once from the JAX PRNG (two
    n-length vectors — the m×n sketch matrix itself is never formed).
    Refinement is guarded: iterates whose gradient norm stops shrinking are
    rejected, so a poor sketch degrades gracefully to the plain
    sketch-and-solve answer instead of diverging.
    """

    def __init__(
        self,
        lam: float = 0.0,
        sketch_factor: int = 8,
        refine_iters: int = 2,
        seed: int = 0,
    ):
        self.lam = lam
        self.sketch_factor = sketch_factor
        self.refine_iters = refine_iters
        self.seed = seed

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        import jax

        feature_scaler = StandardScaler(normalize_std_dev=False).fit(data)
        label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)
        A = jnp.asarray(feature_scaler.batch_apply(data).array)
        B = jnp.asarray(label_scaler.batch_apply(labels).array)
        n_pad, d = A.shape
        n = data.n
        m = min(max(self.sketch_factor * d, d + 1), max(n, d + 1))

        key = jax.random.key(self.seed)
        kb, ks = jax.random.split(key)
        buckets = jax.random.randint(kb, (n_pad,), 0, m)
        signs = jax.random.rademacher(ks, (n_pad,), dtype=A.dtype)
        # Padding rows are zero, so their scattered contribution is zero.
        SA = jax.ops.segment_sum(A * signs[:, None], buckets, num_segments=m)
        SB = jax.ops.segment_sum(B * signs[:, None], buckets, num_segments=m)

        # One factorization serves both the initial sketched solve and the
        # refinement preconditioner.
        gram_s = SA.T @ SA + (self.lam + 1e-8) * jnp.eye(d, dtype=A.dtype)
        chol = jax.scipy.linalg.cholesky(gram_s, lower=True)
        x = jax.scipy.linalg.cho_solve((chol, True), SA.T @ SB)

        # Iterative Hessian sketch refinement: exact gradient, sketched
        # Hessian. x ← x − H_s⁻¹ (Aᵀ(Ax − B) + λx). Guarded: a step is only
        # accepted while the gradient norm shrinks (an undamped fixed point
        # can diverge when the sketch approximates the Gramian poorly).
        prev_gnorm = None
        for _ in range(max(self.refine_iters, 0)):
            grad = A.T @ (A @ x - B) + self.lam * x
            gnorm = float(jnp.linalg.norm(grad))
            if prev_gnorm is not None and gnorm >= prev_gnorm:
                break
            prev_gnorm = gnorm
            x = x - jax.scipy.linalg.cho_solve((chol, True), grad)

        return LinearMapper(x, b_opt=label_scaler.mean, feature_scaler=feature_scaler)

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight
    ) -> float:
        """Sketch pass O(nd) + local solve O(m d²) + refinement passes O(ndk),
        with the same m clamp fit() applies and a per-iteration d*k gradient
        all-reduce in the network term."""
        m = min(max(self.sketch_factor * d, d + 1), max(n, d + 1))
        flops = (n * d + m * d * d + self.refine_iters * n * d * k) / num_machines
        bytes_scanned = (1 + self.refine_iters) * n * d / num_machines
        network = d * (d + k) + self.refine_iters * d * k
        return max(cpu_weight * flops, mem_weight * bytes_scanned) + network_weight * network

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Capacity model: the matrix, the (m, d) sketch, and the sketched
        Gramian + factor."""
        m = min(max(self.sketch_factor * d, d + 1), max(n, d + 1))
        return (
            4.0 * n * d / num_machines
            + 4.0 * n * k / num_machines
            + 4.0 * m * d
            + 8.0 * d * d
        )
