"""Shared per-class statistics and feature-block slicing for the weighted
solvers (BWLS and PerClassWeightedLS both mix class and population moments —
BlockWeightedLeastSquares.scala:120-150, PerClassWeightedLeastSquares.scala:129-167)."""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k", "mw", "absent_to_pop"))
def mixed_class_means(
    X, class_of_row, counts, pop_mean, k: int, mw: float,
    absent_to_pop: bool = False,
):
    """Per-class mixed means ``classMean·mw + popMean·(1−mw)`` as one device
    segment sum over the rows — replacing the reference's per-partition folds.

    ``absent_to_pop=True`` maps classes with no rows to the population mean
    outright (a zero classMean scaled by mw would bias the intercept);
    ``False`` keeps the raw mix (BWLS never reads absent rows).
    """
    sums = jax.ops.segment_sum(X, class_of_row, num_segments=k)
    class_means = sums / jnp.maximum(counts, 1.0)[:, None]
    mixed = class_means * mw + pop_mean[None, :] * (1.0 - mw)
    if absent_to_pop:
        absent = (counts < 0.5).astype(X.dtype)[:, None]
        mixed = mixed * (1.0 - absent) + pop_mean[None, :] * absent
    return mixed


def column_blocks(X, block_size: int, d_eff: int, pad_rows: int) -> List:
    """Slice X into feature-column blocks (the VectorSplitter convention:
    ceil(d/bs) blocks, last one ragged), each zero-padded by ``pad_rows``
    extra rows so per-class dynamic slices never clamp."""
    return [
        jnp.pad(X[:, s : min(s + block_size, d_eff)], ((0, pad_rows), (0, 0)))
        for s in range(0, d_eff, block_size)
    ]
