"""Class-weighted block least squares (the ImageNet solver).

Reference: nodes/learning/BlockWeightedLeastSquares.scala:36-372. The solver
interpolates per-class and population second-moment statistics with
``mixture_weight`` and solves one ridge system per (block, class) pair.

TPU-native layout: rows are sorted by class once on host (replacing Spark's
HashPartitioner(nClasses) reshuffle, BlockWeightedLeastSquares.scala:333-371);
per-class row ranges then become static-shape dynamic slices of the sorted
sharded arrays, so every (block, class) step shares one compiled executable.
Population Gramians reduce over the sharded row axis; per-class (b×b) solves
are replicated.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.block import BlockLinearMapper
from keystone_tpu.ops.learning.classstats import (
    column_blocks,
    mixed_class_means,
)
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.workflow import LabelEstimator

logger = logging.getLogger("keystone_tpu.bwls")


def _class_solve_core(
    A_c,  # (M, b) class rows (zero-padded beyond n_c)
    r_c,  # (M,) class residual column c
    mask,  # (M,) 1 for real class rows, 0 for slice padding
    n_c,  # scalar class count
    pop_cov,  # (b, b)
    pop_mean,  # (b,)
    pop_xtr_col,  # (b,)
    residual_mean_c,  # scalar
    joint_mean_c,  # (b,)
    model_old_col,  # (b,)
    lam,
    mw,
):
    """One per-class column solve (BlockWeightedLeastSquares.scala:241-276)."""
    is_pad = n_c < 0.5  # padded chunk entries have n_c == 0
    n_c = jnp.maximum(n_c, 1.0)
    class_mean = jnp.sum(A_c, axis=0) / n_c
    centered = (A_c - class_mean) * mask[:, None]
    class_cov = centered.T @ centered / n_c
    class_xtr = A_c.T @ r_c / n_c

    mean_diff = class_mean - pop_mean
    joint_xtx = (
        pop_cov * (1.0 - mw)
        + class_cov * mw
        + jnp.outer(mean_diff, mean_diff) * (1.0 - mw) * mw
    )
    mean_mixture_wt = residual_mean_c * (1.0 - mw) + mw * (jnp.sum(r_c) / n_c)
    joint_xtr = (
        pop_xtr_col * (1.0 - mw) + class_xtr * mw - joint_mean_c * mean_mixture_wt
    )

    b = joint_xtx.shape[0]
    lhs = joint_xtx + jnp.eye(b, dtype=A_c.dtype) * lam
    rhs = joint_xtr - model_old_col * lam
    # Padded lanes solve the identity system (zero output) instead of a
    # near-singular one whose NaNs the caller would otherwise discard.
    lhs = jnp.where(is_pad, jnp.eye(b, dtype=A_c.dtype), lhs)
    rhs = jnp.where(is_pad, 0.0, rhs)
    return jnp.linalg.solve(lhs, rhs)


@functools.partial(jax.jit, static_argnames=("M", "lam", "mw"))
def _class_chunk_solve(
    A,  # (n + M, b) block rows, class-sorted, padded
    R,  # (n + M, k) residual
    starts,  # (C,) class row offsets
    counts,  # (C,) class sizes (0 for chunk padding)
    cols,  # (C,) class/column indices
    pop_cov,
    pop_mean,
    pop_xtr,  # (b, k)
    residual_mean,  # (k,)
    joint_means,  # (k, b)
    model_old,  # (b, k)
    M: int,
    lam: float,
    mw: float,
):
    """A chunk of per-class solves as ONE vmapped program — replaces a
    dispatch per class (the reference solves classes inside partition tasks;
    here the class axis is a batch axis on the MXU)."""

    def gather(s, c):
        A_c = jax.lax.dynamic_slice_in_dim(A, s, M, axis=0)
        # Slice both axes at once: a row-slice followed by a column pick
        # would materialize the full (M, k) stripe per class.
        r_c = jax.lax.dynamic_slice(R, (s, c), (M, 1))[:, 0]
        return A_c, r_c

    A_cs, r_cs = jax.vmap(gather)(starts, cols)
    masks = (jnp.arange(M)[None, :] < counts[:, None]).astype(A.dtype)
    A_cs = A_cs * masks[:, :, None]
    r_cs = r_cs * masks
    sol = jax.vmap(
        _class_solve_core,
        in_axes=(0, 0, 0, 0, None, None, 0, 0, 0, 0, None, None),
    )(
        A_cs,
        r_cs,
        masks,
        counts.astype(A.dtype),
        pop_cov,
        pop_mean,
        pop_xtr[:, cols].T,
        residual_mean[cols],
        joint_means[cols],
        model_old[:, cols].T,
        lam,
        mw,
    )
    return sol  # (C, b)


@jax.jit
def _block_pop_stats(A, R, n):
    pop_mean = jnp.sum(A, axis=0) / n
    pop_cov = A.T @ A / n - jnp.outer(pop_mean, pop_mean)
    pop_xtr = A.T @ R / n
    return pop_mean, pop_cov, pop_xtr


@jax.jit
def _block_xtr(A, R, n):
    return A.T @ R / n


@functools.partial(jax.jit, donate_argnums=(2,))
def _residual_update(A, delta, R):
    return R - A @ delta


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """Weighted BCD least squares with per-class covariance mixing."""

    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        num_features: Optional[int] = None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        n, k = labels.n, labels.array.shape[1]
        # Stay on device end to end: rows (possibly mesh-sharded) are sorted
        # by class with a device argsort/gather — replacing the reference's
        # HashPartitioner(nClasses) reshuffle — and all per-class statistics
        # are device segment sums. Only the (k,) class counts come to host,
        # to plan the static chunk shapes. Solve dtype: at least f32 (the
        # reference solves in f64; CPU tests run x64 so f64 inputs keep f64).
        dtype = jnp.promote_types(jnp.asarray(data.array).dtype, jnp.float32)
        X = jnp.asarray(data.array)[:n].astype(dtype)
        Y = jnp.asarray(labels.array)[:n].astype(dtype)
        mw = self.mixture_weight

        class_of_row = jnp.argmax(Y, axis=1)
        order = jnp.argsort(class_of_row, stable=True)
        X = jnp.take(X, order, axis=0)
        Y = jnp.take(Y, order, axis=0)
        class_of_row = jnp.take(class_of_row, order)
        class_counts = np.asarray(
            jnp.bincount(class_of_row, length=k), dtype=np.int64
        )
        class_starts = np.concatenate([[0], np.cumsum(class_counts)[:-1]])
        present = np.nonzero(class_counts > 0)[0]
        if len(present) == 0:
            raise ValueError("BWLS fit requires at least one labeled row")
        M = int(class_counts.max())  # per-class padded slice size

        # jointLabelMean (intercept base): 2mw + 2(1-mw)·n_c/n − 1.
        joint_label_mean = jnp.asarray(
            2 * mw + 2 * (1 - mw) * class_counts / n - 1.0, dtype=dtype
        )

        d_eff = self.num_features or X.shape[1]
        blocks_d = column_blocks(X, self.block_size, d_eff, M)
        num_blocks = len(blocks_d)
        R = jnp.pad(Y - joint_label_mean, ((0, M), (0, 0)))

        counts_d = jnp.asarray(class_counts, dtype=dtype)
        models = [jnp.zeros((b.shape[1], k), dtype=dtype) for b in blocks_d]
        residual_mean = jnp.sum(R, axis=0) / n
        block_stats = [None] * num_blocks

        n_t = jnp.asarray(float(n), dtype=dtype)

        for it in range(self.num_iter):
            for bi in range(num_blocks):
                A = blocks_d[bi]
                if block_stats[bi] is None:
                    pop_mean, pop_cov, pop_xtr = _block_pop_stats(A, R, n_t)
                    # jointMeans per class: classMean·mw + popMean·(1−mw),
                    # class means as one device segment sum over the block.
                    joint_means = mixed_class_means(
                        A[: A.shape[0] - M] if M else A,
                        class_of_row, counts_d, pop_mean, k, float(mw),
                    )
                    block_stats[bi] = (pop_cov, pop_mean, joint_means)
                else:
                    pop_cov, pop_mean, joint_means = block_stats[bi]
                    pop_xtr = _block_xtr(A, R, n_t)
                joint_means_j = block_stats[bi][2]

                model_old = models[bi]
                # Solve classes in fixed-size vmapped chunks (one dispatch
                # per chunk, one executable across chunks; the final chunk is
                # padded with count-0 entries whose outputs are discarded).
                chunk = min(32, len(present))
                new_cols = []
                for lo in range(0, len(present), chunk):
                    sel = present[lo : lo + chunk]
                    pad_len = chunk - len(sel)
                    sel_p = np.concatenate([sel, np.repeat(sel[-1:], pad_len)])
                    sol = _class_chunk_solve(
                        A,
                        R,
                        jnp.asarray(class_starts[sel_p]),
                        jnp.asarray(
                            np.where(
                                np.arange(chunk) < len(sel),
                                class_counts[sel_p],
                                0,
                            )
                        ),
                        jnp.asarray(sel_p),
                        pop_cov,
                        pop_mean,
                        pop_xtr,
                        residual_mean,
                        joint_means_j,
                        model_old,
                        M=M,
                        lam=float(self.lam),
                        mw=float(mw),
                    )
                    new_cols.append(sol[: len(sel)])

                delta = jnp.zeros((A.shape[1], k), dtype=dtype)
                delta = delta.at[:, jnp.asarray(present)].set(
                    jnp.concatenate(new_cols, axis=0).T
                )
                models[bi] = model_old + delta
                R = _residual_update(A, delta, R)
                residual_mean = jnp.sum(R, axis=0) / n
                mesh_lib.sync_if_cpu(residual_mean)
                logger.info("BWLS pass %d block %d done", it, bi)

        # Intercept: jointLabelMean − Σ_d jointMeans[c, d]·W[d, c]
        # (BlockWeightedLeastSquares.scala:315-320).
        full_model = jnp.concatenate(models, axis=0)
        joint_means_all = jnp.concatenate(
            [stats[2] for stats in block_stats], axis=1
        )  # (k, D)
        final_b = joint_label_mean - jnp.sum(
            joint_means_all * full_model.T, axis=1
        )
        return BlockLinearMapper(models, self.block_size, b_opt=final_b)


