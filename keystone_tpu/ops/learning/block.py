"""Block-partitioned linear models and the block least squares solver.

Reference: nodes/learning/BlockLinearMapper.scala — the model is a sequence of
per-feature-block weight matrices; applying it sums per-block GEMM partial
products plus an intercept; fitting runs block coordinate descent with L2
(via the in-tree BCD of :mod:`keystone_tpu.parallel.linalg`, subsuming mlmatrix
``BlockCoordinateDescent`` + ``NormalEquations``).

This is the reference's model-parallel axis: feature blocks over devices map
to the mesh ``model`` axis, while rows stay sharded over ``data``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.util import VectorSplitter
from keystone_tpu.parallel import linalg
from keystone_tpu.workflow import LabelEstimator, Transformer


class BlockLinearMapper(Transformer):
    """Apply a block-partitioned linear model: sum per-block GEMMs + intercept
    (reference: BlockLinearMapper.scala:22-138)."""

    def __init__(
        self,
        xs: Sequence,
        block_size: int,
        b_opt=None,
        feature_scalers: Optional[Sequence[Transformer]] = None,
    ):
        self.xs = [jnp.asarray(x) for x in xs]
        self.block_size = block_size
        self.b_opt = None if b_opt is None else jnp.asarray(b_opt)
        self.feature_scalers = feature_scalers
        self.splitter = VectorSplitter(block_size)

    def _scaled_block(self, block, i: int):
        if self.feature_scalers is None:
            return block
        return self.feature_scalers[i].apply(block)

    def apply(self, x):
        blocks = self.splitter.split_vector(x)
        out = sum(
            self._scaled_block(blk, i) @ self.xs[i] for i, blk in enumerate(blocks)
        )
        if self.b_opt is not None:
            out = out + self.b_opt
        return out

    def device_fn(self):
        """Stage-fusion contract: the whole blockwise model as one
        row-local array function — center by the concatenated means, one
        flat GEMM, add the intercept. Lets the apply path fuse with an
        upstream featurize program into a single dispatch."""
        W_flat = jnp.concatenate(list(self.xs), axis=0)
        mean = std = None
        if self.feature_scalers is not None:
            if any(getattr(s, "mean", None) is None for s in self.feature_scalers):
                return None  # non-scaler transformers: keep the block path
            mean = jnp.concatenate(
                [jnp.asarray(s.mean) for s in self.feature_scalers]
            )
            stds = [getattr(s, "std", None) for s in self.feature_scalers]
            if any(s is not None for s in stds):
                std = jnp.concatenate(
                    [
                        jnp.ones_like(jnp.asarray(self.feature_scalers[i].mean))
                        if stds[i] is None else jnp.asarray(stds[i])
                        for i in range(len(stds))
                    ]
                )
        b = self.b_opt

        def fn(X):
            if mean is not None:
                X = X - mean
            if std is not None:
                X = X / std
            out = X @ W_flat
            return out if b is None else out + b

        return fn

    def batch_apply(self, data: Dataset) -> Dataset:
        blocks = self.splitter.apply(data)
        return self.apply_blocks(blocks)

    def apply_blocks(self, blocks: List[Dataset]) -> Dataset:
        """Apply to pre-split feature blocks (BlockLinearMapper.scala:50-73)."""
        first = blocks[0]
        out = None
        for i, block in enumerate(blocks):
            X = jnp.asarray(block.array)
            if self.feature_scalers is not None:
                X = X - self.feature_scalers[i].mean
                if self.feature_scalers[i].std is not None:
                    X = X / self.feature_scalers[i].std
            partial = X @ self.xs[i]
            out = partial if out is None else out + partial
        if self.b_opt is not None:
            out = out + self.b_opt
        result = Dataset(out, n=first.n, mesh=first.mesh)
        return result._rezero_padding()

    def apply_and_evaluate(self, data: Dataset, evaluator) -> None:
        """Stream per-block partial predictions to an evaluator callback
        (BlockLinearMapper.scala:95-137)."""
        blocks = self.splitter.apply(data)
        acc = None
        for i, block in enumerate(blocks):
            X = jnp.asarray(block.array)
            if self.feature_scalers is not None:
                X = X - self.feature_scalers[i].mean
                if self.feature_scalers[i].std is not None:
                    X = X / self.feature_scalers[i].std
            partial = X @ self.xs[i]
            acc = partial if acc is None else acc + partial
            preds = acc if self.b_opt is None else acc + self.b_opt
            evaluator(Dataset(preds, n=data.n, mesh=data.mesh)._rezero_padding())


def _stack_fits_memory(A_blocks, num_iter: int) -> bool:
    """True when the fused path's transient peak fits comfortably in device
    memory. At stack time up to THREE full-size copies of the feature blocks
    are live (the unscaled splits, the scaled list, and the stack), plus the
    multi-epoch Gramian stash (nb * d_b^2)."""
    try:
        sizes = [
            int(a.nbytes) if hasattr(a, "nbytes") else int(np.asarray(a).nbytes)
            for a in A_blocks
        ]
        total = sum(sizes)
        stash = 0
        if num_iter > 1 and A_blocks:
            d_b = int(A_blocks[0].shape[1])
            itemsize = getattr(A_blocks[0], "dtype", np.dtype(np.float32)).itemsize
            stash = len(A_blocks) * d_b * d_b * max(int(itemsize), 4)
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if not limit:
            return True  # backends without memory stats (CPU): no constraint
        return 3 * total + stash < 0.6 * int(limit)
    except Exception:
        return True


class BlockLeastSquaresEstimator(LabelEstimator):
    """Block coordinate descent ridge regression
    (reference: BlockLinearMapper.scala:199-283).

    Label and per-block feature mean-centering via StandardScaler
    (normalize_std_dev=False), then Gauss-Seidel BCD over feature blocks;
    weight = 3*num_iter + 1 passes over the input.
    """

    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float = 0.0,
        num_features: Optional[int] = None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.num_features = num_features

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def device_fit_fn(self):
        """Fit-fusion contract (workflow/fusion.py): the whole fit —
        feature/label mean-centering + the fused-flat BCD sweep — as one
        traceable function, so the optimizer can compile upstream
        featurization INTO it (featurize + solve = ONE program; the
        feature matrix never materializes between dispatches)."""
        from keystone_tpu.workflow.fusion import DeviceFit, masked_center
        from keystone_tpu.ops.stats import StandardScalerModel

        bs = self.block_size

        def fit_fn(F, Y, n_true: int, lam):
            Fc, Yc, fmean, ymean = masked_center(F, Y, n_true)
            W_stack = linalg.bcd_least_squares_fused_flat(
                Fc, Yc, bs, lam=lam, num_iter=self.num_iter
            )
            return W_stack, fmean, ymean

        def build(params):
            W_stack, fmean, ymean = params
            nb = W_stack.shape[0]
            scalers = [
                StandardScalerModel(fmean[i * bs : (i + 1) * bs])
                for i in range(nb)
            ]
            return BlockLinearMapper(
                [W_stack[i] for i in range(nb)], bs, b_opt=ymean,
                feature_scalers=scalers,
            )

        def supports(d_feat: int) -> bool:
            return d_feat % bs == 0 and self.num_features in (None, d_feat)

        # λ rides as a traced operand and the program is shared by logical
        # identity: a λ-sweep building a fresh estimator per λ compiles
        # the fused featurize+fit ONCE (workflow/fusion.py DeviceFit).
        return DeviceFit(
            fit_fn, build, supports,
            operands=(jnp.asarray(self.lam, jnp.float32),),
            program_key=("BlockLS", bs, self.num_iter, self.num_features),
        )

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        splitter = VectorSplitter(self.block_size, self.num_features)
        blocks = splitter.apply(data)
        return self.fit_blocks(blocks, labels)

    def fit_blocks(self, blocks: List[Dataset], labels: Dataset) -> BlockLinearMapper:
        label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)
        B = jnp.asarray(label_scaler.batch_apply(labels).array)

        feature_scalers = [
            StandardScaler(normalize_std_dev=False).fit(block) for block in blocks
        ]
        A_blocks = [
            jnp.asarray(scaler.batch_apply(block).array)
            for block, scaler in zip(blocks, feature_scalers)
        ]

        def _is_multi(ds):
            return ds.mesh is not None and any(
                s > 1 for s in dict(ds.mesh.shape).values()
            )

        multi_device = _is_multi(labels) or any(_is_multi(b) for b in blocks)
        if (
            len({a.shape for a in A_blocks}) == 1
            and not multi_device
            and _stack_fits_memory(A_blocks, self.num_iter)
        ):
            # Equal-size blocks on one device (the common case): the whole
            # (epochs x blocks) sweep is one compiled program. Multi-device
            # data keeps the stepwise path (per-block programs partition
            # cleanly and match the unsharded reduction order); so do fits
            # whose stacked copy would not fit beside the blocks in HBM.
            stacked = jnp.stack(A_blocks)
            del A_blocks  # the stack is a full second copy; drop the list
            W_stack = linalg.bcd_least_squares_fused(
                stacked, B, lam=self.lam, num_iter=self.num_iter
            )
            Ws = [W_stack[i] for i in range(W_stack.shape[0])]
        else:
            mesh = next(
                (d.mesh for d in [labels, *blocks] if d.mesh is not None), None
            )
            Ws = linalg.bcd_least_squares(
                A_blocks, B, lam=self.lam, num_iter=self.num_iter,
                mesh=mesh if multi_device else None,
            )
        return BlockLinearMapper(
            Ws, self.block_size, b_opt=label_scaler.mean, feature_scalers=feature_scalers
        )

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight
    ) -> float:
        """Analytic cost model (BlockLinearMapper.scala:268-282)."""
        import math

        flops = n * d * (self.block_size + k) / num_machines
        bytes_scanned = n * d / num_machines + d * k
        network = 2.0 * (d * (self.block_size + k)) * math.log2(max(num_machines, 2))
        return self.num_iter * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Capacity model for the selector's HBM feasibility cut: the fit
        holds the feature blocks plus a scaled/stacked second copy (f32),
        labels twice (raw + centered), and the multi-epoch Gramian stash."""
        return (
            8.0 * n * d / num_machines
            + 8.0 * n * k / num_machines
            + 4.0 * d * self.block_size
        )
