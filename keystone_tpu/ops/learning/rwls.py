"""Per-class weighted least squares and the re-weighted BCD core.

Reference: nodes/learning/PerClassWeightedLeastSquares.scala:31-223 (driver:
one weighted least-squares problem per class, assembled into a
BlockLinearMapper) and nodes/learning/internal/ReWeightedLeastSquares.scala:18-142
(the weighted block-coordinate-descent core solving
``W = (Xzmᵀ diag(w) Xzm + λI) \\ Xzmᵀ (w ∘ Y_zm)`` with feature-mean-centered
X and a maintained weighted residual).

TPU-native formulation
----------------------
The reference runs ``nClasses`` *sequential* distributed BCD problems — each
class re-reads the whole dataset per pass (classWiseModels loop,
PerClassWeightedLeastSquares.scala:96-107). Here every class is solved
simultaneously per feature block by decomposing each class's weighted Gramian
around shared population terms. With per-class weights
``w_c = α + β_c·1[class=c]`` (α = (1−mw)/n, β_c = mw/n_c — computeWeights,
PerClassWeightedLeastSquares.scala:170-182) and the class-mixed feature mean
μ_c (computeJointFeatureMean, :129-167):

    Xzm_cᵀ diag(w_c) Xzm_c
        = α·XᵀX + β_c·X_cᵀX_c − μ_c t̃_cᵀ − t̃_c μ_cᵀ + c0_c·μ_c μ_cᵀ

where ``X_cᵀX_c`` is the class-segment Gramian from class-sorted rows,
``t̃_c = α·s + β_c·s_c`` (block column sums), and ``c0_c = α·n + β_c·n_c``
(= 1 for present classes). The population Gramian ``XᵀX`` is ONE MXU GEMM
shared by all classes; the class Gramians cost one total pass over the sorted
rows; right-hand sides and residual updates for ALL classes are three (n, k)
GEMMs plus rank-one / per-class-scalar corrections; the per-class (b, b)
solves run batched over class chunks. Total per-block cost is ~2 data passes
instead of the reference's nClasses passes.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.block import BlockLinearMapper
from keystone_tpu.ops.learning.classstats import (
    column_blocks,
    mixed_class_means,
)
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.workflow import LabelEstimator


# ---------------------------------------------------------------------------
# ReWeightedLeastSquaresSolver — the general weighted BCD core
# ---------------------------------------------------------------------------


@jax.jit
def _rwls_gram(Xb, mu_b, w):
    """Xzmᵀ diag(w) Xzm for one block (cached across passes — the aTaCache of
    ReWeightedLeastSquares.scala:92-101)."""
    Xzm = Xb - mu_b[None, :]
    return (Xzm * w[:, None]).T @ Xzm


@functools.partial(jax.jit, static_argnames=("lam",), donate_argnums=(3,))
def _rwls_step(Xb, mu_b, w, D, W_old, gram, lam: float):
    """One weighted Gauss-Seidel block update
    (ReWeightedLeastSquares.scala:103-135).

    ``D = w∘Y_zm − Σ_b w∘(Xzm_b W_b)`` is the weighted residual (the
    reference maintains ``residual = Σ_b w∘(Xzm_b W_b)`` and recombines with
    ``w∘Y`` in the aTb map; the two are the same iteration). Returns
    (W_new, D_new).
    """
    Xzm = Xb - mu_b[None, :]
    rhs = Xzm.T @ (D + w[:, None] * (Xzm @ W_old))
    b = gram.shape[0]
    W_new = jnp.linalg.solve(gram + lam * jnp.eye(b, dtype=gram.dtype), rhs)
    D_new = D - w[:, None] * (Xzm @ (W_new - W_old))
    return W_new, D_new


class ReWeightedLeastSquaresSolver:
    """Weighted BCD: ``W = (Xᵀ diag(B) X + λI) \\ Xᵀ (B ∘ Y)`` over feature
    blocks with feature-mean centering (reference:
    internal/ReWeightedLeastSquares.scala:18-142)."""

    @staticmethod
    def train_with_l2(
        feature_blocks: Sequence,
        labels_zm,
        weights,
        feature_mean,
        lam: float,
        num_iter: int,
    ) -> Tuple[List[jax.Array], jax.Array]:
        """Returns (per-block models, final weighted residual
        ``Σ_b B∘(Xzm_b W_b)``) — the reference's (model, residual) pair."""
        labels_zm = jnp.asarray(labels_zm)
        dtype = jnp.promote_types(labels_zm.dtype, jnp.float32)
        labels_zm = labels_zm.astype(dtype)
        w = jnp.asarray(weights, dtype=dtype)
        mu = jnp.asarray(feature_mean, dtype=dtype)
        blocks = [jnp.asarray(b).astype(dtype) for b in feature_blocks]
        k = labels_zm.shape[1]

        offsets = np.concatenate(
            [[0], np.cumsum([b.shape[1] for b in blocks])]
        )
        mus = [mu[offsets[i] : offsets[i + 1]] for i in range(len(blocks))]

        grams = [None] * len(blocks)
        models = [
            jnp.zeros((b.shape[1], k), dtype=dtype) for b in blocks
        ]
        D = w[:, None] * labels_zm
        for _ in range(max(int(num_iter), 1)):
            for bi, Xb in enumerate(blocks):
                if grams[bi] is None:
                    grams[bi] = _rwls_gram(Xb, mus[bi], w)
                models[bi], D = _rwls_step(
                    Xb, mus[bi], w, D, models[bi], grams[bi], float(lam)
                )
                mesh_lib.sync_if_cpu(D)
        residual = w[:, None] * labels_zm - D
        return models, residual


# ---------------------------------------------------------------------------
# PerClassWeightedLeastSquaresEstimator — all classes batched per block
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=())
def _pcwls_prep(X_pad, W_old, jfm_blk, D, onehot, valid, alpha, beta):
    """Per-block, all-classes right-hand-side ingredients.

    T[:, c] = D[:, c] + w_c∘(Xzm_c W_old_c) expanded through the α/β weight
    split; returns (P = XᵀT, t = 1ᵀT). The per-class centering enters as the
    rank-one corrections ``P[:,c] − t_c μ_c`` applied in the chunk solve.
    """
    U = X_pad @ W_old  # (n+M, k)
    o = jnp.einsum("cb,bc->c", jfm_blk, W_old)  # μ_cᵀ W_old_c
    Um = (U - o[None, :]) * valid[:, None]
    V = alpha * Um + onehot * Um * beta[None, :]
    T = D + V
    P = X_pad.T @ T  # (b, k)
    t = jnp.sum(T, axis=0)  # (k,)
    return P, t


@functools.partial(jax.jit, static_argnames=())
def _pcwls_residual_update(X_pad, dW, jfm_blk, D, onehot, valid, alpha, beta):
    """D −= w_c∘(Xzm_c ΔW_c) for every class at once (one (n,k) GEMM)."""
    U = X_pad @ dW
    o = jnp.einsum("cb,bc->c", jfm_blk, dW)
    Um = (U - o[None, :]) * valid[:, None]
    V = alpha * Um + onehot * Um * beta[None, :]
    return D - V


@functools.partial(jax.jit, static_argnames=("M", "lam"))
def _pcwls_chunk_solve(
    A,  # (n+M, b) class-sorted padded block (raw, uncentered)
    starts,  # (C,) class row offsets
    counts,  # (C,) class sizes (0 padding lanes)
    G,  # (b, b) population Gramian XᵀX
    s,  # (b,) block column sums
    seg_s,  # (C, b) class column sums s_c
    jfm,  # (C, b) per-class mixed feature means μ_c
    P_sel,  # (C, b) XᵀT columns for these classes
    t_sel,  # (C,) 1ᵀT for these classes
    beta,  # (C,)
    c0,  # (C,) α·n + β_c·n_c (1 for present classes)
    alpha,
    M: int,
    lam: float,
):
    """Batched per-class solves for one chunk of classes: build each class's
    weighted Gramian from the shared population terms + its segment Gramian,
    then one batched (C, b, b) solve on the MXU."""

    def gather(start):
        return jax.lax.dynamic_slice_in_dim(A, start, M, axis=0)

    A_c = jax.vmap(gather)(starts)  # (C, M, b)
    mask = (jnp.arange(M)[None, :] < counts[:, None]).astype(A.dtype)
    A_c = A_c * mask[:, :, None]
    G_c = jnp.einsum("cmb,cmd->cbd", A_c, A_c)  # class segment Gramians

    t_tilde = alpha * s[None, :] + beta[:, None] * seg_s  # (C, b)
    lhs = (
        alpha * G[None]
        + beta[:, None, None] * G_c
        - jfm[:, :, None] * t_tilde[:, None, :]
        - t_tilde[:, :, None] * jfm[:, None, :]
        + c0[:, None, None] * (jfm[:, :, None] * jfm[:, None, :])
    )
    b = G.shape[0]
    lhs = lhs + lam * jnp.eye(b, dtype=A.dtype)[None]
    # Zero-count padding lanes solve the identity system (defined output).
    is_pad = (counts < 0.5)[:, None, None]
    lhs = jnp.where(is_pad, jnp.eye(b, dtype=A.dtype)[None], lhs)
    rhs = P_sel - t_sel[:, None] * jfm  # (C, b)
    rhs = jnp.where(is_pad[:, :, 0], 0.0, rhs)
    return jnp.linalg.solve(lhs, rhs[..., None])[..., 0]  # (C, b)


class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    """Per-class weighted BCD least squares
    (reference: PerClassWeightedLeastSquares.scala:31-223).

    Each class c solves an independent weighted ridge problem with weights
    ``(1−mw)/n`` on every row plus ``mw/n_c`` extra on its own rows, features
    centered by ``μ_c = mw·classMean_c + (1−mw)·popMean`` and labels by the
    jointLabelMean — exactly the reference's per-class invocation of
    ReWeightedLeastSquaresSolver, but with all classes batched per block
    (see module docstring). Classes absent from the data get β_c = 0 (pure
    population weighting) instead of the reference's division by a zero
    count.
    """

    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        num_features: Optional[int] = None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        n, k = labels.n, labels.array.shape[1]
        dtype = jnp.promote_types(jnp.asarray(data.array).dtype, jnp.float32)
        X = jnp.asarray(data.array)[:n].astype(dtype)
        Y = jnp.asarray(labels.array)[:n].astype(dtype)
        mw = float(self.mixture_weight)

        # Class-sort rows on device (the HashPartitioner reshuffle analog).
        class_of_row = jnp.argmax(Y, axis=1)
        order = jnp.argsort(class_of_row, stable=True)
        X = jnp.take(X, order, axis=0)
        class_of_row = jnp.take(class_of_row, order)
        counts = np.asarray(jnp.bincount(class_of_row, length=k), dtype=np.int64)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        present = np.nonzero(counts > 0)[0]
        if len(present) == 0:
            raise ValueError("PCWLS fit requires at least one labeled row")
        M = int(counts.max())

        alpha = (1.0 - mw) / n
        beta = np.where(counts > 0, mw / np.maximum(counts, 1), 0.0)
        c0 = alpha * n + beta * counts  # 1 for present classes
        # jointLabelMean (computeJointLabelMean, :184-191).
        jlm = (counts / n) * 2.0 * (1.0 - mw) - 1.0 + 2.0 * mw

        beta_d = jnp.asarray(beta, dtype=dtype)
        alpha_d = jnp.asarray(alpha, dtype=dtype)
        onehot = jax.nn.one_hot(class_of_row, k, dtype=dtype)
        onehot = jnp.pad(onehot, ((0, M), (0, 0)))
        valid = jnp.pad(jnp.ones((n,), dtype=dtype), (0, M))

        pop_mean = jnp.sum(X, axis=0) / n
        # μ_c rows: mw·classMean + (1−mw)·popMean; absent classes fall back
        # to the population mean (classMean := 0 contribution scaled by mw
        # would bias the intercept — use popMean for both mixture terms).
        jfm = mixed_class_means(
            X, class_of_row, jnp.asarray(counts, dtype=dtype), pop_mean,
            k, mw, absent_to_pop=True,
        )

        d_eff = self.num_features or X.shape[1]
        bs = self.block_size
        col_starts = list(range(0, d_eff, bs))

        # Zero-meaned labels in the sorted order; D starts at w_c∘y_zm_c.
        Y_zm = jnp.take(Y, order, axis=0) - jnp.asarray(jlm, dtype=dtype)[None, :]
        Y_zm = jnp.pad(Y_zm, ((0, M), (0, 0)))
        D = (alpha_d * Y_zm + onehot * Y_zm * beta_d[None, :]) * valid[:, None]

        blocks = column_blocks(X, bs, d_eff, M)
        jfm_blocks = [
            jfm[:, s : min(s + bs, d_eff)] for s in col_starts
        ]
        models = [jnp.zeros((b.shape[1], k), dtype=dtype) for b in blocks]

        grams = [None] * len(blocks)  # population XᵀX per block
        col_sums = [None] * len(blocks)
        seg_sums = [None] * len(blocks)

        chunk = int(min(16, len(present)))
        for _ in range(max(int(self.num_iter), 1)):
            for bi, A in enumerate(blocks):
                if grams[bi] is None:
                    A_real = A[: A.shape[0] - M] if M else A
                    grams[bi] = A_real.T @ A_real
                    col_sums[bi] = jnp.sum(A_real, axis=0)
                    seg_sums[bi] = jax.ops.segment_sum(
                        A_real, class_of_row, num_segments=k
                    )
                P, t = _pcwls_prep(
                    A, models[bi], jfm_blocks[bi], D, onehot, valid,
                    alpha_d, beta_d,
                )
                W_new = jnp.array(models[bi])
                for lo in range(0, len(present), chunk):
                    sel = present[lo : lo + chunk]
                    pad_len = chunk - len(sel)
                    sel_p = np.concatenate([sel, np.repeat(sel[-1:], pad_len)])
                    counts_sel = np.where(
                        np.arange(chunk) < len(sel), counts[sel_p], 0
                    )
                    sol = _pcwls_chunk_solve(
                        A,
                        jnp.asarray(starts[sel_p]),
                        jnp.asarray(counts_sel, dtype=dtype),
                        grams[bi],
                        col_sums[bi],
                        seg_sums[bi][sel_p],
                        jfm_blocks[bi][jnp.asarray(sel_p)],
                        P[:, sel_p].T,
                        t[jnp.asarray(sel_p)],
                        beta_d[jnp.asarray(sel_p)],
                        jnp.asarray(c0[sel_p], dtype=dtype),
                        alpha_d,
                        M=M,
                        lam=float(self.lam),
                    )
                    W_new = W_new.at[:, jnp.asarray(sel)].set(
                        sol[: len(sel)].T
                    )
                dW = W_new - models[bi]
                models[bi] = W_new
                D = _pcwls_residual_update(
                    A, dW, jfm_blocks[bi], D, onehot, valid, alpha_d, beta_d
                )
                mesh_lib.sync_if_cpu(D)

        # finalB = jointLabelMean − Σ_d jfm[c, d]·W[d, c]
        # (PerClassWeightedLeastSquares.scala:118-121).
        full_model = jnp.concatenate(models, axis=0)
        jfm_full = jnp.concatenate(jfm_blocks, axis=1)  # (k, D)
        final_b = jnp.asarray(jlm, dtype=dtype) - jnp.sum(
            jfm_full * full_model.T, axis=1
        )
        return BlockLinearMapper(models, self.block_size, b_opt=final_b)
