"""Solver and model nodes (reference: nodes/learning/)."""

from .block import BlockLeastSquaresEstimator, BlockLinearMapper
from .bwls import BlockWeightedLeastSquaresEstimator
from .rwls import (
    PerClassWeightedLeastSquaresEstimator,
    ReWeightedLeastSquaresSolver,
)
from .classifiers import (
    LinearDiscriminantAnalysis,
    LogisticRegressionEstimator,
    LogisticRegressionModel,
    NaiveBayesEstimator,
    NaiveBayesModel,
)
from .clustering import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    KMeansModel,
    KMeansPlusPlusEstimator,
)
from .cost import (
    CostModel,
    LeastSquaresEstimator,
    TransformerLabelEstimatorChain,
)
from .kernel import (
    GaussianKernelGenerator,
    GaussianKernelTransformer,
    KernelBlockLinearMapper,
    KernelRidgeRegression,
    NystromKernelMapper,
    NystromKernelRidge,
)
from .lbfgs import DenseLBFGSwithL2, SparseLBFGSwithL2, run_lbfgs
from .sketch import IterativeHessianSketch, SketchedLeastSquares
from .streaming_ls import (
    BlockStreamedLeastSquares,
    CosineBankFeaturize,
    StreamingFeaturizedLeastSquares,
    StreamingFeaturizedLinearModel,
    StreamingLeastSquaresChoice,
    cosine_bank_featurize,
)
from .linear import (
    LinearMapEstimator,
    LinearMapper,
    LocalLeastSquaresEstimator,
    SketchedLeastSquaresEstimator,
    SparseLinearMapper,
)
from .pca import (
    ApproximatePCAEstimator,
    BatchPCATransformer,
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
    PCATransformer,
    ZCAWhitener,
    ZCAWhitenerEstimator,
)
