"""Kernel ridge regression via blockwise Gauss-Seidel (arXiv:1602.05310).

Reference: nodes/learning/KernelRidgeRegression.scala:37-275,
KernelMatrix.scala:17-90, KernelGenerator.scala:18-206,
KernelBlockLinearMapper.scala:28-115.

The n×n kernel matrix is never materialized: column blocks are generated on
demand from the sharded training rows (blocked ‖x−y‖² via one GEMM + norm
broadcasts + exp — XLA fuses the elementwise tail into the matmul), and the
dual model W (n×k, row-sharded) is updated block by block.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.parallel.linalg import _psd_factor, _solve_psd
from keystone_tpu.utils import profiling
from keystone_tpu.workflow import LabelEstimator, Transformer

logger = logging.getLogger("keystone_tpu.kernel")


# ---------------------------------------------------------------------------
# Gaussian kernel
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("gamma", "use_pallas", "kdtype")
)
def _gaussian_block(X, Xb, x_norms, xb_norms, gamma: float, use_pallas: bool,
                    kdtype: str = "f32"):
    """K[i, j] = exp(-γ ‖X_i − Xb_j‖²) via ‖x‖² + ‖y‖² − 2x·y
    (reference: KernelGenerator.scala:121-205). On TPU the distance+exp
    epilogue is fused into the matmul by the Pallas kernel so the squared-
    distance intermediate never round-trips HBM. ``use_pallas`` is resolved
    by the *eager* caller (pallas_direct_ok) — a bare pallas_call on a
    mesh-sharded operand would force a gather, so sharded callers pass
    False here and reach the kernels through shard_map (parallel.ring).

    ``kdtype`` picks the MXU recipe for the cross-term GEMM (the norms,
    distance assembly, exp epilogue — and the RESULT — stay f32 in all
    modes; Cholesky solves downstream are untouched):
      - "f32": 6-pass (HIGHEST) — exact-f32, the default.
      - "bf16x3": 3-pass bf16 decomposition (HIGH) — HALF the MXU cost at
        ~2⁻¹⁶ operand error; kernel entries match f32 to ~1e-5. The
        recommended fast mode.
      - "bf16": single-pass bf16 operands — 6x cheaper, but the kernel-
        entry error (~γ·‖x‖‖y‖·2⁻⁸) can EXCEED small ridge λ, making
        K+λI indefinite — and block Gauss-Seidel then DIVERGES (measured:
        XOR at λ=1e-3 collapses to 25% accuracy while a direct solve of
        the same perturbed system stays at 97%; tests/test_kernel_bf16).
        Use only with λ comfortably above the kernel-error scale.
    """
    from keystone_tpu.ops import pallas_ops

    cd = jnp.bfloat16 if kdtype == "bf16" else jnp.float32
    # bf16x3 takes the XLA path even when Pallas is available: Mosaic has
    # no lowering for 3-pass dot precision, and a fused hi/lo-split
    # Pallas variant MEASURED SLOWER than XLA's 3-pass dot at the bench
    # geometry (0.265 s vs 0.204 s device — the per-operand hi/lo splits
    # do not hoist out of the block scan) with worse fit-path noise, so
    # it was removed; the unfused epilogue costs only ~5% extra HBM
    # traffic here.
    if use_pallas and kdtype != "bf16x3":
        return pallas_ops.gaussian_kernel_block(
            X, Xb, x_norms, xb_norms, gamma, compute_dtype=cd
        )
    if kdtype == "bf16":
        dot = jax.lax.dot_general(
            X.astype(jnp.bfloat16), Xb.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    elif kdtype == "bf16x3":
        dot = jax.lax.dot_general(
            X, Xb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGH,
        )
    else:
        dot = X @ Xb.T
    sq = x_norms[:, None] + xb_norms[None, :] - 2.0 * dot
    return jnp.exp(-gamma * jnp.maximum(sq, 0.0))


def _slice_block(train_X, train_norms, start, size: int):
    Xb = jax.lax.dynamic_slice_in_dim(train_X, start, size, axis=0)
    nb = jax.lax.dynamic_slice_in_dim(train_norms, start, size, axis=0)
    return Xb, nb


def _column_block(train_X, train_norms, start, size: int, gamma: float,
                  use_pallas: bool, kdtype: str = "f32"):
    """K(train, train[start:start+size]) — (n_padded, size)."""
    Xb, nb = _slice_block(train_X, train_norms, start, size)
    return _gaussian_block(
        train_X, Xb, train_norms, nb, gamma, use_pallas, kdtype
    )


def _diag_block(train_X, train_norms, start, size: int, gamma: float,
                use_pallas: bool, kdtype: str = "f32"):
    """K(block, block) — (size, size)."""
    Xb, nb = _slice_block(train_X, train_norms, start, size)
    return _gaussian_block(Xb, Xb, nb, nb, gamma, use_pallas, kdtype)


class GaussianKernelTransformer:
    """Holds the train rows; produces kernel column blocks on demand."""

    def __init__(self, gamma: float, train_X, n_train: int,
                 kernel_dtype: str = "f32"):
        from keystone_tpu.ops import pallas_ops

        self.gamma = float(gamma)
        self.train_X = jnp.asarray(train_X)
        self.n_train = n_train
        self.kernel_dtype = kernel_dtype
        self._train_norms = jnp.sum(self.train_X * self.train_X, axis=1)
        # Resolved once per transformer: direct Pallas dispatch is only safe
        # when the captured train rows are not mesh-sharded.
        self._use_pallas = pallas_ops.pallas_direct_ok(self.train_X)

    def column_block(self, start: int, size: int):
        """K(train, train[start:start+size]) — (n_padded, size)."""
        return _column_block(
            self.train_X, self._train_norms, start, size, self.gamma,
            self._use_pallas, self.kernel_dtype,
        )

    def test_block(self, test_X, start: int, size: int):
        """K(test, train[start:start+size])."""
        from keystone_tpu.ops import pallas_ops

        test_X = jnp.asarray(test_X)
        t_norms = jnp.sum(test_X * test_X, axis=1)
        Xb = jax.lax.dynamic_slice_in_dim(self.train_X, start, size, axis=0)
        nb = jax.lax.dynamic_slice_in_dim(self._train_norms, start, size, axis=0)
        use_pallas = self._use_pallas and pallas_ops.pallas_direct_ok(test_X)
        return _gaussian_block(
            test_X, Xb, t_norms, nb, self.gamma, use_pallas,
            self.kernel_dtype,
        )

    def diag_block(self, start: int, size: int):
        """K(train[start:start+size], train[start:start+size])."""
        return _diag_block(
            self.train_X, self._train_norms, start, size, self.gamma,
            self._use_pallas, self.kernel_dtype,
        )


class GaussianKernelGenerator:
    """Factory binding γ; ``fit(data)`` captures the training rows
    (reference: KernelGenerator.scala:18-60).

    ``kernel_dtype="bf16"`` generates kernel blocks with the bf16-operand/
    f32-accumulate MXU recipe — solves stay f32 Cholesky. See
    :func:`_gaussian_block` for the quantified error model.
    """

    def __init__(self, gamma: float, kernel_dtype: str = "f32"):
        if kernel_dtype not in ("f32", "bf16", "bf16x3"):
            raise ValueError(
                'kernel_dtype must be "f32", "bf16x3" or "bf16", got '
                f"{kernel_dtype!r}"
            )
        self.gamma = gamma
        self.kernel_dtype = kernel_dtype

    def fit(self, data: Dataset) -> GaussianKernelTransformer:
        return GaussianKernelTransformer(
            self.gamma, data.array, data.n, self.kernel_dtype
        )


# ---------------------------------------------------------------------------
# KRR solver
# ---------------------------------------------------------------------------


def _krr_block_step_math(K_block, W, K_bb, y_bb, w_old, valid_col, valid_row, start, lam):
    """Shared math of one Gauss-Seidel dual block update (un-jitted body).

    The (K_bb + λI) system is SPD (K is a Gram matrix of the Gaussian
    kernel), so the local solve is the shared Cholesky-with-rescue path
    (`parallel.linalg._solve_psd`) — ~1.6× faster than TPU's LU kernel at
    bs=4096 and the same robustness story as the BCD solvers. Ghost
    rows/columns of a ragged final block get an identity diagonal so they
    solve to exactly zero (their rhs is masked to zero)."""
    K_block = K_block * valid_row[:, None] * valid_col[None, :]
    residual = K_block.T @ W
    K_bb = K_bb * valid_col[:, None] * valid_col[None, :]
    rhs = y_bb - (residual - K_bb.T @ w_old)
    b = K_bb.shape[0]
    gram = jnp.where(
        (valid_col[:, None] * valid_col[None, :]) > 0,
        K_bb,
        jnp.eye(b, dtype=K_bb.dtype),
    )
    w_new = _solve_psd(gram, rhs * valid_col[:, None], lam)
    W_updated = jax.lax.dynamic_update_slice_in_dim(W, w_new, start, axis=0)
    return w_new, W_updated


def _diag_factor_prepass(X, x_norms, gamma, lam_t, bs: int, n_train: int,
                         num_blocks: int, use_pallas: bool, kdtype: str,
                         dtype):
    """Batched per-block (gram, Cholesky) pre-pass: generate every diagonal
    block once (masked + identity-ghosted for a ragged final block) and
    factor the whole stack BEFORE the sweep. The sweep then reuses the
    stashed factors on every visit — epochs 2+ pay zero kernel-diag regen
    and zero re-factorization, the same stash discipline as
    ``bcd_from_gram``. Diag generation costs nb·bs²·d MACs once (bs/n of
    one epoch's column work) instead of riding free as a slice of the
    column block — the trade that lets the fused-residual path skip
    materializing the (n_pad, bs) column block entirely."""

    def diag_system(block):
        start = block * bs
        Xb, nb_ = _slice_block(X, x_norms, start, bs)
        K_bb = _gaussian_block(Xb, Xb, nb_, nb_, gamma, use_pallas, kdtype)
        valid_col = ((jnp.arange(bs) + start) < n_train).astype(dtype)
        mask = valid_col[:, None] * valid_col[None, :]
        gram = jnp.where(mask > 0, K_bb.astype(dtype), jnp.eye(bs, dtype=dtype))
        return gram, _psd_factor(gram, lam_t)

    return jax.lax.map(diag_system, jnp.arange(num_blocks))


@functools.partial(
    jax.jit,
    static_argnames=(
        "gamma", "lam", "bs", "n_train", "num_blocks", "use_pallas", "kdtype"
    ),
)
def _krr_fit_fused(X, Y, order, gamma: float, lam: float, bs: int,
                   n_train: int, num_blocks: int, use_pallas: bool,
                   carry0=None, kdtype: str = "f32"):
    """The whole KRR training sweep as ONE program: a batched diagonal
    gram + Cholesky pre-pass (factors stashed, reused on EVERY block
    visit — the per-step re-factorization of rounds ≤5 is gone), then a
    lax.scan over the (epochs × blocks) order where each step computes
    only the residual K_blockᵀW and the stashed-factor solve. On the
    Pallas engines (f32/bf16) the residual comes from the fused
    ``gaussian_resid_block`` epilogue — the (n_pad, bs) kernel column
    block is never written to HBM (the bf16x3 engine keeps the XLA
    3-pass dot + GEMM, which Mosaic cannot lower). No host round trips —
    the single-dispatch replacement for the reference's per-block driver
    loop (KernelRidgeRegression.scala:136-231).

    ``carry0``: optional ``(W0, stack0)`` initial carry — the resume hook
    for checkpointed fits, which run this program over order *segments*
    (the pre-pass recomputes per segment dispatch; it is deterministic,
    so resumed sweeps see bit-identical factors)."""
    from keystone_tpu.ops import pallas_ops

    n_pad, k = Y.shape
    x_norms = jnp.sum(X * X, axis=1)
    lam_t = jnp.asarray(lam, dtype=Y.dtype)

    grams, chols = _diag_factor_prepass(
        X, x_norms, gamma, lam_t, bs, n_train, num_blocks, use_pallas,
        kdtype, Y.dtype,
    )
    fused_resid = use_pallas and kdtype != "bf16x3"
    resid_dtype = jnp.bfloat16 if kdtype == "bf16" else jnp.float32

    def step(carry, block):
        W, w_stack = carry
        start = block * bs
        valid_col = ((jnp.arange(bs) + start) < n_train).astype(Y.dtype)
        Xb, nb_ = _slice_block(X, x_norms, start, bs)
        if fused_resid:
            residual = pallas_ops.gaussian_resid_block(
                X, Xb, x_norms, nb_, W, gamma, compute_dtype=resid_dtype,
            ).astype(Y.dtype)
        else:
            # Ghost rows (padding and beyond-n_train) of W are exactly
            # zero — the solver invariant below — so the unmasked kernel
            # block contracts to the same residual the masked form gave.
            K_block = _gaussian_block(
                X, Xb, x_norms, nb_, gamma, False, kdtype
            )
            residual = K_block.T @ W
        gram = jax.lax.dynamic_index_in_dim(grams, block, 0, keepdims=False)
        chol = jax.lax.dynamic_index_in_dim(chols, block, 0, keepdims=False)
        y_bb = jax.lax.dynamic_slice_in_dim(Y, start, bs, axis=0)
        y_bb = y_bb * valid_col[:, None]
        w_old = jax.lax.dynamic_index_in_dim(w_stack, block, 0, keepdims=False)
        # gram's identity ghost diagonal contributes w_old's ghost rows —
        # exactly zero (ghost solves are zero every step), so this equals
        # the masked-K_bb form.
        rhs = y_bb - (residual - gram.T @ w_old)
        # Ghost rows of rhs are masked, the factor is stashed: the solve
        # returns exactly zero ghost rows (preserving the W invariant).
        w_new = _solve_psd(gram, rhs * valid_col[:, None], lam_t, chol=chol)
        W = jax.lax.dynamic_update_slice_in_dim(W, w_new, start, axis=0)
        w_stack = jax.lax.dynamic_update_index_in_dim(w_stack, w_new, block, 0)
        return (W, w_stack), None

    if carry0 is None:
        carry0 = (
            jnp.zeros((n_pad, k), dtype=Y.dtype),
            jnp.zeros((num_blocks, bs, k), dtype=Y.dtype),
        )
    (W, w_stack), _ = jax.lax.scan(step, carry0, order)
    return W, w_stack


@functools.lru_cache(maxsize=8)
def _krr_mesh_program(mesh, gamma: float, lam: float, bs: int,
                      n_train: int, num_blocks: int,
                      kdtype: str = "f32"):
    """Build (and cache) the shard_map sweep program for one (mesh, fit
    geometry). The cache makes checkpointed fits — which dispatch this
    program once per order *segment* — reuse one traced callable, so
    shard_map's jit cache hits instead of retracing and recompiling the
    whole scan every segment. Bounded like the BCD mesh cache
    (``parallel.linalg._mesh_bcd_step``)."""
    from keystone_tpu.parallel import mesh as mesh_lib

    axis = mesh_lib.DATA_AXIS
    psize = dict(mesh.shape)[axis]

    def body(x_local, y_local, order, stack_init):
        ln = x_local.shape[0]
        n_pad = ln * psize
        lam_t = jnp.asarray(lam, dtype=y_local.dtype)
        me = jax.lax.axis_index(axis)
        g_idx = me * ln + jnp.arange(ln)
        valid_local = (g_idx < n_train).astype(y_local.dtype)
        X_full = jax.lax.all_gather(x_local, axis, tiled=True)
        Y_full = jax.lax.all_gather(y_local, axis, tiled=True)
        full_norms = jnp.sum(X_full * X_full, axis=1)
        local_norms = jnp.sum(x_local * x_local, axis=1)

        # Batched diag + Cholesky pre-pass (replicated — X_full is already
        # gathered): the sweep reuses stashed factors on every block
        # visit, the same stash discipline as the single-device form.
        grams, chols = _diag_factor_prepass(
            X_full, full_norms, gamma, lam_t, bs, n_train, num_blocks,
            False, kdtype, y_local.dtype,
        )

        def step(carry, block):
            W_local, w_stack = carry
            start = block * bs
            Xb = jax.lax.dynamic_slice_in_dim(X_full, start, bs, axis=0)
            nb = jax.lax.dynamic_slice_in_dim(full_norms, start, bs, axis=0)
            valid_col = ((jnp.arange(bs) + start) < n_train).astype(y_local.dtype)

            K_local = _gaussian_block(
                x_local, Xb, local_norms, nb, gamma, False, kdtype
            ) * (valid_local[:, None] * valid_col[None, :])

            residual = jax.lax.psum(K_local.T @ W_local, axis)
            y_bb = (
                jax.lax.dynamic_slice_in_dim(Y_full, start, bs, axis=0)
                * valid_col[:, None]
            )
            w_old = jax.lax.dynamic_index_in_dim(
                w_stack, block, 0, keepdims=False
            )
            gram = jax.lax.dynamic_index_in_dim(grams, block, 0, keepdims=False)
            chol = jax.lax.dynamic_index_in_dim(chols, block, 0, keepdims=False)
            # gram's identity ghost diagonal contributes w_old's ghost
            # rows — exactly zero — so this equals the masked-K_bb form.
            rhs = y_bb - (residual - gram.T @ w_old)
            # Replicated SPD solve — same Cholesky-with-rescue path as the
            # single-device form, so mesh and 1-device fits stay in parity.
            w_new = _solve_psd(gram, rhs * valid_col[:, None], lam_t, chol=chol)

            rel = jnp.clip(g_idx - start, 0, bs - 1)
            in_block = ((g_idx >= start) & (g_idx < start + bs))[:, None]
            W_local = jnp.where(in_block, w_new[rel], W_local)
            w_stack = jax.lax.dynamic_update_index_in_dim(
                w_stack, w_new, block, 0
            )
            return (W_local, w_stack), None

        # Resume hook: the dual model's rows for block b are exactly the
        # block's latest stack entry, so W_local re-derives from the
        # replicated stack (zeros on a fresh fit) — each device slices the
        # rows it owns out of the flattened stack. Rows past num_blocks·bs
        # (mesh-divisibility padding) belong to no block: zero-pad so the
        # slice stays in range.
        flat = stack_init.reshape(num_blocks * bs, stack_init.shape[2])
        if n_pad > num_blocks * bs:
            flat = jnp.pad(flat, ((0, n_pad - num_blocks * bs), (0, 0)))
        W0 = jax.lax.dynamic_slice_in_dim(flat, me * ln, ln, axis=0)
        (_, w_stack), _ = jax.lax.scan(step, (W0, stack_init), order)
        # w_stack is built from psum-backed replicated solves, so it is
        # identical on every device — replicated out_spec (check_vma=False:
        # the static checker cannot see through the masked arithmetic).
        return w_stack

    from jax.sharding import PartitionSpec as P

    return mesh_lib.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P()),
        out_specs=P(),
        check_vma=False,
    )


def _krr_fit_fused_mesh(X, Y, order, gamma: float, lam: float, bs: int,
                        n_train: int, num_blocks: int, mesh, stack0=None,
                        kdtype: str = "f32"):
    """The whole KRR training sweep as ONE shard_map program over the mesh's
    ``data`` axis — the multi-device form of :func:`_krr_fit_fused`, so
    sharded fits keep the single-dispatch speed story instead of a host
    loop with per-block syncs (KernelRidgeRegression.scala:136-231 driver
    loop → one compiled scan).

    Layout: train rows X, labels Y and the dual model W stay row-sharded;
    each device all_gathers X once (the KRR regime is n·d ≪ n², so a
    replicated X is cheap next to the never-materialized kernel — for
    sequences too long to replicate, the ring tier in ``parallel.ring`` is
    the right tool). Per block step: every device computes its local slice
    of the kernel column block, the (bs, k) residual is one ``psum`` over
    ICI, the (bs, bs) solve is replicated, and each device scatters the new
    block weights into whatever slice of the block its local rows cover
    (blocks need not align with shard boundaries).
    """
    if stack0 is None:
        stack0 = jnp.zeros((num_blocks, bs, Y.shape[1]), dtype=Y.dtype)
    program = _krr_mesh_program(
        mesh, float(gamma), float(lam), bs, int(n_train), num_blocks, kdtype
    )
    return program(X, Y, order, stack0)


@functools.partial(jax.jit, static_argnames=("lam",), donate_argnums=(1,))
def _krr_block_step(K_block, W, K_bb, y_bb, w_old, valid_col, valid_row, start, lam: float):
    """One Gauss-Seidel block update of the dual model; returns (w_new, W').

    K_block: (n_pad, b) kernel columns; W: (n_pad, k) dual model (donated —
    the update is scattered in place); K_bb: (b, b); y_bb, w_old: (b, k);
    valid_col: (b,) mask for ghost columns in a ragged final block;
    valid_row: (n_pad,) mask for padding rows; start: block row offset.
    """
    return _krr_block_step_math(
        K_block, W, K_bb, y_bb, w_old, valid_col, valid_row, start,
        jnp.asarray(lam, dtype=W.dtype),
    )


class KernelBlockLinearMapper(Transformer):
    """Apply the dual model to test data block-by-block
    (reference: KernelBlockLinearMapper.scala:28-115)."""

    def __init__(
        self,
        w_locals: List,
        block_size: int,
        kernel_transformer: GaussianKernelTransformer,
        n_train: int,
    ):
        self.w_locals = [jnp.asarray(w) for w in w_locals]
        self.block_size = block_size
        self.kernel_transformer = kernel_transformer
        self.n_train = n_train
        self._ring_operands = None  # (mesh, Xtr_sharded, W_sharded) cache

    def apply(self, x):
        return self.batch_apply(Dataset.of(np.asarray(x)[None])).to_numpy()[0]

    def batch_apply(self, data: Dataset) -> Dataset:
        from keystone_tpu.parallel import mesh as mesh_lib
        from keystone_tpu.parallel import ring

        mesh = data.mesh
        if mesh is not None and mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS) > 1:
            # Multi-device: ring schedule — train rows + dual model circulate
            # the mesh via ppermute; no block gather, no replicated W.
            if self._ring_operands is None or self._ring_operands[0] is not mesh:
                # The sharded (train rows, dual model) pair is invariant per
                # model+mesh: build once, reuse across test batches.
                p = mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS)
                W_full = jnp.concatenate(self.w_locals, axis=0)[: self.n_train]
                Xtr = self.kernel_transformer.train_X[: self.n_train]
                # Ghost train rows have nonzero kernel values, but zero model
                # rows, so padding contributes nothing to the product.
                W_pad, _ = mesh_lib.pad_rows(np.asarray(W_full), p)
                Xtr_pad, _ = mesh_lib.pad_rows(np.asarray(Xtr), p)
                self._ring_operands = (
                    mesh,
                    mesh_lib.shard_rows(Xtr_pad, mesh),
                    mesh_lib.shard_rows(W_pad, mesh),
                )
            _, Xtr_s, W_s = self._ring_operands
            out = ring.ring_kernel_apply(
                data.array, Xtr_s, W_s,
                self.kernel_transformer.gamma, mesh=mesh,
            )
            return Dataset(out, n=data.n, mesh=mesh)._rezero_padding()

        X = jnp.asarray(data.array)
        out = None
        for bi, w in enumerate(self.w_locals):
            start = bi * self.block_size
            Kb = self.kernel_transformer.test_block(X, start, w.shape[0])
            partial = Kb @ w
            out = partial if out is None else out + partial
        return Dataset(out, n=data.n, mesh=data.mesh)._rezero_padding()


class KernelRidgeRegression(LabelEstimator):
    """Solve (K + λI) W = Y by Gauss-Seidel block coordinate descent
    (reference: KernelRidgeRegression.scala:37-235)."""

    def __init__(
        self,
        kernel_generator: GaussianKernelGenerator,
        lam: float,
        block_size: int,
        num_epochs: int,
        block_permuter: Optional[int] = None,
        profile: bool = False,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_blocks: int = 25,
    ):
        self.kernel_generator = kernel_generator
        self.lam = lam
        self.block_size = block_size
        self.num_epochs = num_epochs
        self.block_permuter = block_permuter
        # Explicit opt-in for the per-phase timing breakdown (the analog of
        # the reference's kernelGen/residual/localSolve/modelUpdate ns logs).
        # Profiling forces the stepwise per-block path with a sync per block;
        # logging configuration alone never changes which solver path runs.
        self.profile = profile
        # Mid-solver checkpoint/resume — the preemption story the reference
        # could not have (Spark lineage recomputes; there is no TPU analog).
        # The fused sweep runs in segments of ``checkpoint_every_blocks``
        # block updates (each still one dispatch — the default mirrors the
        # reference's blocksBeforeCheckpoint=25 lineage truncation cadence,
        # KernelRidgeRegression.scala:199-203); after each segment the
        # (position, block-weight stack) pair is written atomically to
        # ``checkpoint_path``. A later fit with the same geometry resumes
        # from the last completed segment and deletes the file on success.
        if profile and checkpoint_path is not None:
            raise ValueError(
                "profile=True forces the stepwise path; checkpointing "
                "segments the fused path — pick one"
            )
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every_blocks = int(checkpoint_every_blocks)

    def fit(self, data: Dataset, labels: Dataset) -> KernelBlockLinearMapper:
        n_train = data.n
        bs = self.block_size
        num_blocks = -(-n_train // bs)
        # Pad rows to a whole number of blocks so block slices never clamp
        # (dynamic_slice silently shifts a slice that runs past the end).
        n_pad = max(data.num_padded, num_blocks * bs)

        X = jnp.asarray(data.array)
        Y = jnp.asarray(labels.array)
        if X.shape[0] < n_pad:
            X = jnp.pad(X, ((0, n_pad - X.shape[0]), (0, 0)))
        if Y.shape[0] < n_pad:
            Y = jnp.pad(Y, ((0, n_pad - Y.shape[0]), (0, 0)))

        transformer = self.kernel_generator.fit(Dataset(X, n=n_train, mesh=data.mesh))
        k = Y.shape[1]

        rng = np.random.default_rng(self.block_permuter) if self.block_permuter is not None else None

        timing_on = self.profile
        # The stepwise per-block path is only reachable under profiling now
        # (multi-device fits run the fused shard_map sweep — one compiled
        # program, so the forced-host CPU test backend's multi-program
        # collective deadlock cannot arise either), and profiling always
        # syncs per block for timing attribution.
        multi_device = data.mesh is not None and any(
            s > 1 for s in dict(data.mesh.shape).values()
        )
        sync_blocks = timing_on
        use_fused = not timing_on

        if use_fused:
            # Fast path: the whole (epochs × blocks) sweep is one compiled
            # scan — kernel blocks generated in-loop, zero host round trips.
            # Single-dispatch on one device AND on meshes (shard_map form).
            orders = []
            for _ in range(self.num_epochs):
                order = list(range(num_blocks))
                if rng is not None:
                    rng.shuffle(order)
                orders.extend(order)
            order_arr = jnp.asarray(np.array(orders, dtype=np.int32))

            if multi_device:
                from keystone_tpu.parallel import mesh as mesh_lib

                p = mesh_lib.axis_size(data.mesh, mesh_lib.DATA_AXIS)
                if X.shape[0] % p:
                    extra = p - X.shape[0] % p
                    X = jnp.pad(X, ((0, extra), (0, 0)))
                    Y = jnp.pad(Y, ((0, extra), (0, 0)))

            gamma_f, lam_f = float(self.kernel_generator.gamma), float(self.lam)
            kdtype = getattr(self.kernel_generator, "kernel_dtype", "f32")

            def run_segment(seg, stack0):
                """One dispatch over a slice of the block order."""
                if multi_device:
                    return _krr_fit_fused_mesh(
                        X, Y, seg, gamma_f, lam_f, bs, int(n_train),
                        num_blocks, data.mesh, stack0=stack0, kdtype=kdtype,
                    )
                from keystone_tpu.ops import pallas_ops

                carry0 = None
                if stack0 is not None:
                    flat = stack0.reshape(num_blocks * bs, k)
                    if Y.shape[0] > num_blocks * bs:
                        flat = jnp.pad(
                            flat, ((0, Y.shape[0] - num_blocks * bs), (0, 0))
                        )
                    carry0 = (flat, stack0)
                _, w_stack = _krr_fit_fused(
                    X, Y, seg, gamma_f, lam_f, bs, int(n_train), num_blocks,
                    pallas_ops.pallas_direct_ok(X), carry0=carry0,
                    kdtype=kdtype,
                )
                return w_stack

            if self.checkpoint_path is None or order_arr.shape[0] == 0:
                # (an empty order — num_epochs=0 — has nothing to resume)
                w_stack = run_segment(order_arr, None)
            else:
                if jax.process_count() > 1:
                    # The fingerprint samples rows of a globally-sharded X
                    # (non-addressable from one process) and every process
                    # would race the same file; single-controller only.
                    raise NotImplementedError(
                        "checkpoint_path is not supported on multi-host "
                        "meshes; checkpoint from a single-controller fit"
                    )
                w_stack = self._fit_checkpointed(
                    run_segment, X, Y, order_arr, num_blocks, bs, k, n_train
                )
            w_locals = [w_stack[i] for i in range(num_blocks)]
            return KernelBlockLinearMapper(w_locals, bs, transformer, n_train)

        valid_row = (jnp.arange(n_pad) < n_train).astype(Y.dtype)
        W = jnp.zeros((n_pad, k), dtype=Y.dtype)
        w_locals = [jnp.zeros((bs, k), dtype=Y.dtype) for _ in range(num_blocks)]

        # Per-phase breakdown, the analog of the reference's kernelGen/
        # residual/localSolve/modelUpdate ns logs (KernelRidgeRegression.scala:213-221).
        # The phase barrier costs a host-device sync per block, so only pay
        # it when the profiling summary will actually be emitted.
        timer = profiling.PhaseTimer("krr_fit")

        for epoch in range(self.num_epochs):
            order = list(range(num_blocks))
            if rng is not None:
                rng.shuffle(order)
            for block in order:
                t0 = time.perf_counter()
                start = block * bs
                # Ragged last block: mask ghost columns beyond n_train.
                valid_col = (
                    (jnp.arange(start, start + bs) < n_train).astype(Y.dtype)
                )
                with timer.phase("kernel_gen"):
                    K_block = transformer.column_block(start, bs)
                    K_bb = transformer.diag_block(start, bs)
                    if timing_on:
                        # Barrier so the async kernel GEMMs are attributed
                        # here, not to the solve phase that touches them.
                        jax.block_until_ready((K_block, K_bb))
                y_bb = jax.lax.dynamic_slice_in_dim(Y, start, bs, axis=0)
                y_bb = y_bb * valid_col[:, None]

                # The in-step scatter is the analog of updateModel's
                # prefix-length index intersection (KernelRidgeRegression.scala:237-274).
                with timer.phase("block_solve"):
                    w_new, W = _krr_block_step(
                        K_block, W, K_bb, y_bb, w_locals[block],
                        valid_col, valid_row, start, float(self.lam),
                    )
                    w_locals[block] = w_new
                    if sync_blocks:
                        W.block_until_ready()
                if sync_blocks:
                    # Without the per-block sync this would time only the
                    # async enqueue, not the compute — skip it entirely.
                    logger.info(
                        "EPOCH_%d_BLOCK_%d took %.3f seconds",
                        epoch, block, time.perf_counter() - t0,
                    )
        if timing_on:
            timer.log_summary()
        return KernelBlockLinearMapper(w_locals, bs, transformer, n_train)

    # -- mid-solver checkpoint/resume ------------------------------------

    def _fingerprint(self, X, Y, order_arr, num_blocks, bs, k,
                     n_train) -> str:
        """Geometry + hyperparameter + block-order + data digest: a
        checkpoint may only resume the fit that wrote it. Data is pinned by
        a bitwise sample of up to 64 evenly-spaced (X, Y) rows — inputs are
        stored values, so the sample is topology-independent — which catches
        'same shapes, different data' (e.g. a reseeded upstream featurizer)
        without hashing a dataset that may be most of HBM."""
        import hashlib

        h = hashlib.sha256()
        h.update(np.asarray(order_arr, dtype=np.int32).tobytes())
        spec = (
            f"n={int(n_train)} d={X.shape[1]} bs={bs} k={k} nb={num_blocks} "
            f"gamma={float(self.kernel_generator.gamma)!r} "
            f"lam={float(self.lam)!r} epochs={self.num_epochs} "
            f"permuter={self.block_permuter!r} "
            f"dtypes={X.dtype}/{Y.dtype} "
            f"kdtype={getattr(self.kernel_generator, 'kernel_dtype', 'f32')}"
        )
        h.update(spec.encode())
        idx = np.unique(
            np.linspace(0, max(int(n_train) - 1, 0), 64).astype(np.int64)
        )
        h.update(np.asarray(X[idx]).tobytes())
        h.update(np.asarray(Y[idx]).tobytes())
        return h.hexdigest()

    def _fit_checkpointed(self, run_segment, X, Y, order_arr, num_blocks,
                          bs, k, n_train):
        """Run the fused sweep in segments, persisting (position, stack)
        after each; resume from ``checkpoint_path`` when a compatible
        checkpoint exists. The write is atomic (tmp + rename), so a
        preemption mid-save leaves the previous checkpoint intact."""
        import os

        path = self.checkpoint_path
        fp = self._fingerprint(X, Y, order_arr, num_blocks, bs, k, n_train)
        total = int(order_arr.shape[0])
        pos, stack = 0, None

        if os.path.exists(path):
            # Close the NpzFile before the fit runs: a handle left open for
            # the fit's duration would make the completed-fit os.remove
            # below fail on non-POSIX platforms.
            with np.load(path, allow_pickle=False) as ck:
                if str(ck["fingerprint"]) != fp:
                    raise ValueError(
                        f"checkpoint at {path} was written by a different KRR "
                        "fit (geometry/hyperparameters/block order differ); "
                        "delete it or point checkpoint_path elsewhere"
                    )
                pos = int(ck["pos"])
                stack = jnp.asarray(ck["stack"])
            logger.info("KRR resume from %s: block update %d/%d", path, pos, total)

        every = max(self.checkpoint_every_blocks, 1)
        while pos < total:
            seg = order_arr[pos : pos + every]
            stack = run_segment(seg, stack)
            pos += int(seg.shape[0])
            if pos < total:
                host_stack = np.asarray(stack)  # syncs the segment
                tmp = f"{path}.tmp.npz"  # .npz: stops savez renaming it
                np.savez(tmp, pos=pos, stack=host_stack, fingerprint=fp)
                os.replace(tmp, path)
        # Sync the (async-dispatched) final segment BEFORE deleting the
        # checkpoint: a preemption while the device is still inside that
        # segment must find the last save intact, not gone.
        jax.block_until_ready(stack)
        if os.path.exists(path):
            os.remove(path)  # completed: the model supersedes the checkpoint
        return stack

    @property
    def weight(self) -> int:
        return self.num_epochs + 1


# ---------------------------------------------------------------------------
# Nyström-approximated KRR (beyond-parity, TPU-native)
# ---------------------------------------------------------------------------


class NystromKernelMapper(Transformer):
    """Predict with a landmark model: f(x) = K(x, L) α."""

    def __init__(self, landmarks, alpha, gamma: float):
        self.landmarks = jnp.asarray(landmarks)
        self.alpha = jnp.asarray(alpha)
        self.gamma = float(gamma)
        self._lm_norms = jnp.sum(self.landmarks * self.landmarks, axis=1)

    def apply(self, x):
        return self.batch_apply(Dataset.of(np.asarray(x)[None])).to_numpy()[0]

    def batch_apply(self, data: Dataset) -> Dataset:
        from keystone_tpu.ops import pallas_ops

        X = jnp.asarray(data.array)
        x_norms = jnp.sum(X * X, axis=1)
        K = _gaussian_block(
            X, self.landmarks, x_norms, self._lm_norms, self.gamma,
            pallas_ops.pallas_direct_ok(X, self.landmarks),
        )
        out = K @ self.alpha
        return Dataset(out, n=data.n, mesh=data.mesh)._rezero_padding()


@functools.partial(jax.jit, static_argnames=("gamma", "use_pallas"))
def _nystrom_fit_kernel(X, Y, L, gamma: float, lam, n_valid,
                        use_pallas: bool = False):
    """Nyström KRR normal equations: (K_nmᵀ K_nm + λ K_mm) α = K_nmᵀ Y.

    One compiled program: landmark kernel blocks via the fused gaussian
    kernel, all contractions MXU GEMMs. Padding rows of X/Y are zero; their
    kernel values exp(-γ‖0 − l‖²) are nonzero, so they are masked out of the
    contractions by the validity mask.
    """
    x_norms = jnp.sum(X * X, axis=1)
    l_norms = jnp.sum(L * L, axis=1)
    mask = (jnp.arange(X.shape[0]) < n_valid).astype(Y.dtype)
    K_nm = _gaussian_block(X, L, x_norms, l_norms, gamma, use_pallas) * mask[:, None]
    K_mm = _gaussian_block(L, L, l_norms, l_norms, gamma, use_pallas)
    m = L.shape[0]
    lhs = K_nm.T @ K_nm + lam * K_mm
    # Scale-relative jitter: duplicate landmarks make lhs exactly singular,
    # and an absolute 1e-8 vanishes below one ulp at f32 magnitudes ~n.
    jitter = 1e-6 * (jnp.trace(lhs) / m + 1.0)
    lhs = lhs + jitter * jnp.eye(m, dtype=Y.dtype)
    rhs = K_nm.T @ Y
    return jnp.linalg.solve(lhs, rhs)


class NystromKernelRidge(LabelEstimator):
    """Kernel ridge regression via the Nyström landmark approximation
    (Williams & Seeger, NIPS 2000) — a beyond-parity alternative to the
    exact blockwise KRR solver: m landmarks reduce the n×n dual problem to
    an m×m solve after one K(X, L) generation pass, trading a controlled
    approximation for O(n·m) kernel work instead of O(n²).

    Landmarks come from k-means centers (better coverage) or a uniform row
    sample. All compute is one jitted program of fused kernel blocks + GEMMs.
    """

    def __init__(
        self,
        kernel_generator: GaussianKernelGenerator,
        lam: float,
        num_landmarks: int,
        kmeans_landmarks: bool = True,
        seed: int = 0,
    ):
        self.kernel_generator = kernel_generator
        self.lam = lam
        self.num_landmarks = num_landmarks
        self.kmeans_landmarks = kmeans_landmarks
        self.seed = seed

    def fit(self, data: Dataset, labels: Dataset) -> NystromKernelMapper:
        from keystone_tpu.ops.learning.clustering import KMeansPlusPlusEstimator

        m = min(self.num_landmarks, data.n)
        if self.kmeans_landmarks:
            # KMeans fit() performs the single host conversion itself.
            km = KMeansPlusPlusEstimator(m, 10, seed=self.seed).fit(data)
            L = jnp.asarray(km.means, dtype=jnp.asarray(data.array).dtype)
        else:
            # Only m rows leave the device.
            rng = np.random.default_rng(self.seed)
            idx = rng.choice(data.n, m, replace=False)
            L = jnp.take(jnp.asarray(data.array), jnp.asarray(idx), axis=0)

        X = jnp.asarray(data.array)
        Y = jnp.asarray(labels.array)
        # Align physical row counts: data and labels may carry different
        # padding (mesh multiples vs unpadded host arrays).
        n_pad = max(X.shape[0], Y.shape[0])
        if X.shape[0] < n_pad:
            X = jnp.pad(X, ((0, n_pad - X.shape[0]), (0, 0)))
        if Y.shape[0] < n_pad:
            Y = jnp.pad(Y, ((0, n_pad - Y.shape[0]), (0, 0)))
        from keystone_tpu.ops import pallas_ops

        alpha = _nystrom_fit_kernel(
            X, Y, L, float(self.kernel_generator.gamma),
            jnp.asarray(self.lam, dtype=Y.dtype), data.n,
            pallas_ops.pallas_direct_ok(X, L),
        )
        return NystromKernelMapper(L, alpha, self.kernel_generator.gamma)

    @property
    def weight(self) -> int:
        return 2
