"""Estimator API over the out-of-core streaming least-squares tier.

``StreamingFeaturizedLeastSquares`` is the pipeline-facing form of
``parallel.streaming``: the featurizer lives INSIDE the estimator, so the
fit generates features per row tile and folds them into the (d, d) normal
equations — the feature matrix never materializes (72 GB at the real
TIMIT geometry vs 16 GB of HBM). The fitted model applies the same
featurizer tile-wise. This is the user-facing handle on the BENCH_r04
headline path and on the reference's streaming-by-construction substrate
(CsvDataLoader.scala:10-31 lazy rows; per-partition Gramian accumulation,
BlockWeightedLeastSquares.scala:177-313).

Default semantics match ``BlockLeastSquaresEstimator``
(BlockLinearMapper.scala:224-243): features and labels are mean-centered
(the column sums accumulate in the same tile pass as the Gramian — a
rank-1 correction, not a second data pass) and the model carries the
intercept. ``center=False`` gives the raw-BCD semantics of
``linalg.bcd_least_squares`` instead.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel import streaming
from keystone_tpu.workflow import LabelEstimator, Transformer


class StreamingFeaturizedLinearModel(Transformer):
    """Apply featurize + block weights tile-wise (features never resident).

    A centered fit supplies (fmean, ymean); predictions are then
    (F − fmean) @ W + ymean, which folds into the single affine offset
    ymean − fmean @ W_flat — BlockLinearMapper's model shape without a
    second pass over the features.
    """

    def __init__(self, featurize, W_stack, tile_rows: int,
                 fmean=None, ymean=None):
        self.featurize = featurize
        self.W_stack = jnp.asarray(W_stack)
        self.tile_rows = tile_rows
        self.fmean = None if fmean is None else jnp.asarray(fmean)
        self.ymean = None if ymean is None else jnp.asarray(ymean)
        Wf = self.W_stack.reshape(-1, self.W_stack.shape[2])
        self.offset = (
            None if self.ymean is None
            else self.ymean - self.fmean.astype(jnp.float32) @ Wf
        )

    def apply(self, x):
        F = self.featurize(jnp.asarray(x)[None, :])
        Wf = self.W_stack.reshape(-1, self.W_stack.shape[2])
        out = (F.astype(jnp.float32) @ Wf)[0]
        return out if self.offset is None else out + self.offset

    def batch_apply(self, data: Dataset) -> Dataset:
        preds = streaming.streaming_predict(
            jnp.asarray(data.array), self.W_stack, self.featurize,
            self.tile_rows,
        )
        if self.offset is not None:
            preds = preds + self.offset
        return Dataset(preds, n=data.n, mesh=data.mesh)._rezero_padding()


class StreamingFeaturizedLeastSquares(LabelEstimator):
    """Featurize-inside-the-fit block least squares (the streaming tier).

    ``featurize``: traceable ``(rows, d_in) -> (rows, d_feat)`` array
    function (e.g. a cosine random-feature bank). The fit is ONE compiled
    program per device (tile scan -> Gramian fold -> BCD epochs on the
    normal equations); sharded input runs the mesh form (per-device folds
    + one psum). ``tile_rows=None`` sizes tiles to a ~2 GB feature slab.
    """

    def __init__(
        self,
        featurize: Callable,
        d_feat: int,
        block_size: int,
        num_iter: int = 1,
        lam: float = 0.0,
        tile_rows: Optional[int] = None,
        feat_itemsize: int = 4,
        center: bool = True,
    ):
        self.featurize = featurize
        self.d_feat = d_feat
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.tile_rows = tile_rows or streaming.pick_tile_rows(
            d_feat, feat_itemsize
        )
        self.center = center

    @property
    def weight(self) -> int:
        return self.num_iter + 1

    def fit(self, data: Dataset, labels: Dataset) -> StreamingFeaturizedLinearModel:
        X = jnp.asarray(data.array)
        Y = jnp.asarray(labels.array)
        multi = data.mesh is not None and any(
            s > 1 for s in dict(data.mesh.shape).values()
        )
        fmean = ymean = None
        if multi:
            kw = dict(
                featurize=self.featurize, d_feat=self.d_feat,
                tile_rows=min(self.tile_rows, max(X.shape[0] // mesh_lib.axis_size(
                    data.mesh, mesh_lib.DATA_AXIS), 1)),
                block_size=self.block_size, lam=self.lam,
                num_iter=self.num_iter, mesh=data.mesh, n_true=data.n,
            )
            if self.center:
                W, fmean, ymean = streaming.streaming_bcd_fit_mesh_centered(
                    X, Y, **kw
                )
            else:
                W = streaming.streaming_bcd_fit_mesh(X, Y, **kw)
        else:
            kw = dict(
                featurize=self.featurize, d_feat=self.d_feat,
                tile_rows=min(self.tile_rows, X.shape[0]),
                block_size=self.block_size, lam=self.lam,
                num_iter=self.num_iter,
                valid=int(data.n) if data.n != X.shape[0] else None,
            )
            if self.center:
                W, fmean, ymean, _ = streaming.streaming_bcd_fit_centered(
                    X, Y, **kw
                )
            else:
                W, _, _ = streaming.streaming_bcd_fit(X, Y, **kw)
        return StreamingFeaturizedLinearModel(
            self.featurize, W, self.tile_rows, fmean=fmean, ymean=ymean,
        )


def cosine_bank_featurize(Wrf_flat, brf_flat, feat_dtype=jnp.float32):
    """Featurize closure over a flat cosine random-feature bank, using the
    fused Pallas kernel when safely dispatchable (same recipe as the bench
    headline)."""
    from keystone_tpu.ops import pallas_ops

    Wrf_flat = jnp.asarray(Wrf_flat)
    brf_flat = jnp.asarray(brf_flat)
    use_pallas = pallas_ops.pallas_direct_ok(Wrf_flat)

    def featurize(X_t):
        if use_pallas:
            return pallas_ops.cosine_features(
                X_t, Wrf_flat, brf_flat,
                compute_dtype=feat_dtype, out_dtype=feat_dtype,
            )
        return jnp.cos(
            X_t.astype(jnp.float32) @ Wrf_flat.T + brf_flat
        ).astype(feat_dtype)

    return featurize
