"""Estimator API over the out-of-core streaming least-squares tier.

``StreamingFeaturizedLeastSquares`` is the pipeline-facing form of
``parallel.streaming``: the featurizer lives INSIDE the estimator, so the
fit generates features per row tile and folds them into the (d, d) normal
equations — the feature matrix never materializes (72 GB at the real
TIMIT geometry vs 16 GB of HBM). The fitted model applies the same
featurizer tile-wise. This is the user-facing handle on the BENCH_r04
headline path and on the reference's streaming-by-construction substrate
(CsvDataLoader.scala:10-31 lazy rows; per-partition Gramian accumulation,
BlockWeightedLeastSquares.scala:177-313).

Default semantics match ``BlockLeastSquaresEstimator``
(BlockLinearMapper.scala:224-243): features and labels are mean-centered
(the column sums accumulate in the same tile pass as the Gramian — a
rank-1 correction, not a second data pass) and the model carries the
intercept. ``center=False`` gives the raw-BCD semantics of
``linalg.bcd_least_squares`` instead.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel import streaming
from keystone_tpu.workflow import LabelEstimator, Transformer


class StreamingFeaturizedLinearModel(Transformer):
    """Apply featurize + block weights tile-wise (features never resident).

    A centered fit supplies (fmean, ymean); predictions are then
    (F − fmean) @ W + ymean, which folds into the single affine offset
    ymean − fmean @ W_flat — BlockLinearMapper's model shape without a
    second pass over the features.

    ``d_in`` (when known) makes the model tolerant of graph position:
    fed RAW rows (width d_in) it featurizes tile-wise; fed
    ALREADY-FEATURIZED rows (width d_feat — e.g. a saved-state reuse in a
    later pipeline whose featurize nodes are intact) it applies the
    weights directly. The fused optimizer rewrite needs this because the
    same fitted transformer serves both rewired (raw-input) and original
    (featurized-input) apply sites.
    """

    def __init__(self, featurize, W_stack, tile_rows: int,
                 fmean=None, ymean=None, d_in: Optional[int] = None):
        self.featurize = featurize
        self.W_stack = jnp.asarray(W_stack)
        self.tile_rows = tile_rows
        self.d_in = d_in
        self.fmean = None if fmean is None else jnp.asarray(fmean)
        self.ymean = None if ymean is None else jnp.asarray(ymean)
        Wf = self.W_stack.reshape(-1, self.W_stack.shape[2])
        self.offset = (
            None if self.ymean is None
            else self.ymean - self.fmean.astype(jnp.float32) @ Wf
        )

    @property
    def d_feat(self) -> int:
        return self.W_stack.shape[0] * self.W_stack.shape[1]

    def _featurize_for(self, width: int):
        if self.d_in is None or width == self.d_in:
            return self.featurize
        if width == self.d_feat:
            return _identity_featurize
        raise ValueError(
            f"input width {width} matches neither raw d_in={self.d_in} "
            f"nor d_feat={self.d_feat}"
        )

    def apply(self, x):
        x = jnp.asarray(x)
        F = self._featurize_for(x.shape[-1])(x[None, :])
        Wf = self.W_stack.reshape(-1, self.W_stack.shape[2])
        out = (F.astype(jnp.float32) @ Wf)[0]
        return out if self.offset is None else out + self.offset

    def batch_apply(self, data: Dataset) -> Dataset:
        X = jnp.asarray(data.array)
        preds = streaming.streaming_predict(
            X, self.W_stack, self._featurize_for(X.shape[-1]),
            self.tile_rows,
        )
        if self.offset is not None:
            preds = preds + self.offset
        return Dataset(preds, n=data.n, mesh=data.mesh)._rezero_padding()


class StreamingFeaturizedLeastSquares(LabelEstimator):
    """Featurize-inside-the-fit block least squares (the streaming tier).

    ``featurize``: traceable ``(rows, d_in) -> (rows, d_feat)`` array
    function (e.g. a cosine random-feature bank). The fit is ONE compiled
    program per device (tile scan -> Gramian fold -> BCD epochs on the
    normal equations); sharded input runs the mesh form (per-device folds
    + one psum). ``tile_rows=None`` sizes tiles to a ~2 GB feature slab.
    """

    def __init__(
        self,
        featurize: Callable,
        d_feat: int,
        block_size: int,
        num_iter: int = 1,
        lam: float = 0.0,
        tile_rows: Optional[int] = None,
        feat_itemsize: int = 4,
        center: bool = True,
    ):
        self.featurize = featurize
        self.d_feat = d_feat
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.tile_rows = tile_rows or streaming.pick_tile_rows(
            d_feat, feat_itemsize
        )
        self.center = center

    @property
    def weight(self) -> int:
        return self.num_iter + 1

    def device_fit_fn(self):
        """Fit-fusion contract (workflow/fusion.py): upstream transform +
        the internal tile-scanned featurize/Gramian/BCD program compile as
        ONE dispatch. F here is the estimator's INPUT (the upstream
        program's output, typically narrow raw-ish rows) — the internal
        cosine features still materialize only one tile slab at a time.
        A BankFeaturize featurizer rides as TRACED DeviceFit operands so
        its arrays never embed as HLO constants."""
        from keystone_tpu.parallel.streaming import BankFeaturize, _fit_core
        from keystone_tpu.workflow.fusion import DeviceFit

        bank = self.featurize if isinstance(self.featurize, BankFeaturize) else None

        def fit_fn(F, Y, n_true: int, lam, *bank_params):
            if bank is not None:
                bank_type, bank_key = type(bank), bank.static_key()
                featurize = lambda X_t: bank_type.apply_bank(  # noqa: E731
                    bank_key, bank_params, X_t
                )
            else:
                featurize = self.featurize
            tile = min(self.tile_rows, F.shape[0])
            W, _, _, fmean, ymean = _fit_core(
                F, Y, featurize, self.d_feat, tile, self.block_size,
                lam, self.num_iter, False,
                n_true if n_true != F.shape[0] else None, None,
                self.center,
            )
            return W, fmean, ymean

        def build(params):
            W, fmean, ymean = params
            return StreamingFeaturizedLinearModel(
                self.featurize, W, self.tile_rows, fmean=fmean, ymean=ymean,
            )

        lam_op = jnp.asarray(self.lam, jnp.float32)
        if bank is not None:
            # Logical program identity: λ-sweeps over same-shape banks
            # share one fused executable (bank values ride as operands).
            program_key = (
                "StreamingFLS", self.d_feat, self.block_size,
                self.num_iter, self.tile_rows, self.center,
                type(bank).__name__, bank.static_key(),
            )
            return DeviceFit(
                fit_fn, build, operands=(lam_op,) + tuple(bank.params),
                program_key=program_key,
            )
        # Generic featurize closures have no shareable identity: keep the
        # per-instance program cache (λ still traced).
        return DeviceFit(fit_fn, build, operands=(lam_op,))

    def fit(self, data: Dataset, labels: Dataset) -> StreamingFeaturizedLinearModel:
        X = jnp.asarray(data.array)
        Y = jnp.asarray(labels.array)
        multi = data.mesh is not None and any(
            s > 1 for s in dict(data.mesh.shape).values()
        )
        fmean = ymean = None
        if multi:
            kw = dict(
                featurize=self.featurize, d_feat=self.d_feat,
                tile_rows=min(self.tile_rows, max(X.shape[0] // mesh_lib.axis_size(
                    data.mesh, mesh_lib.DATA_AXIS), 1)),
                block_size=self.block_size, lam=self.lam,
                num_iter=self.num_iter, mesh=data.mesh, n_true=data.n,
            )
            if self.center:
                W, fmean, ymean = streaming.streaming_bcd_fit_mesh_centered(
                    X, Y, **kw
                )
            else:
                W = streaming.streaming_bcd_fit_mesh(X, Y, **kw)
        else:
            kw = dict(
                featurize=self.featurize, d_feat=self.d_feat,
                tile_rows=min(self.tile_rows, X.shape[0]),
                block_size=self.block_size, lam=self.lam,
                num_iter=self.num_iter,
                valid=int(data.n) if data.n != X.shape[0] else None,
            )
            if self.center:
                W, fmean, ymean, _ = streaming.streaming_bcd_fit_centered(
                    X, Y, **kw
                )
            else:
                W, _, _ = streaming.streaming_bcd_fit(X, Y, **kw)
        return StreamingFeaturizedLinearModel(
            self.featurize, W, self.tile_rows, fmean=fmean, ymean=ymean,
        )


class CosineBankFeaturize(streaming.BankFeaturize):
    """Cosine random-feature bank as a :class:`BankFeaturize`: the bank
    arrays ride as jit operands, so every streamed fit over any bank of
    the same SHAPE shares one compiled program (λ-sweeps and pipeline
    re-optimizations never recompile the tile scan), and a TIMIT-scale
    bank never embeds as an HLO constant. Uses the fused Pallas cosine
    kernel when safely dispatchable (same recipe as the bench headline).
    """

    def __init__(self, Wrf_flat, brf_flat, feat_dtype=jnp.float32):
        from keystone_tpu.ops import pallas_ops

        self.Wrf = jnp.asarray(Wrf_flat)
        self.brf = jnp.asarray(brf_flat)
        self.feat_dtype = jnp.dtype(feat_dtype)
        self.use_pallas = bool(pallas_ops.pallas_direct_ok(self.Wrf))

    @property
    def params(self):
        return (self.Wrf, self.brf)

    def static_key(self) -> tuple:
        return (str(self.feat_dtype), self.use_pallas)

    @classmethod
    def apply_bank(cls, static_key, params, X_t):
        from keystone_tpu.ops import pallas_ops

        feat_dtype, use_pallas = jnp.dtype(static_key[0]), static_key[1]
        Wrf, brf = params
        if use_pallas:
            return pallas_ops.cosine_features(
                X_t, Wrf, brf,
                compute_dtype=feat_dtype, out_dtype=feat_dtype,
            )
        return jnp.cos(
            X_t.astype(jnp.float32) @ Wrf.T + brf
        ).astype(feat_dtype)


def cosine_bank_featurize(Wrf_flat, brf_flat, feat_dtype=jnp.float32):
    """Build a :class:`CosineBankFeaturize` (kept as the public factory)."""
    return CosineBankFeaturize(Wrf_flat, brf_flat, feat_dtype)


def _identity_featurize(X_t):
    """Module-level identity featurize: stable jit identity for the
    already-featurized (resident) fallback of the streaming choice."""
    return X_t


def _source_d_in(src) -> int:
    """Row width of a shard source's DATA field (view or paired form) —
    cheap metadata, no segment load or pairing construction. Raises the
    same TypeError as ``_paired_source`` for non-dense sources (e.g. a
    COOShardSource), so the deliberate guard is what callers hit."""
    width = getattr(src, "width", None)
    if width is None:
        width = getattr(src, "d_in", None)
    if width is None:
        raise TypeError(
            f"cannot stream a dense fit from shard source "
            f"{type(src).__name__}"
        )
    return int(width)


def _paired_source(data: Dataset, labels: Dataset):
    """Assemble the (X_seg, Y_seg, valid_rows) segment source a
    shard-backed fit folds over. The common spill-path case — data and
    labels are views over ONE set of disk shards — costs zero extra
    reads; resident labels (they usually fit host RAM even when rows
    don't) are sliced per segment."""
    from keystone_tpu.data.prefetch import (
        DenseShardSource,
        DenseShardView,
        PairedDenseSource,
        ResidentDenseSource,
    )

    def _same_provider(a, b):
        """Same segment provider: identical object, or disk-shard sources
        over the same directory (distinct DiskDenseShards handles on one
        shard set are equivalent)."""
        if a is b:
            return True
        sa, sb = getattr(a, "shards", None), getattr(b, "shards", None)
        if sa is None or sb is None:
            return False
        if sa is sb:
            return True
        da, db = getattr(sa, "directory", None), getattr(sb, "directory", None)
        return da is not None and da == db

    src = data.shard_source
    if isinstance(src, DenseShardView):
        if (
            labels is not None
            and labels.is_shard_backed
            and isinstance(labels.shard_source, DenseShardView)
            and labels.shard_source.field == "y"
            and _same_provider(labels.shard_source.paired, src.paired)
        ):
            # Field check rides in PairedDenseSource too: a swapped
            # (data, labels) pair must raise, never silently fit the
            # shards' stored labels against themselves.
            return PairedDenseSource(src)
        if (
            labels is not None
            and labels.is_shard_backed
            and isinstance(labels.shard_source, DenseShardView)
            and labels.shard_source.field == "x"
        ):
            raise ValueError(
                "labels is a rows ('x') shard view — pass the labels "
                "('y') view (a duplicated/swapped pair would silently "
                "fit rows against rows)"
            )
        if labels is None:
            raise ValueError("shard-backed fit needs labels")
        return PairedDenseSource(src, np.asarray(labels.array)[: labels.n])
    if isinstance(src, (DenseShardSource, PairedDenseSource,
                        ResidentDenseSource)):
        # The source already delivers (X_seg, Y_seg, valid) triples with
        # its own embedded labels. Silently fitting against those while
        # the caller passed DIFFERENT labels would train the wrong model
        # with no error — accept only labels that view the same source.
        if labels is not None:
            lsrc = (
                labels.shard_source if labels.is_shard_backed else None
            )
            lbase = (
                lsrc.paired if isinstance(lsrc, DenseShardView) else lsrc
            )
            base = getattr(src, "paired", src)
            same = lsrc is src or (
                lbase is not None and _same_provider(lbase, base)
            )
            if not same:
                raise ValueError(
                    "data's shard source embeds its own labels; pass the "
                    "matching labels view of the same shards (unrelated "
                    "labels would be silently ignored)"
                )
        return src
    raise TypeError(
        f"cannot stream a fit from shard source {type(src).__name__}"
    )


def _fit_paired_source(source, featurize, d_feat: int, block_size: int,
                       lam, num_iter: int, center: bool,
                       prefetch_depth: int = 2, checkpoint=None,
                       ) -> "StreamingFeaturizedLinearModel":
    """Shared disk-tier fit body: prefetched segment folds -> centered
    BCD on the normal equations -> the same affine model every streaming
    tier returns (existing streaming parity tolerances apply).
    ``checkpoint`` (a CheckpointSpec / directory; None consults
    ``KEYSTONE_CHECKPOINT_DIR``) makes the fold resumable — a killed fit
    re-run with the same spec continues from its last snapshot,
    bit-identically (docs/reliability.md)."""
    W, fmean, ymean, _ = streaming.streaming_bcd_fit_segments(
        source, bank=streaming.as_bank(featurize), d_feat=d_feat,
        block_size=block_size, lam=lam, num_iter=num_iter, center=center,
        prefetch_depth=prefetch_depth, checkpoint=checkpoint,
    )
    return StreamingFeaturizedLinearModel(
        featurize, W, streaming.pick_tile_rows(d_feat, 4),
        fmean=fmean, ymean=ymean,
    )


def pick_block_size(d_feat: int, hint: int) -> int:
    """Largest divisor of d_feat that is <= hint (BCD needs d % bs == 0)."""
    for b in range(min(hint, d_feat), 0, -1):
        if d_feat % b == 0:
            return b
    return 1


class ComposedDeviceFeaturize:
    """Composition of device-fusable transformers as a featurize callable.

    Holds the member transformers (picklable — the save contract) and
    rebuilds the composed function on unpickle; one instance per fused
    estimator, so the closure-path jit cache keys stay stable across
    fits.
    """

    def __init__(self, members):
        self.members = list(members)
        self._build()

    def _build(self):
        fns = [m.device_fn() for m in self.members]

        def composed(X_t):
            for f in fns:
                X_t = f(X_t)
            return X_t

        self._fn = composed

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_fn", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._build()

    def __call__(self, X_t):
        return self._fn(X_t)


def _extract_bank(members) -> Optional[CosineBankFeaturize]:
    """Recognize the cosine-featurizer shapes the optimizer produces and
    turn them into a :class:`CosineBankFeaturize` (bank-as-operand program
    keys; the TIMIT composition — gather of CosineRandomFeatures branches
    + VectorCombiner — is exactly this after GatherFusionRule)."""
    from keystone_tpu.ops.stats import CosineRandomFeaturesModel
    from keystone_tpu.ops.util import VectorCombiner
    from keystone_tpu.workflow.fusion import FusedGatherTransformer

    if len(members) != 1:
        return None
    m = members[0]
    if isinstance(m, CosineRandomFeaturesModel):
        return CosineBankFeaturize(m.W, m.b)
    if isinstance(m, FusedGatherTransformer):
        if not isinstance(m.combiner, VectorCombiner):
            return None
        rfs = []
        for br in m.branches:
            if len(br) != 1 or not isinstance(br[0], CosineRandomFeaturesModel):
                return None
            rfs.append(br[0])
        return CosineBankFeaturize(
            jnp.concatenate([rf.W for rf in rfs]),
            jnp.concatenate([rf.b for rf in rfs]),
        )
    return None


class BlockStreamedLeastSquares(LabelEstimator):
    """The north-star tier as a pipeline estimator: per-block featurize →
    psum → solve → residual update (``streaming_block_bcd_mesh``), for
    geometries where even the (d, d) Gramian of the gram-streamed tier
    exceeds device memory (d ≳ 60k on a 16 GB chip). Requires a
    :class:`CosineBankFeaturize` (the residual sweep needs per-block bank
    slices). Centered by default — same BlockLeastSquares semantics as
    the other tiers (means fold into the block steps; NORTHSTAR.md).
    """

    def __init__(
        self,
        bank: CosineBankFeaturize,
        d_feat: int,
        block_size: int,
        num_iter: int = 3,
        lam: float = 0.0,
        center: bool = True,
    ):
        if not isinstance(bank, CosineBankFeaturize):
            raise TypeError(
                "BlockStreamedLeastSquares needs a CosineBankFeaturize "
                "(per-block bank slices drive the residual sweep)"
            )
        if bank.Wrf.shape[0] != d_feat:
            raise ValueError(
                f"bank rows {bank.Wrf.shape[0]} != d_feat {d_feat}"
            )
        self.bank = bank
        self.d_feat = d_feat
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.center = center

    @property
    def label(self) -> str:
        return f"BlockStreamedLeastSquares({self.d_feat},{self.block_size})"

    @property
    def weight(self) -> int:
        return self.num_iter + 1

    def fit(self, data: Dataset, labels: Dataset) -> StreamingFeaturizedLinearModel:
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if data.is_shard_backed:
            # The block tier's residual sweep re-featurizes X every block
            # step — it NEEDS raw rows device-resident, so a ShardSource
            # materializes here (spilled datasets that still fit run;
            # genuinely over-RAM sets belong to the gram/disk tier, which
            # the capacity selector routes there).
            data = data.materialize()
        if labels.is_shard_backed:
            labels = labels.materialize()
        X = jnp.asarray(data.array)
        Y = jnp.asarray(labels.array)
        mesh = data.mesh
        if mesh is None or not any(
            s > 1 for s in dict(mesh.shape).values()
        ):
            # Single-device form: a 1-device mesh (psums are identities).
            mesh = mesh_lib.make_mesh(devices=_jax.devices()[:1])
            X = _jax.device_put(X, NamedSharding(mesh, P(mesh_lib.DATA_AXIS)))
            Y = _jax.device_put(Y, NamedSharding(mesh, P(mesh_lib.DATA_AXIS)))
        n_true = int(data.n) if data.n != X.shape[0] else None
        out = streaming.streaming_block_bcd_mesh(
            X, Y, self.bank.Wrf, self.bank.brf,
            block_size=self.block_size, lam=self.lam,
            num_iter=self.num_iter, mesh=mesh, n_true=n_true,
            center=self.center, feat_dtype=self.bank.feat_dtype,
        )
        if self.center:
            W, fmean, ymean = out
        else:
            W, fmean, ymean = out, None, None
        return StreamingFeaturizedLinearModel(
            self.bank, W,
            streaming.pick_tile_rows(self.d_feat, 4),
            fmean=fmean, ymean=ymean,
        )


class StreamingLeastSquaresChoice(LabelEstimator):
    """The cost model's streaming-tier selection for
    :class:`~keystone_tpu.ops.learning.cost.LeastSquaresEstimator`.

    When the resident solvers' operands exceed device memory, ``optimize``
    returns this choice; the optimizer's StreamedFitFusionRule then binds
    the upstream featurize program INTO the fit (``fuse_with_members``),
    producing the out-of-core tier — featurize per row tile, Gramian
    fold, centered BCD (BlockLeastSquaresEstimator semantics). Fitting it
    DIRECTLY (no fusable upstream) tile-streams the already-resident
    features through the same solver: correct, but without the memory
    win, since the input had to materialize to reach it.

    Cost model: one streamed data pass building the normal equations
    (the Exact solver's n·d·(d+k) flops — LinearMapper.scala:100-115)
    plus ``num_iter`` Gramian-space epochs, at a streaming overhead
    factor, so resident solvers win whenever they fit.
    """

    streamed_fit_fusable = True
    # Streamed fits pay the full normal-equations syrk plus per-tile
    # featurize regeneration; measured on-chip (BENCH_r04) the resident
    # residual-BCD solver is several times faster at the same geometry
    # when its operands fit — bias selection toward resident solvers
    # whenever the analytic models land close.
    _STREAM_OVERHEAD = 2.0

    def __init__(
        self,
        num_iter: int = 3,
        lam: float = 0.0,
        block_size_hint: int = 4096,
        center: bool = True,
    ):
        self.num_iter = num_iter
        self.lam = lam
        self.block_size_hint = block_size_hint
        self.center = center
        # Set by the owning LeastSquaresEstimator before cost evaluation
        # (bytes per RAW input row — the streamed fit keeps raw rows, not
        # features, resident).
        self.raw_row_bytes: Optional[float] = None
        # Density of the raw input (set by the owner): decides how an
        # UNSET raw_row_bytes defaults in resident_bytes. None (no owner)
        # is treated as dense — the conservative direction for a
        # feasibility cut.
        self.input_is_sparse: Optional[bool] = None
        # Feature-slab budget for the tile scan; the owner shrinks it when
        # the device budget is small so the capacity model and the actual
        # fit agree on the working set.
        self.slab_bytes: int = 2 << 30
        # Device-memory budget (set by the owner): decides the TIER —
        # gram-streamed (one data pass, needs an 8d² Gramian+factor
        # stash) vs block-streamed (the north-star program: per-block
        # Gramians only, num_iter featurize passes) for d where 8d²
        # itself exceeds the budget (~60k dims on a 16 GB chip).
        self.budget_bytes: Optional[float] = None
        # DISK-tier knobs (set by the owner when the sampled input is
        # shard-backed): raw rows then stream from disk segments, so the
        # capacity model prices staged buffers instead of n·raw resident,
        # and the fit folds over a prefetched ShardSource.
        self.data_is_shard_backed: bool = False
        self.shard_segment_bytes: Optional[float] = None
        self.prefetch_depth: int = 2
        # Reliability knob: CheckpointSpec (or directory) the disk-tier
        # fold snapshots/resumes through; None defers to the
        # KEYSTONE_CHECKPOINT_DIR env (the run.py --checkpoint-dir
        # wiring), unset = no checkpointing.
        self.checkpoint = None

    @property
    def label(self) -> str:
        return f"StreamingLeastSquaresChoice({self.num_iter},{self.lam})"

    @property
    def weight(self) -> int:
        return self.num_iter + 1

    def _gram_tier_ok(self, d_feat: int) -> bool:
        """The d-only discriminator shared by the capacity model and
        build_estimator: the gram tier needs its (d, d) Gramian + factor
        stash resident."""
        if self.budget_bytes is None:
            return True
        slab = min(
            streaming.pick_tile_rows(d_feat, 4, slab_bytes=self.slab_bytes)
            * d_feat * 4.0,
            float(self.slab_bytes),
        )
        return 8.0 * d_feat * d_feat + slab <= self.budget_bytes

    def _block_tier_bs(self, d_feat: int) -> int:
        """Block size for the block-streamed tier: the hint, shrunk until
        the per-block Gramian/factor stash (8·d·bs bytes) fits a quarter
        of the budget."""
        hint = self.block_size_hint
        if self.budget_bytes is not None:
            cap = max(int(self.budget_bytes / (32.0 * d_feat)), 1)
            hint = min(hint, cap)
        return pick_block_size(d_feat, hint)

    def build_estimator(self, featurize, d_feat: int):
        from keystone_tpu import obs

        gram_ok = self._gram_tier_ok(d_feat)

        def emit(winner: str, reason: str) -> None:
            # The streaming tier's own cost-model decision, audited like
            # the solver selection (obs plane, ISSUE 9).
            obs.record_cost_decision(obs.CostDecision(
                decision="streaming_tier",
                winner=winner,
                candidates=[
                    {"label": "gram", "feasible": gram_ok},
                    {"label": "block",
                     "feasible": isinstance(
                         featurize, CosineBankFeaturize)},
                ],
                reason=reason,
                context={
                    "d_feat": int(d_feat),
                    "budget_bytes": self.budget_bytes,
                    "featurize": type(featurize).__name__,
                },
            ))

        if gram_ok:
            emit("gram", "gramian_fits_budget")
            bs = pick_block_size(d_feat, self.block_size_hint)
            return StreamingFeaturizedLeastSquares(
                featurize, d_feat=d_feat, block_size=bs,
                num_iter=self.num_iter, lam=self.lam, center=self.center,
                tile_rows=streaming.pick_tile_rows(
                    d_feat, 4, slab_bytes=self.slab_bytes
                ),
            )
        if not isinstance(featurize, CosineBankFeaturize):
            # The capacity model assumed the block tier (no d² term), but
            # only bank featurizers can drive per-block slices. Best
            # effort: run the gram tier anyway (it may exceed the budget)
            # rather than crash a fit the selector already committed to.
            import logging

            logging.getLogger("keystone_tpu.streaming").warning(
                "d_feat=%d: (d, d) Gramian exceeds the device budget and "
                "the block-streamed tier needs a cosine bank featurizer "
                "(got %s); falling back to the gram tier — the fit may "
                "not fit device memory", d_feat, type(featurize).__name__,
            )
            emit("gram", "block_needs_bank_featurizer")
            return StreamingFeaturizedLeastSquares(
                featurize, d_feat=d_feat,
                block_size=pick_block_size(d_feat, self.block_size_hint),
                num_iter=self.num_iter, lam=self.lam, center=self.center,
                tile_rows=streaming.pick_tile_rows(
                    d_feat, 4, slab_bytes=self.slab_bytes
                ),
            )
        emit("block", "gramian_exceeds_budget")
        return BlockStreamedLeastSquares(
            featurize, d_feat=d_feat, block_size=self._block_tier_bs(d_feat),
            num_iter=self.num_iter, lam=self.lam, center=self.center,
        )

    def fuse_with_members(self, members) -> "StreamedFitEstimator":
        fused = StreamedFitEstimator(members, self)
        # A pending cost-decision back-annotation (cost.py optimize)
        # follows the fit wherever it actually runs: the fused streamed
        # estimator replaces this choice in the graph, so the executor
        # stamps the measured wall through IT, not through the choice.
        ref = getattr(self, "_pending_cost_outcome", None)
        if ref is not None:
            fused._pending_cost_outcome = ref
            self._pending_cost_outcome = None
        return fused

    def fit_source(self, data: Dataset, labels: Dataset, featurize,
                   d_feat: int):
        """The DISK tier: fold the normal equations over prefetched
        shard segments (featurize applied per tile inside the fold), so
        neither host RAM nor HBM ever holds the raw rows — the
        capacity-selected path for datasets past the host budget."""
        return _fit_paired_source(
            _paired_source(data, labels), featurize, d_feat,
            block_size=pick_block_size(d_feat, self.block_size_hint),
            lam=self.lam, num_iter=self.num_iter, center=self.center,
            prefetch_depth=self.prefetch_depth,
            checkpoint=getattr(self, "checkpoint", None),
        )

    def fit(self, data: Dataset, labels: Dataset):
        from keystone_tpu.ops.sparse import Densify, is_sparse_dataset

        if data.is_shard_backed:
            return self.fit_source(
                data, labels, _identity_featurize,
                _source_d_in(data.shard_source),
            )
        if is_sparse_dataset(data):
            data = Densify().batch_apply(data)
        d_feat = int(jnp.asarray(data.array).shape[-1])
        return self.build_estimator(_identity_featurize, d_feat).fit(
            data, labels
        )

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
        network_weight,
    ) -> float:
        flops = (n * d * (d + k) + self.num_iter * d * d * k) / num_machines
        bytes_scanned = n * d / num_machines + 2.0 * d * d
        network = d * (d + k)  # the single (G, FY) psum
        return (
            self._STREAM_OVERHEAD
            * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Capacity model of whichever TIER ``build_estimator`` would pick
        at this d (the shared ``_gram_tier_ok`` discriminator keeps the
        two consistent). Gram tier: raw rows + labels (sharded) + the
        (d, d) Gramian/factor stash + one feature slab. Block tier (the
        north-star program): raw rows + labels + residual + per-BLOCK
        Gramian/factor stash + one block slab + the bank — no d² term."""
        raw = self.raw_row_bytes
        if self.input_is_sparse:
            # Resident SPARSE input: fit() densifies before the tile scan
            # (the streamed fold featurizes dense row tiles), so the
            # resident operand is the DENSIFIED matrix — 4d bytes/row —
            # whatever the COO row width was. Pricing the COO width here
            # let this tier look feasible at geometries where its own
            # densify would OOM (found by the round-6 replay test when
            # the TPU weights made it cost-competitive with the sparse
            # gram engine).
            raw = 4.0 * d
        elif not raw:
            # Unknown raw width, dense input: the raw operand IS the full
            # f32 row — 4d bytes (the old min(d, 512) cap underestimated
            # wide-dense rows ~32x at d=16384, letting this tier look
            # feasible when the raw operand alone exceeds HBM).
            raw = 4.0 * d
        bs = min(self.block_size_hint, d)
        slab = min(
            streaming.pick_tile_rows(d, 4, slab_bytes=self.slab_bytes)
            * d * 4.0,
            float(self.slab_bytes),
        )
        if self.data_is_shard_backed:
            # Disk tier: raw rows + labels live in the shard files and
            # stream through (prefetch_depth + 1) staged segment buffers,
            # so no term scales with n. The fit ALWAYS runs the gram fold
            # here (fit_source — the block tier needs resident raw rows),
            # so price the gram-tier stash unconditionally: if its 8d²
            # Gramian busts the device budget, the disk tier honestly
            # reports infeasible rather than OOMing mid-fold.
            seg = self.shard_segment_bytes or (8192.0 * (raw + 4.0 * k))
            return (
                (self.prefetch_depth + 1) * seg
                + 8.0 * d * d
                + 8.0 * d * bs
                + slab
            )
        common = n * raw / num_machines + 4.0 * n * k / num_machines
        if self._gram_tier_ok(d):
            return (
                common
                + 8.0 * d * d      # G + diagonal-block Cholesky stash
                + 8.0 * d * bs     # diag/chol block stacks in the solve
                + slab
            )
        bs_b = self._block_tier_bs(d)
        return (
            common
            + 4.0 * n * k / num_machines  # residual R alongside Y
            + 8.0 * d * bs_b              # per-block Gramian + factor stash
            + 4.0 * (n / num_machines) * bs_b  # one block slab
            + d * raw                     # bank rows ~ raw row width
        )


class StreamedFitEstimator(LabelEstimator):
    """A capacity-selected streaming fit bound to its upstream featurize
    program (the rewrite StreamedFitFusionRule performs).

    The members' composed ``device_fn`` becomes the tile featurizer of a
    :class:`StreamingFeaturizedLeastSquares` — featurize + Gramian fold +
    centered BCD compile as one scanned program and the feature matrix
    never materializes (the cost-model-driven form of the ``--streaming``
    flag this replaces; reference analog: LeastSquaresEstimator.scala:
    59-84 picking BlockLeastSquares, whose per-partition featurize+solve
    never materializes the global matrix either). Cosine featurizer
    shapes lower to the bank-as-operand program (stable compile keys).
    """

    def __init__(self, members, choice: StreamingLeastSquaresChoice):
        self.members = list(members)
        self.choice = choice
        self._featurize = _extract_bank(self.members) or ComposedDeviceFeaturize(
            self.members
        )

    @property
    def can_serve_raw_input(self) -> bool:
        """True when the fitted model can PROVABLY disambiguate raw vs
        featurized input by width — the gate StreamedFitFusionRule checks
        before rewiring apply sites to feed raw rows. Requires a bank
        featurizer (widths known statically) with d_in != d_feat."""
        Wrf = getattr(self._featurize, "Wrf", None)
        return Wrf is not None and Wrf.shape[0] != Wrf.shape[1]

    @property
    def label(self) -> str:
        inner = " > ".join(m.label for m in self.members)
        return f"StreamedFit[{inner} -> {self.choice.label}]"

    @property
    def weight(self) -> int:
        return self.choice.weight

    def _fallback(self, data: Dataset, labels: Dataset):
        raw_width = self._raw_width(data)
        for m in self.members:
            data = m.batch_apply(data)
        model = self.choice.fit(data, labels)
        # Apply sites may have been rewired to feed RAW rows (the rule
        # rewires only when can_serve_raw_input): make the fallback model
        # width-adaptive too, or those sites would crash on a raw batch.
        if (
            self.can_serve_raw_input
            and isinstance(model, StreamingFeaturizedLinearModel)
            and raw_width is not None
        ):
            model.featurize = self._featurize
            model.d_in = raw_width
        return model

    @staticmethod
    def _raw_width(data: Dataset):
        try:
            if data.is_host:
                items = data.to_list()
                return int(np.asarray(items[0]).shape[-1]) if items else None
            return int(jnp.asarray(data.array).shape[-1])
        except Exception:
            return None

    def fit(self, data: Dataset, labels: Dataset):
        if data.is_host or labels.is_host:
            return self._fallback(data, labels)
        if data.is_shard_backed:
            return self._fit_shard_backed(data, labels)
        X = jnp.asarray(data.array)
        d_feat = int(
            jax.eval_shape(
                self._featurize,
                jax.ShapeDtypeStruct((1,) + X.shape[1:], X.dtype),
            ).shape[-1]
        )
        d_in = int(X.shape[-1])
        est = self.choice.build_estimator(self._featurize, d_feat)
        model = est.fit(data, labels)
        if d_in == d_feat:
            # Width cannot disambiguate raw vs featurized input. The rule
            # never rewires apply sites in this case (can_serve_raw_input
            # is False), so every apply site featurizes upstream: the
            # model must always take the identity path.
            model.featurize = _identity_featurize
            model.d_in = None
        else:
            # d_in makes the model adaptive: rewired apply sites feed raw
            # rows (featurize-inside, tile-wise); saved-state reuse in
            # later pipelines with intact featurize nodes feeds
            # featurized rows.
            model.d_in = d_in
        return model

    def _fit_shard_backed(self, data: Dataset, labels: Dataset):
        """The out-of-core pipeline fit: raw rows stream from disk shards
        through the prefetcher, the bound featurize program runs per tile
        inside the fold, and the feature matrix never materializes at ANY
        tier — disk, host, or HBM."""
        d_in = _source_d_in(data.shard_source)
        d_feat = int(
            jax.eval_shape(
                self._featurize,
                jax.ShapeDtypeStruct((1, d_in), jnp.float32),
            ).shape[-1]
        )
        model = self.choice.fit_source(data, labels, self._featurize, d_feat)
        if d_in == d_feat:
            model.featurize = _identity_featurize
            model.d_in = None
        else:
            model.d_in = d_in
        return model
